"""Record serialization for the host record path.

The reference delegates serialization to Spark's SerializerInstance
inside the wrapped sort-shuffle writers (RdmaWrapperShuffleWriter.scala:85-101)
and wraps fetched streams for decompression on read
(RdmaShuffleReader.scala:51-58).  Here serializers are pluggable; the
default pickles record batches with a small length-prefixed framing so
partitions can be concatenated and sliced bytewise.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterable, Iterator, List, Tuple

import numpy as np

Record = Tuple[Any, Any]

_LEN = struct.Struct("<I")


def as_view(data) -> memoryview:
    """Normalize any bytes-like block payload (``bytes``, ``bytearray``,
    ``memoryview``, contiguous uint8 ndarray) to a flat memoryview
    WITHOUT copying — the zero-copy exchange hands deserializers views
    of its destination rows, and every frame walker below slices this
    one view instead of materializing ``bytes``."""
    if isinstance(data, memoryview):
        return data.cast("B") if data.format != "B" else data
    return memoryview(data)


class Serializer:
    # True when the serializer offers ``deserialize_columns`` (the
    # columnar fast path); readers route on this flag.  ``data``
    # arguments throughout are bytes-like: deserializers must accept
    # memoryview/uint8-ndarray slices of an exchange destination row,
    # not just materialized ``bytes``.
    supports_columns = False

    def serialize(self, records: Iterable[Record]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def deserialize(self, data) -> Iterator[Record]:  # pragma: no cover
        raise NotImplementedError


class PickleSerializer(Serializer):
    """Batched pickle with 4-byte batch length prefixes."""

    def __init__(self, batch_size: int = 4096):
        self.batch_size = batch_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                out += _LEN.pack(len(raw))
                out += raw
                batch = []
        if batch:
            raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
            out += _LEN.pack(len(raw))
            out += raw
        return bytes(out)

    def deserialize(self, data) -> Iterator[Record]:
        view = as_view(data)
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off}, have {len(view) - off}B"
                )
            for rec in pickle.loads(view[off : off + n]):
                yield rec
            off += n


class ColumnarSerializer(Serializer):
    """Raw-column frames for fixed-width records — the unsafe-row analog
    (the reference wraps Spark's ``UnsafeShuffleWriter`` precisely to
    keep record bytes off slow object paths,
    RdmaWrapperShuffleWriter.scala:85-101).

    Frame layout (concatenation-safe like every serializer here):

        1B magic (0xC2) | 1B flags (bit 0: key-sorted) |
        1B key-dtype len | key dtype str |
        1B val-dtype len | val dtype str | 4B record count |
        raw key column | raw val column

    ``serialize`` accepts a :class:`ColumnBatch`, an iterable of
    batches, or a plain iterable of (k, v) tuples (packed into one
    batch, dtypes inferred).  Records that cannot pack into fixed-width
    columns (ragged lists from a tuple-plane group combine, arbitrary
    objects) fall back to a PICKLE frame (magic 0xC3) so a
    manager-global columnar serializer never breaks the tuple plane;
    ``deserialize`` yields (k, v) tuples for generic-plane interop;
    ``deserialize_columns`` is the fast path, yielding zero-copy
    :class:`ColumnBatch` views over the input buffer (a pickle frame
    there is re-packed, or raises if unpackable)."""

    MAGIC = 0xC2
    MAGIC_PICKLE = 0xC3
    supports_columns = True

    def serialize(self, records) -> bytes:
        from sparkrdma_tpu.utils.columns import ColumnBatch

        if isinstance(records, ColumnBatch):
            batches = [records]
        else:
            records = list(records) if not isinstance(records, list) else records
            if records and all(isinstance(b, ColumnBatch) for b in records):
                batches = records
            elif records:
                try:
                    batches = [ColumnBatch.from_records(records)]
                except (TypeError, ValueError):
                    # not fixed-width packable (ragged combiners,
                    # arbitrary objects): pickle frame
                    raw = pickle.dumps(
                        records, protocol=pickle.HIGHEST_PROTOCOL
                    )
                    return (
                        bytes([self.MAGIC_PICKLE]) + _LEN.pack(len(raw)) + raw
                    )
            else:
                batches = []
        out = bytearray()
        for b in batches:
            if len(b) == 0:
                continue
            header, kv, vv = self._frame_parts(b)
            out += header
            out += kv.data  # memoryview: bytearray += ndarray would
            out += vv.data  # dispatch to numpy broadcasting instead
        return bytes(out)

    def frame_header(self, key_dtype, val_dtype, count: int,
                     key_sorted: bool) -> bytes:
        """One frame's header bytes — exposed so the writer's
        direct-assembly commit can lay frames out in its own buffer and
        gather columns straight into place (zero intermediate copies)."""
        kd = np.dtype(key_dtype).str.encode("ascii")
        vd = np.dtype(val_dtype).str.encode("ascii")
        if len(kd) > 255 or len(vd) > 255:
            raise ValueError("dtype string too long to frame")
        flags = 1 if key_sorted else 0
        return (
            bytes([self.MAGIC, flags, len(kd)]) + kd + bytes([len(vd)]) + vd
            + _LEN.pack(count)
        )

    def _frame_parts(self, b) -> Tuple[bytes, np.ndarray, np.ndarray]:
        """(header, key bytes view, val bytes view) for one batch —
        the views are uint8 reinterpretations, NOT copies."""
        header = self.frame_header(
            b.keys.dtype, b.vals.dtype, len(b), b.key_sorted
        )
        return (
            header,
            np.ascontiguousarray(b.keys).view(np.uint8),
            np.ascontiguousarray(b.vals).view(np.uint8),
        )

    def serialize_chunks(self, records):
        """Zero-copy serialize: returns ``(total_length, chunks_fn)``
        where the chunks are small headers plus uint8 views over the
        column buffers — the commit path copies each byte ONCE, straight
        into its staging buffer (``ChunkedPayload`` contract,
        resolver.commit_map_output)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        batches = (
            [records] if isinstance(records, ColumnBatch)
            else [b for b in records]
        )
        parts = []
        total = 0
        for b in batches:
            if len(b) == 0:
                continue
            header, kv, vv = self._frame_parts(b)
            parts.append((header, kv, vv))
            total += len(header) + kv.shape[0] + vv.shape[0]

        def chunks():
            for header, kv, vv in parts:
                yield header
                yield kv
                yield vv

        return total, chunks

    def deserialize_columns(self, data):
        """Fast path: yields :class:`ColumnBatch` per frame (pickle
        frames are re-packed into columns, or raise if unpackable)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        for item in self._iter_items(data):
            if isinstance(item, ColumnBatch):
                yield item
            else:
                try:
                    yield ColumnBatch.from_records(item)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        "stream holds records that cannot pack into "
                        "columns; read through deserialize() or use the "
                        "pickle serializer"
                    ) from e

    def _iter_items(self, data):
        """Walk frames: yields a ColumnBatch per columnar frame, a raw
        record list per pickle-fallback frame.  ``data`` may be any
        bytes-like; column arrays come out as zero-copy views over it
        (keep the backing row alive while the batches are)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        view = as_view(data)
        off = 0
        total = len(view)
        while off < total:
            if view[off] == self.MAGIC_PICKLE:
                (n,) = _LEN.unpack_from(view, off + 1)
                off += 1 + _LEN.size
                yield pickle.loads(view[off : off + n])
                off += n
                continue
            if view[off] != self.MAGIC:
                raise ValueError(
                    f"bad columnar frame magic {view[off]:#x} at {off} "
                    "(mixed-serializer stream?)"
                )
            off += 1
            flags = view[off]
            off += 1
            nk = view[off]
            off += 1
            kd = np.dtype(bytes(view[off : off + nk]).decode("ascii"))
            off += nk
            nv = view[off]
            off += 1
            vd = np.dtype(bytes(view[off : off + nv]).decode("ascii"))
            off += nv
            (count,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            kbytes = count * kd.itemsize
            vbytes = count * vd.itemsize
            if off + kbytes + vbytes > total:
                raise ValueError(
                    f"truncated columnar frame: need {kbytes + vbytes}B "
                    f"at {off}, have {total - off}B"
                )
            keys = np.frombuffer(view, dtype=kd, count=count, offset=off)
            off += kbytes
            vals = np.frombuffer(view, dtype=vd, count=count, offset=off)
            off += vbytes
            yield ColumnBatch(keys, vals, key_sorted=bool(flags & 1))

    def deserialize(self, data) -> Iterator[Record]:
        # ColumnBatch and raw record lists both iterate as (k, v)
        for item in self._iter_items(data):
            yield from item


class CompressedSerializer(Serializer):
    """Compression wrapper over any serializer — the analog of the
    reference's read-side stream wrapping for codec support
    (``wrapStream`` reflection, RdmaShuffleReader.scala:51-58,117-127),
    applied symmetrically on write.  Codecs: ``zlib`` (default) and
    ``lzma``; payloads below ``min_size`` are stored raw (codec tag 0)
    since small-block compression costs more than it saves.

    Framing is ``1B tag + 4B length + body`` per serialize() call, so
    outputs are CONCATENATION-SAFE like the inner serializer's — the
    writer's spill-merge and any block concatenation rely on this
    (plain ``zlib.decompress`` would silently discard trailing frames).

    Wire-format versioning: this framed layout is
    ``WIRE_FORMAT_VERSION`` 2 (v1 was unframed ``1B tag + body``).  Any
    future layout change MUST claim fresh codec tag values so that
    mixed-version data fails fast on the existing "unknown codec tag"
    check instead of decoding garbage — tags 0-2 are forever v2.
    """

    WIRE_FORMAT_VERSION = 2
    _RAW, _ZLIB, _LZMA = 0, 1, 2

    def __init__(self, inner: Serializer = None, codec: str = "zlib",
                 level: int = 1, min_size: int = 256):
        self.inner = inner or PickleSerializer()
        if codec not in ("zlib", "lzma"):
            raise ValueError(f"unknown codec: {codec!r}")
        self.codec = codec
        self.level = level
        self.min_size = min_size
        self.supports_columns = getattr(self.inner, "supports_columns", False)

    # one frame per this many records: bounds frame bodies far below the
    # 4B length field's 4 GiB ceiling for sane record sizes
    frame_records = 65536

    def serialize(self, records: Iterable[Record]) -> bytes:
        from sparkrdma_tpu.utils.columns import ColumnBatch

        if isinstance(records, ColumnBatch):
            # columnar fast path: one frame per batch, no per-record walk
            return self._frame(self.inner.serialize(records))
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            if isinstance(rec, ColumnBatch):
                if batch:
                    out += self._frame(self.inner.serialize(batch))
                    batch = []
                out += self._frame(self.inner.serialize(rec))
                continue
            batch.append(rec)
            if len(batch) >= self.frame_records:
                out += self._frame(self.inner.serialize(batch))
                batch = []
        if batch or not out:
            out += self._frame(self.inner.serialize(batch))
        return bytes(out)

    def _frame(self, raw: bytes) -> bytes:
        if len(raw) < self.min_size:
            tag, body = self._RAW, raw
        elif self.codec == "zlib":
            import zlib

            tag, body = self._ZLIB, zlib.compress(raw, self.level)
        else:
            import lzma

            tag, body = self._LZMA, lzma.compress(raw)
        if len(body) >= 1 << 32:
            raise ValueError(
                f"frame body of {len(body)}B exceeds the 4 GiB framing "
                f"limit ({self.frame_records} records averaging "
                ">64 KiB each) — lower frame_records for huge records"
            )
        return bytes([tag]) + _LEN.pack(len(body)) + body

    def _iter_frames(self, data) -> Iterator[bytes]:
        view = as_view(data)
        off = 0
        while off < len(view):
            if off + 1 + _LEN.size > len(view):
                raise ValueError(f"truncated frame header at offset {off}")
            tag = view[off]
            (n,) = _LEN.unpack_from(view, off + 1)
            off += 1 + _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated frame: need {n}B at {off}, "
                    f"have {len(view) - off}B"
                )
            body = bytes(view[off : off + n])
            off += n
            if tag == self._RAW:
                yield body
            elif tag == self._ZLIB:
                import zlib

                yield zlib.decompress(body)
            elif tag == self._LZMA:
                import lzma

                yield lzma.decompress(body)
            else:
                raise ValueError(f"unknown codec tag {tag}")

    def deserialize(self, data) -> Iterator[Record]:
        for raw in self._iter_frames(data):
            yield from self.inner.deserialize(raw)

    def deserialize_columns(self, data: bytes):
        """Columnar read path through the codec wrapper (only valid when
        ``supports_columns`` — i.e. the inner serializer is columnar)."""
        for raw in self._iter_frames(data):
            if raw:
                yield from self.inner.deserialize_columns(raw)
