"""Record serialization for the host record path.

The reference delegates serialization to Spark's SerializerInstance
inside the wrapped sort-shuffle writers (RdmaWrapperShuffleWriter.scala:85-101)
and wraps fetched streams for decompression on read
(RdmaShuffleReader.scala:51-58).  Here serializers are pluggable; the
default pickles record batches with a small length-prefixed framing so
partitions can be concatenated and sliced bytewise.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterable, Iterator, List, Optional, Tuple

import numpy as np

Record = Tuple[Any, Any]

_LEN = struct.Struct("<I")


class FrameTooLargeError(ValueError):
    """A single compression frame's body exceeds the 4-byte framing
    limit.  Structured: carries the offending sizes so callers (and the
    error message) can say exactly which knob to turn instead of a
    generic 'value too large'."""

    def __init__(self, frame_bytes: int, record_count: int,
                 frame_records: int, limit: int):
        self.frame_bytes = int(frame_bytes)
        self.record_count = int(record_count)
        self.frame_records = int(frame_records)
        self.limit = int(limit)
        per_record = self.frame_bytes // max(self.record_count, 1)
        super().__init__(
            f"compressed frame body of {self.frame_bytes}B exceeds the "
            f"{self.limit}B framing limit: {self.record_count} record(s) "
            f"averaging ~{per_record}B serialized each — lower "
            f"spark.shuffle.tpu.compressFrameRecords (currently "
            f"{self.frame_records}) so one frame holds fewer records"
        )


def as_view(data) -> memoryview:
    """Normalize any bytes-like block payload (``bytes``, ``bytearray``,
    ``memoryview``, contiguous uint8 ndarray) to a flat memoryview
    WITHOUT copying — the zero-copy exchange hands deserializers views
    of its destination rows, and every frame walker below slices this
    one view instead of materializing ``bytes``."""
    if isinstance(data, memoryview):
        return data.cast("B") if data.format != "B" else data
    return memoryview(data)


class Serializer:
    # True when the serializer offers ``deserialize_columns`` (the
    # columnar fast path); readers route on this flag.  ``data``
    # arguments throughout are bytes-like: deserializers must accept
    # memoryview/uint8-ndarray slices of an exchange destination row,
    # not just materialized ``bytes``.
    supports_columns = False

    def serialize(self, records: Iterable[Record]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def deserialize(self, data) -> Iterator[Record]:  # pragma: no cover
        raise NotImplementedError

    def frame_spans(self, data) -> List[Tuple[int, int]]:
        """(start, end) byte spans of this serializer's self-contained
        frames inside ``data`` — the frame-parallel decode entry point
        (shuffle/decode.py): every serializer here frames
        concatenation-safely, so any contiguous GROUP of spans
        deserializes independently via ``deserialize(data[a:b])`` and
        one large block can fan out across decode workers.  Base
        serializers treat the whole payload as one frame."""
        return [(0, len(as_view(data)))]


class PickleSerializer(Serializer):
    """Batched pickle with 4-byte batch length prefixes."""

    def __init__(self, batch_size: int = 4096):
        self.batch_size = batch_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                out += _LEN.pack(len(raw))
                out += raw
                batch = []
        if batch:
            raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
            out += _LEN.pack(len(raw))
            out += raw
        return bytes(out)

    def deserialize(self, data) -> Iterator[Record]:
        view = as_view(data)
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off}, have {len(view) - off}B"
                )
            for rec in pickle.loads(view[off : off + n]):
                yield rec
            off += n

    def frame_spans(self, data) -> List[Tuple[int, int]]:
        """One span per length-prefixed pickle batch.  The walk is one
        native call when ``_staging.so`` is present (interpreter cost
        per BLOCK, not per frame); the Python loop is the fallback and
        the authority for truncation errors."""
        from sparkrdma_tpu.memory.staging import native_frame_spans

        view = as_view(data)
        walked = native_frame_spans(view, 0)
        if walked is not None:
            return list(zip(walked[:, 0].tolist(),
                            walked[:, 1].tolist()))
        spans: List[Tuple[int, int]] = []
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            end = off + _LEN.size + n
            if end > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off + _LEN.size}, "
                    f"have {len(view) - off - _LEN.size}B"
                )
            spans.append((off, end))
            off = end
        return spans


class ColumnarSerializer(Serializer):
    """Raw-column frames for fixed-width records — the unsafe-row analog
    (the reference wraps Spark's ``UnsafeShuffleWriter`` precisely to
    keep record bytes off slow object paths,
    RdmaWrapperShuffleWriter.scala:85-101).

    Frame layout (concatenation-safe like every serializer here):

        1B magic (0xC2) | 1B flags (bit 0: key-sorted) |
        1B key-dtype len | key dtype str |
        1B val-dtype len | val dtype str | 4B record count |
        raw key column | raw val column

    ``serialize`` accepts a :class:`ColumnBatch`, an iterable of
    batches, or a plain iterable of (k, v) tuples (packed into one
    batch, dtypes inferred).  Records that cannot pack into fixed-width
    columns (ragged lists from a tuple-plane group combine, arbitrary
    objects) fall back to a PICKLE frame (magic 0xC3) so a
    manager-global columnar serializer never breaks the tuple plane;
    ``deserialize`` yields (k, v) tuples for generic-plane interop;
    ``deserialize_columns`` is the fast path, yielding zero-copy
    :class:`ColumnBatch` views over the input buffer (a pickle frame
    there is re-packed, or raises if unpackable)."""

    MAGIC = 0xC2
    MAGIC_PICKLE = 0xC3
    supports_columns = True

    def serialize(self, records) -> bytes:
        from sparkrdma_tpu.utils.columns import ColumnBatch

        if isinstance(records, ColumnBatch):
            batches = [records]
        else:
            records = list(records) if not isinstance(records, list) else records
            if records and all(isinstance(b, ColumnBatch) for b in records):
                batches = records
            elif records:
                try:
                    batches = [ColumnBatch.from_records(records)]
                except (TypeError, ValueError):
                    # not fixed-width packable (ragged combiners,
                    # arbitrary objects): pickle frame
                    raw = pickle.dumps(
                        records, protocol=pickle.HIGHEST_PROTOCOL
                    )
                    return (
                        bytes([self.MAGIC_PICKLE]) + _LEN.pack(len(raw)) + raw
                    )
            else:
                batches = []
        out = bytearray()
        for b in batches:
            if len(b) == 0:
                continue
            header, kv, vv = self._frame_parts(b)
            out += header
            out += kv.data  # memoryview: bytearray += ndarray would
            out += vv.data  # dispatch to numpy broadcasting instead
        return bytes(out)

    def frame_header(self, key_dtype, val_dtype, count: int,
                     key_sorted: bool) -> bytes:
        """One frame's header bytes — exposed so the writer's
        direct-assembly commit can lay frames out in its own buffer and
        gather columns straight into place (zero intermediate copies)."""
        kd = np.dtype(key_dtype).str.encode("ascii")
        vd = np.dtype(val_dtype).str.encode("ascii")
        if len(kd) > 255 or len(vd) > 255:
            raise ValueError("dtype string too long to frame")
        flags = 1 if key_sorted else 0
        return (
            bytes([self.MAGIC, flags, len(kd)]) + kd + bytes([len(vd)]) + vd
            + _LEN.pack(count)
        )

    def _frame_parts(self, b) -> Tuple[bytes, np.ndarray, np.ndarray]:
        """(header, key bytes view, val bytes view) for one batch —
        the views are uint8 reinterpretations, NOT copies."""
        header = self.frame_header(
            b.keys.dtype, b.vals.dtype, len(b), b.key_sorted
        )
        return (
            header,
            np.ascontiguousarray(b.keys).view(np.uint8),
            np.ascontiguousarray(b.vals).view(np.uint8),
        )

    def serialize_chunks(self, records):
        """Zero-copy serialize: returns ``(total_length, chunks_fn)``
        where the chunks are small headers plus uint8 views over the
        column buffers — the commit path copies each byte ONCE, straight
        into its staging buffer (``ChunkedPayload`` contract,
        resolver.commit_map_output)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        batches = (
            [records] if isinstance(records, ColumnBatch)
            else [b for b in records]
        )
        parts = []
        total = 0
        for b in batches:
            if len(b) == 0:
                continue
            header, kv, vv = self._frame_parts(b)
            parts.append((header, kv, vv))
            total += len(header) + kv.shape[0] + vv.shape[0]

        def chunks():
            for header, kv, vv in parts:
                yield header
                yield kv
                yield vv

        return total, chunks

    def deserialize_columns(self, data):
        """Fast path: yields :class:`ColumnBatch` per frame (pickle
        frames are re-packed into columns, or raise if unpackable)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        for item in self._iter_items(data):
            if isinstance(item, ColumnBatch):
                yield item
            else:
                try:
                    yield ColumnBatch.from_records(item)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        "stream holds records that cannot pack into "
                        "columns; read through deserialize() or use the "
                        "pickle serializer"
                    ) from e

    def _iter_items(self, data):
        """Walk frames: yields a ColumnBatch per columnar frame, a raw
        record list per pickle-fallback frame.  ``data`` may be any
        bytes-like; column arrays come out as zero-copy views over it
        (keep the backing row alive while the batches are)."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        view = as_view(data)
        off = 0
        total = len(view)
        while off < total:
            if view[off] == self.MAGIC_PICKLE:
                (n,) = _LEN.unpack_from(view, off + 1)
                off += 1 + _LEN.size
                yield pickle.loads(view[off : off + n])
                off += n
                continue
            if view[off] != self.MAGIC:
                raise ValueError(
                    f"bad columnar frame magic {view[off]:#x} at {off} "
                    "(mixed-serializer stream?)"
                )
            off += 1
            flags = view[off]
            off += 1
            nk = view[off]
            off += 1
            kd = np.dtype(bytes(view[off : off + nk]).decode("ascii"))
            off += nk
            nv = view[off]
            off += 1
            vd = np.dtype(bytes(view[off : off + nv]).decode("ascii"))
            off += nv
            (count,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            kbytes = count * kd.itemsize
            vbytes = count * vd.itemsize
            if off + kbytes + vbytes > total:
                raise ValueError(
                    f"truncated columnar frame: need {kbytes + vbytes}B "
                    f"at {off}, have {total - off}B"
                )
            keys = np.frombuffer(view, dtype=kd, count=count, offset=off)
            off += kbytes
            vals = np.frombuffer(view, dtype=vd, count=count, offset=off)
            off += vbytes
            yield ColumnBatch(keys, vals, key_sorted=bool(flags & 1))

    def deserialize(self, data) -> Iterator[Record]:
        # ColumnBatch and raw record lists both iterate as (k, v)
        for item in self._iter_items(data):
            yield from item

    def frame_spans(self, data) -> List[Tuple[int, int]]:
        """One span per columnar/pickle frame: a header-only walk (no
        column views built) so splitting a block across decode workers
        costs O(frames), not O(bytes) — and one NATIVE call when
        ``_staging.so`` is present (the C side parses the fixed-width
        dtype headers; exotic dtypes fall back here)."""
        from sparkrdma_tpu.memory.staging import native_columnar_frame_spans

        view = as_view(data)
        walked = native_columnar_frame_spans(view)
        if walked is not None:
            return list(zip(walked[:, 0].tolist(),
                            walked[:, 1].tolist()))
        spans: List[Tuple[int, int]] = []
        off = 0
        total = len(view)
        while off < total:
            start = off
            if view[off] == self.MAGIC_PICKLE:
                (n,) = _LEN.unpack_from(view, off + 1)
                off += 1 + _LEN.size + n
            elif view[off] == self.MAGIC:
                off += 2  # magic + flags
                nk = view[off]
                kd = np.dtype(bytes(view[off + 1 : off + 1 + nk]).decode("ascii"))
                off += 1 + nk
                nv = view[off]
                vd = np.dtype(bytes(view[off + 1 : off + 1 + nv]).decode("ascii"))
                off += 1 + nv
                (count,) = _LEN.unpack_from(view, off)
                off += _LEN.size + count * (kd.itemsize + vd.itemsize)
            else:
                raise ValueError(
                    f"bad columnar frame magic {view[off]:#x} at {off} "
                    "(mixed-serializer stream?)"
                )
            if off > total:
                raise ValueError(
                    f"truncated columnar frame at {start}: frame ends at "
                    f"{off}, stream holds {total}B"
                )
            spans.append((start, off))
        return spans


class CompressedSerializer(Serializer):
    """Compression wrapper over any serializer — the analog of the
    reference's read-side stream wrapping for codec support
    (``wrapStream`` reflection, RdmaShuffleReader.scala:51-58,117-127),
    applied symmetrically on write.  Codecs: ``zlib`` (default) and
    ``lzma``; payloads below ``min_size`` are stored raw (codec tag 0)
    since small-block compression costs more than it saves.

    Framing is ``1B tag + 4B length + body`` per serialize() call, so
    outputs are CONCATENATION-SAFE like the inner serializer's — the
    writer's spill-merge and any block concatenation rely on this
    (plain ``zlib.decompress`` would silently discard trailing frames).

    Wire-format versioning: this framed layout is
    ``WIRE_FORMAT_VERSION`` 2 (v1 was unframed ``1B tag + body``).  Any
    future layout change MUST claim fresh codec tag values so that
    mixed-version data fails fast on the existing "unknown codec tag"
    check instead of decoding garbage — tags 0-2 are forever v2.
    """

    WIRE_FORMAT_VERSION = 2
    _RAW, _ZLIB, _LZMA = 0, 1, 2

    # hard framing ceiling of the 4B length field (class attribute so
    # the structured-error unit test can lower it without manufacturing
    # a 4 GiB frame)
    MAX_FRAME_BODY = (1 << 32) - 1

    def __init__(self, inner: Serializer = None, codec: str = "zlib",
                 level: int = 1, min_size: int = 256,
                 frame_records: Optional[int] = None):
        self.inner = inner or PickleSerializer()
        if codec not in ("zlib", "lzma"):
            raise ValueError(f"unknown codec: {codec!r}")
        self.codec = codec
        self.level = level
        self.min_size = min_size
        if frame_records is not None:
            self.frame_records = max(1, int(frame_records))
        self.supports_columns = getattr(self.inner, "supports_columns", False)

    # one frame per this many records: bounds frame bodies far below the
    # 4B length field's 4 GiB ceiling for sane record sizes, and sets
    # the granularity of frame-parallel decode (conf
    # spark.shuffle.tpu.compressFrameRecords overrides per manager)
    frame_records = 65536

    def serialize(self, records: Iterable[Record]) -> bytes:
        from sparkrdma_tpu.utils.columns import ColumnBatch

        if isinstance(records, ColumnBatch):
            # columnar fast path: one frame per frame_records-sized
            # sub-batch (zero-copy column views), no per-record walk —
            # bounding frames keeps decompression splittable at frame
            # boundaries (one giant batch would serialize into a
            # single monolithic frame no decode worker can share)
            out = bytearray()
            for sub in self._iter_frame_batches(records):
                out += self._frame(self.inner.serialize(sub), len(sub))
            if not out:
                out += self._frame(self.inner.serialize(records), 0)
            return bytes(out)
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            if isinstance(rec, ColumnBatch):
                if batch:
                    out += self._frame(self.inner.serialize(batch), len(batch))
                    batch = []
                for sub in self._iter_frame_batches(rec):
                    out += self._frame(self.inner.serialize(sub), len(sub))
                continue
            batch.append(rec)
            if len(batch) >= self.frame_records:
                out += self._frame(self.inner.serialize(batch), len(batch))
                batch = []
        if batch or not out:
            out += self._frame(self.inner.serialize(batch), len(batch))
        return bytes(out)

    def _iter_frame_batches(self, b):
        """Slice one ColumnBatch into ≤ frame_records sub-batches —
        column VIEWS, no copies; sortedness carries over."""
        from sparkrdma_tpu.utils.columns import ColumnBatch

        n = len(b)
        fr = self.frame_records
        if n == 0:
            return
        if n <= fr:
            yield b
            return
        for lo in range(0, n, fr):
            yield ColumnBatch(
                b.keys[lo : lo + fr], b.vals[lo : lo + fr],
                key_sorted=b.key_sorted,
            )

    def _frame(self, raw: bytes, record_count: int = -1) -> bytes:
        if len(raw) < self.min_size:
            tag, body = self._RAW, raw
        elif self.codec == "zlib":
            import zlib

            tag, body = self._ZLIB, zlib.compress(raw, self.level)
        else:
            import lzma

            tag, body = self._LZMA, lzma.compress(raw)
        if len(body) > self.MAX_FRAME_BODY:
            raise FrameTooLargeError(
                len(body), record_count, self.frame_records,
                self.MAX_FRAME_BODY,
            )
        return bytes([tag]) + _LEN.pack(len(body)) + body

    def frame_spans(self, data) -> List[Tuple[int, int]]:
        """One span per ``tag + length + body`` frame — decompression
        splits at these boundaries, so one large block's inflate fans
        out across decode workers (each span group is decoded
        independently through ``deserialize``/``deserialize_columns``).
        Walked natively when ``_staging.so`` is present (1-byte tag
        prefix + 4B length, the same layout the pickle walk uses)."""
        from sparkrdma_tpu.memory.staging import native_frame_spans

        view = as_view(data)
        walked = native_frame_spans(view, 1)
        if walked is not None:
            return list(zip(walked[:, 0].tolist(),
                            walked[:, 1].tolist()))
        spans: List[Tuple[int, int]] = []
        off = 0
        while off < len(view):
            if off + 1 + _LEN.size > len(view):
                raise ValueError(f"truncated frame header at offset {off}")
            (n,) = _LEN.unpack_from(view, off + 1)
            end = off + 1 + _LEN.size + n
            if end > len(view):
                raise ValueError(
                    f"truncated frame: need {n}B at {off + 1 + _LEN.size}, "
                    f"have {len(view) - off - 1 - _LEN.size}B"
                )
            spans.append((off, end))
            off = end
        return spans

    def _iter_frames(self, data) -> Iterator[bytes]:
        view = as_view(data)
        off = 0
        while off < len(view):
            if off + 1 + _LEN.size > len(view):
                raise ValueError(f"truncated frame header at offset {off}")
            tag = view[off]
            (n,) = _LEN.unpack_from(view, off + 1)
            off += 1 + _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated frame: need {n}B at {off}, "
                    f"have {len(view) - off}B"
                )
            # zero-copy: codecs and the inner deserializers all take
            # buffer views — materializing ``bytes`` here would copy
            # every compressed body once more on the decode hot path
            body = view[off : off + n]
            off += n
            if tag == self._RAW:
                yield body
            elif tag == self._ZLIB:
                import zlib

                yield zlib.decompress(body)
            elif tag == self._LZMA:
                import lzma

                yield lzma.decompress(body)
            else:
                raise ValueError(f"unknown codec tag {tag}")

    def deserialize(self, data) -> Iterator[Record]:
        for raw in self._iter_frames(data):
            yield from self.inner.deserialize(raw)

    def deserialize_columns(self, data: bytes):
        """Columnar read path through the codec wrapper (only valid when
        ``supports_columns`` — i.e. the inner serializer is columnar)."""
        for raw in self._iter_frames(data):
            if raw:
                yield from self.inner.deserialize_columns(raw)
