"""Record serialization for the host record path.

The reference delegates serialization to Spark's SerializerInstance
inside the wrapped sort-shuffle writers (RdmaWrapperShuffleWriter.scala:85-101)
and wraps fetched streams for decompression on read
(RdmaShuffleReader.scala:51-58).  Here serializers are pluggable; the
default pickles record batches with a small length-prefixed framing so
partitions can be concatenated and sliced bytewise.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterable, Iterator, List, Tuple

Record = Tuple[Any, Any]

_LEN = struct.Struct("<I")


class Serializer:
    def serialize(self, records: Iterable[Record]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def deserialize(self, data: bytes) -> Iterator[Record]:  # pragma: no cover
        raise NotImplementedError


class PickleSerializer(Serializer):
    """Batched pickle with 4-byte batch length prefixes."""

    def __init__(self, batch_size: int = 4096):
        self.batch_size = batch_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                out += _LEN.pack(len(raw))
                out += raw
                batch = []
        if batch:
            raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
            out += _LEN.pack(len(raw))
            out += raw
        return bytes(out)

    def deserialize(self, data: bytes) -> Iterator[Record]:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off}, have {len(view) - off}B"
                )
            for rec in pickle.loads(view[off : off + n]):
                yield rec
            off += n


class CompressedSerializer(Serializer):
    """Compression wrapper over any serializer — the analog of the
    reference's read-side stream wrapping for codec support
    (``wrapStream`` reflection, RdmaShuffleReader.scala:51-58,117-127),
    applied symmetrically on write.  Codecs: ``zlib`` (default) and
    ``lzma``; payloads below ``min_size`` are stored raw (codec tag 0)
    since small-block compression costs more than it saves.

    Framing is ``1B tag + 4B length + body`` per serialize() call, so
    outputs are CONCATENATION-SAFE like the inner serializer's — the
    writer's spill-merge and any block concatenation rely on this
    (plain ``zlib.decompress`` would silently discard trailing frames).

    Wire-format versioning: this framed layout is
    ``WIRE_FORMAT_VERSION`` 2 (v1 was unframed ``1B tag + body``).  Any
    future layout change MUST claim fresh codec tag values so that
    mixed-version data fails fast on the existing "unknown codec tag"
    check instead of decoding garbage — tags 0-2 are forever v2.
    """

    WIRE_FORMAT_VERSION = 2
    _RAW, _ZLIB, _LZMA = 0, 1, 2

    def __init__(self, inner: Serializer = None, codec: str = "zlib",
                 level: int = 1, min_size: int = 256):
        self.inner = inner or PickleSerializer()
        if codec not in ("zlib", "lzma"):
            raise ValueError(f"unknown codec: {codec!r}")
        self.codec = codec
        self.level = level
        self.min_size = min_size

    # one frame per this many records: bounds frame bodies far below the
    # 4B length field's 4 GiB ceiling for sane record sizes
    frame_records = 65536

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.frame_records:
                out += self._frame(self.inner.serialize(batch))
                batch = []
        if batch or not out:
            out += self._frame(self.inner.serialize(batch))
        return bytes(out)

    def _frame(self, raw: bytes) -> bytes:
        if len(raw) < self.min_size:
            tag, body = self._RAW, raw
        elif self.codec == "zlib":
            import zlib

            tag, body = self._ZLIB, zlib.compress(raw, self.level)
        else:
            import lzma

            tag, body = self._LZMA, lzma.compress(raw)
        if len(body) >= 1 << 32:
            raise ValueError(
                f"frame body of {len(body)}B exceeds the 4 GiB framing "
                f"limit ({self.frame_records} records averaging "
                ">64 KiB each) — lower frame_records for huge records"
            )
        return bytes([tag]) + _LEN.pack(len(body)) + body

    def deserialize(self, data: bytes) -> Iterator[Record]:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if off + 1 + _LEN.size > len(view):
                raise ValueError(f"truncated frame header at offset {off}")
            tag = view[off]
            (n,) = _LEN.unpack_from(view, off + 1)
            off += 1 + _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated frame: need {n}B at {off}, "
                    f"have {len(view) - off}B"
                )
            body = bytes(view[off : off + n])
            off += n
            if tag == self._RAW:
                raw = body
            elif tag == self._ZLIB:
                import zlib

                raw = zlib.decompress(body)
            elif tag == self._LZMA:
                import lzma

                raw = lzma.decompress(body)
            else:
                raise ValueError(f"unknown codec tag {tag}")
            yield from self.inner.deserialize(raw)
