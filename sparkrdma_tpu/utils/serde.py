"""Record serialization for the host record path.

The reference delegates serialization to Spark's SerializerInstance
inside the wrapped sort-shuffle writers (RdmaWrapperShuffleWriter.scala:85-101)
and wraps fetched streams for decompression on read
(RdmaShuffleReader.scala:51-58).  Here serializers are pluggable; the
default pickles record batches with a small length-prefixed framing so
partitions can be concatenated and sliced bytewise.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterable, Iterator, List, Tuple

Record = Tuple[Any, Any]

_LEN = struct.Struct("<I")


class Serializer:
    def serialize(self, records: Iterable[Record]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def deserialize(self, data: bytes) -> Iterator[Record]:  # pragma: no cover
        raise NotImplementedError


class PickleSerializer(Serializer):
    """Batched pickle with 4-byte batch length prefixes."""

    def __init__(self, batch_size: int = 4096):
        self.batch_size = batch_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                out += _LEN.pack(len(raw))
                out += raw
                batch = []
        if batch:
            raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
            out += _LEN.pack(len(raw))
            out += raw
        return bytes(out)

    def deserialize(self, data: bytes) -> Iterator[Record]:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off}, have {len(view) - off}B"
                )
            for rec in pickle.loads(view[off : off + n]):
                yield rec
            off += n


class CompressedSerializer(Serializer):
    """Compression wrapper over any serializer — the analog of the
    reference's read-side stream wrapping for codec support
    (``wrapStream`` reflection, RdmaShuffleReader.scala:51-58,117-127),
    applied symmetrically on write.  Codecs: ``zlib`` (default) and
    ``lzma``; payloads below ``min_size`` are stored raw (1-byte codec
    tag 0) since small-block compression costs more than it saves.
    """

    _RAW, _ZLIB, _LZMA = 0, 1, 2

    def __init__(self, inner: Serializer = None, codec: str = "zlib",
                 level: int = 1, min_size: int = 256):
        self.inner = inner or PickleSerializer()
        if codec not in ("zlib", "lzma"):
            raise ValueError(f"unknown codec: {codec!r}")
        self.codec = codec
        self.level = level
        self.min_size = min_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        raw = self.inner.serialize(records)
        if len(raw) < self.min_size:
            return bytes([self._RAW]) + raw
        if self.codec == "zlib":
            import zlib

            return bytes([self._ZLIB]) + zlib.compress(raw, self.level)
        import lzma

        return bytes([self._LZMA]) + lzma.compress(raw)

    def deserialize(self, data: bytes) -> Iterator[Record]:
        if not data:
            return
        tag, body = data[0], bytes(memoryview(data)[1:])
        if tag == self._RAW:
            raw = body
        elif tag == self._ZLIB:
            import zlib

            raw = zlib.decompress(body)
        elif tag == self._LZMA:
            import lzma

            raw = lzma.decompress(body)
        else:
            raise ValueError(f"unknown codec tag {tag}")
        yield from self.inner.deserialize(raw)
