"""Record serialization for the host record path.

The reference delegates serialization to Spark's SerializerInstance
inside the wrapped sort-shuffle writers (RdmaWrapperShuffleWriter.scala:85-101)
and wraps fetched streams for decompression on read
(RdmaShuffleReader.scala:51-58).  Here serializers are pluggable; the
default pickles record batches with a small length-prefixed framing so
partitions can be concatenated and sliced bytewise.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterable, Iterator, List, Tuple

Record = Tuple[Any, Any]

_LEN = struct.Struct("<I")


class Serializer:
    def serialize(self, records: Iterable[Record]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def deserialize(self, data: bytes) -> Iterator[Record]:  # pragma: no cover
        raise NotImplementedError


class PickleSerializer(Serializer):
    """Batched pickle with 4-byte batch length prefixes."""

    def __init__(self, batch_size: int = 4096):
        self.batch_size = batch_size

    def serialize(self, records: Iterable[Record]) -> bytes:
        out = bytearray()
        batch: List[Record] = []
        for rec in records:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                out += _LEN.pack(len(raw))
                out += raw
                batch = []
        if batch:
            raw = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
            out += _LEN.pack(len(raw))
            out += raw
        return bytes(out)

    def deserialize(self, data: bytes) -> Iterator[Record]:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if off + _LEN.size > len(view):
                raise ValueError(f"truncated batch header at offset {off}")
            (n,) = _LEN.unpack_from(view, off)
            off += _LEN.size
            if off + n > len(view):
                raise ValueError(
                    f"truncated batch: need {n}B at {off}, have {len(view) - off}B"
                )
            for rec in pickle.loads(view[off : off + n]):
                yield rec
            off += n
