"""Runtime wire-protocol frame validator (``spark.shuffle.tpu.wireDebug``).

Third runtime sanitizer in the dbglock/ledger lineage: the static half
of the wire contract lives in tools/wirecheck.py (WC01–WC05 over the
declarative ``WIRE_SCHEMA`` tables); this module is the runtime half.
When the manager flips it on (before building its node, like the lock
factory and the resource ledger), both TCP engines' receive paths and
the loopback dispatch plane validate every frame as it arrives:

- header sanity — known opcode, length within the frame bound;
- RPC frames decode through the declarative schemas (every count and
  length field bounds-checked against the received bytes) BEFORE the
  application listener sees them;
- every check lands in ``wire_frames_validated_total`` /
  ``wire_frames_rejected_total`` counters labeled by engine and opcode
  (``metrics_report.py`` renders the wire-health table from them), and
  every rejection logs with a hexdump context.

Off by default: call sites check :func:`wire_debug_enabled` first, so
the production receive path pays one module-global read per frame.

A rejected RPC frame is DROPPED — the blast radius is that one frame,
never the channel (the control plane's segments are independently
decodable, so a lost frame degrades to the existing timeout/retry
machinery).  A bad frame HEADER still tears the channel down in the
engines — a byte stream whose framing lies is desynced and cannot be
resynchronized — but the validator names the opcode and context first.
"""

from __future__ import annotations

import logging
from typing import Optional

from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.rpc.messages import (
    WireFormatError,
    decode_msg,
    hex_context,
)

logger = logging.getLogger(__name__)

_enabled = False


def set_wire_debug(on: bool) -> None:
    """Flip the process-global validator (manager does this from conf
    BEFORE building its node, the dbglock/ledger flow)."""
    global _enabled
    _enabled = bool(on)


def wire_debug_enabled() -> bool:
    return _enabled


def opcode_label(opcode) -> str:
    """Stable metric label for one transport opcode."""
    from sparkrdma_tpu.transport import tcp as wire

    return {
        wire.OP_RPC: "rpc",
        wire.OP_READ_REQ: "read_req",
        wire.OP_READ_RESP: "read_resp",
    }.get(opcode, str(opcode))


def header_error(engine: str, opcode: int, length: int) -> Optional[str]:
    """Validate one frame header; returns the error description (after
    counting the rejection) or None after counting the validation."""
    from sparkrdma_tpu.transport import tcp as wire

    label = opcode_label(opcode)
    err = None
    if opcode not in (wire.OP_RPC, wire.OP_READ_REQ, wire.OP_READ_RESP):
        err = f"unknown opcode {opcode}"
    elif not 0 <= length <= wire._MAX_FRAME:
        err = f"bad frame length {length} for opcode {label}"
    if err is None:
        counter(
            "wire_frames_validated_total", engine=engine, opcode=label
        ).inc()
        return None
    counter(
        "wire_frames_rejected_total", engine=engine, opcode=label
    ).inc()
    if RECORDER.enabled:
        fr_event(
            "transport", "wire_reject",
            engine=engine, opcode=label, reason=err,
        )
        # a lying frame header desyncs the channel — snapshot the
        # rings before the engine tears it down
        RECORDER.auto_dump("wire_reject")
    return err


def rpc_frame_ok(engine: str, frame) -> bool:
    """Schema-validate one RPC frame before dispatch.  A rejection is
    counted, hexdump-logged, and the frame dropped (one-frame blast
    radius); True means the frame decodes cleanly end to end."""
    try:
        decode_msg(bytes(frame))
    except WireFormatError as e:
        counter(
            "wire_frames_rejected_total", engine=engine, opcode="rpc"
        ).inc()
        logger.warning(
            "wireDebug[%s]: dropping RPC frame: %s (frame %s)",
            engine, e, hex_context(bytes(frame)),
        )
        if RECORDER.enabled:
            fr_event(
                "transport", "wire_reject",
                engine=engine, opcode="rpc", reason=str(e)[:200],
            )
            RECORDER.auto_dump("wire_reject")
        return False
    counter(
        "wire_frames_validated_total", engine=engine, opcode="rpc"
    ).inc()
    return True


__all__ = [
    "set_wire_debug",
    "wire_debug_enabled",
    "opcode_label",
    "header_error",
    "rpc_frame_ok",
]
