"""Core identifier and location types.

TPU-native analogs of the reference's id/location vocabulary
(reference: RdmaUtils.scala:26-138):

- ``BlockLocation`` — where one (map, reduce) block lives.  The reference
  encodes ``(address: i64, length: i32, mKey: i32)`` where ``address`` is a
  raw mmap'd virtual address and ``mKey`` the ibverbs memory-region key.
  Here ``address`` is a byte offset inside the owner's HBM arena segment
  and ``mkey`` is the arena segment id (epoch-tagged so stale locations
  are detectable) — same 16-byte wire entry, same role.
- ``BlockManagerId`` — (executor_id, host, port) triple identifying a
  block-serving endpoint, with a compact UTF-8 wire format.
- ``ShuffleManagerId`` — (host, port, BlockManagerId) identifying one
  shuffle-manager instance, with an interning cache so the driver's maps
  hold one object per peer (reference: RdmaUtils.scala:121-138).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass
from typing import Dict, Tuple

# One location entry on the wire: little-endian (address: i64, length: i32,
# mkey: i32) == 16 bytes, matching the reference's ENTRY_SIZE
# (RdmaMapTaskOutput.scala:27).
_LOCATION_STRUCT = struct.Struct("<qii")
LOCATION_ENTRY_SIZE = _LOCATION_STRUCT.size  # 16

# String/port wire pieces — offsets always advance by these ``.size``
# constants, never by integer literals (wirecheck WC04).
_U16 = struct.Struct("<H")
_I32 = struct.Struct("<i")


@dataclass(frozen=True, slots=True)
class BlockLocation:
    """Address of one shuffle block inside a registered memory domain.

    address: byte offset within the owning arena segment (device HBM).
    length:  block length in bytes.
    mkey:    arena segment key — identifies which registered segment of the
             owning executor holds the block (0 == EMPTY/no data).
    """

    address: int
    length: int
    mkey: int

    def write(self, buf: bytearray) -> None:
        buf += _LOCATION_STRUCT.pack(self.address, self.length, self.mkey)

    @staticmethod
    def read(view: memoryview, offset: int = 0) -> "BlockLocation":
        a, l, k = _LOCATION_STRUCT.unpack_from(view, offset)
        return BlockLocation(a, l, k)

    def pack(self) -> bytes:
        return _LOCATION_STRUCT.pack(self.address, self.length, self.mkey)

    @property
    def is_empty(self) -> bool:
        return self.length == 0


# Sentinel for "partition produced no bytes" — mkey 0 is reserved.
BlockLocation.EMPTY = BlockLocation(0, 0, 0)


def _write_utf8(buf: bytearray, s: str) -> None:
    raw = s.encode("utf-8")
    if len(raw) > 0xFFFF:
        raise ValueError(f"string too long for wire format: {len(raw)}")
    buf += _U16.pack(len(raw))
    buf += raw


def _read_utf8(view: memoryview, offset: int) -> Tuple[str, int]:
    if offset + _U16.size > len(view):
        raise ValueError(f"truncated string header at offset {offset}")
    (n,) = _U16.unpack_from(view, offset)
    start = offset + _U16.size
    end = start + n
    if end > len(view):
        raise ValueError(
            f"truncated string: need {n}B at offset {start}, "
            f"have {len(view) - start}B"
        )
    s = bytes(view[start:end]).decode("utf-8")
    return s, end


@dataclass(frozen=True, slots=True)
class BlockManagerId:
    """Identifies a block-serving endpoint (executor_id, host, port).

    Compact wire format mirroring the reference's
    SerializableBlockManagerId (RdmaUtils.scala:28-67): length-prefixed
    UTF-8 strings plus an i32 port.
    """

    executor_id: str
    host: str
    port: int

    def write(self, buf: bytearray) -> None:
        _write_utf8(buf, self.executor_id)
        _write_utf8(buf, self.host)
        buf += _I32.pack(self.port)

    @staticmethod
    def read(view: memoryview, offset: int = 0) -> Tuple["BlockManagerId", int]:
        executor_id, offset = _read_utf8(view, offset)
        host, offset = _read_utf8(view, offset)
        (port,) = _I32.unpack_from(view, offset)
        return BlockManagerId(executor_id, host, port), offset + _I32.size

    def serialized_length(self) -> int:
        return (
            _U16.size + len(self.executor_id.encode("utf-8"))
            + _U16.size + len(self.host.encode("utf-8"))
            + _I32.size
        )


@dataclass(frozen=True, slots=True)
class ShuffleManagerId:
    """One shuffle-manager instance: (host, port) of its transport endpoint
    plus the Spark-style BlockManagerId it serves.

    Interned via :func:`get_cached_shuffle_manager_id` so driver-side maps
    compare by identity (reference: RdmaUtils.scala:121-138).
    """

    host: str
    port: int
    block_manager_id: BlockManagerId

    def write(self, buf: bytearray) -> None:
        _write_utf8(buf, self.host)
        buf += _I32.pack(self.port)
        self.block_manager_id.write(buf)

    @staticmethod
    def read(view: memoryview, offset: int = 0) -> Tuple["ShuffleManagerId", int]:
        host, offset = _read_utf8(view, offset)
        (port,) = _I32.unpack_from(view, offset)
        bmid, offset = BlockManagerId.read(view, offset + _I32.size)
        return get_cached_shuffle_manager_id(ShuffleManagerId(host, port, bmid)), offset

    def serialized_length(self) -> int:
        return (
            _U16.size + len(self.host.encode("utf-8"))
            + _I32.size
            + self.block_manager_id.serialized_length()
        )


_smid_cache: Dict[ShuffleManagerId, ShuffleManagerId] = {}
_smid_lock = threading.Lock()  # lock-order: 94


def get_cached_shuffle_manager_id(smid: ShuffleManagerId) -> ShuffleManagerId:
    cached = _smid_cache.get(smid)
    if cached is not None:
        return cached
    with _smid_lock:
        return _smid_cache.setdefault(smid, smid)
