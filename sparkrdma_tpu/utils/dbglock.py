"""Runtime lock sanitizer: rank-checked lock wrappers (conf lockDebug).

The static gate (tools/concheck.py) proves the declared lock hierarchy
acyclic from the ``# lock-order: N`` ranks; this module validates the
SAME hierarchy at runtime, catching the orders statics cannot see —
callbacks run inline under a lock, cross-class call chains, code paths
only a chaos test reaches.  ``LockFactory`` hands out:

- plain ``threading`` primitives while disabled (the default): zero
  steady-state overhead, identity-checkable in tests;
- :class:`DebugLock`-wrapped primitives when conf
  ``spark.shuffle.tpu.lockDebug`` is on (TpuShuffleManager flips the
  process-global factory exactly like the metrics registry), which

  * keep a per-thread acquisition stack (lock, rank, acquire site),
  * assert rank monotonicity at acquire time — taking a lock whose
    rank is <= the highest rank already held by this thread raises
    :class:`LockOrderViolation` (and counts
    ``lock_rank_violations_total``), unless it is a reentrant
    re-acquisition of a lock the thread already owns,
  * record hold-time histograms (``lock_hold_us{lock=...}``) through
    the metrics registry, rendered by tools/metrics_report.py.

Ranks are the canonical hierarchy documented in README "Concurrency
discipline"; a lock may only be acquired with a rank strictly greater
than every lock its thread already holds.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import List, Optional, Tuple

from sparkrdma_tpu.metrics import counter, histogram

# log-ladder microsecond buckets for lock hold times: 1us .. 10s
HOLD_US_EDGES = [
    float(m * d)
    for d in (1, 10, 100, 1_000, 10_000, 100_000, 1_000_000)
    for m in (1, 2.5, 5)
]


class LockOrderViolation(RuntimeError):
    """A thread acquired a lock out of rank order (potential deadlock)."""


_TLS = threading.local()


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def held_locks() -> List[Tuple[str, int, str]]:
    """This thread's acquisition stack: [(name, rank, acquire site)]."""
    return [(e.lock.name, e.lock.rank, e.site) for e in _held_stack()]


def _call_site(depth: int = 2) -> str:
    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except (ValueError, AttributeError):
        return "<unknown>"


class _Held:
    """One entry of a thread's acquisition stack."""

    __slots__ = ("lock", "depth", "t0", "site", "released")

    def __init__(self, lock: "DebugLock", site: str):
        self.lock = lock
        self.depth = 1
        self.t0 = time.monotonic()
        self.site = site
        # set by a CROSS-THREAD release (a plain Lock used as a
        # signal): the owner thread purges stale entries lazily
        self.released = False


class DebugLock:
    """Rank-checked wrapper over a ``threading.Lock``/``RLock``.

    Forwards ``_release_save``/``_acquire_restore``/``_is_owned`` so a
    ``threading.Condition`` built over a reentrant DebugLock keeps full
    wait/notify semantics — a ``wait()`` ends the current hold period
    (observing its hold time) and re-entry after wake re-opens one
    without re-running the rank check (the lock was logically held)."""

    __slots__ = ("name", "rank", "_inner", "_reentrant", "_m_hold",
                 "_m_acquires", "_cur")

    def __init__(self, name: str, rank: int, inner, reentrant: bool):
        self.name = name
        self.rank = int(rank)
        self._inner = inner
        self._reentrant = reentrant
        self._cur: Optional[_Held] = None  # current holder's entry
        self._m_hold = histogram(
            "lock_hold_us", edges=HOLD_US_EDGES, lock=name
        )
        self._m_acquires = counter("lock_acquires_total", lock=name)

    # -- rank discipline ----------------------------------------------------
    def _entry(self) -> Optional[_Held]:
        for e in _held_stack():
            if e.lock is self:
                return e
        return None

    def _check_rank(self, site: str) -> None:
        stack = _held_stack()
        worst = None
        for e in stack:
            if e.lock.rank >= self.rank and (
                worst is None or e.lock.rank > worst.lock.rank
            ):
                worst = e
        if worst is None:
            return
        counter("lock_rank_violations_total").inc()
        held = ", ".join(
            f"{e.lock.name}(rank {e.lock.rank}) at {e.site}"
            for e in stack
        )
        raise LockOrderViolation(
            f"acquiring {self.name} (rank {self.rank}) at {site} "
            f"while holding {worst.lock.name} (rank {worst.lock.rank}) "
            f"— lock-order ranks must strictly increase inward; "
            f"held: [{held}]"
        )

    # -- lock protocol ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1,
                _site_depth: int = 2) -> bool:
        site = _call_site(_site_depth)
        stack = _held_stack()
        if any(e.released for e in stack):
            # purge entries a cross-thread release marked stale
            stack[:] = [e for e in stack if not e.released]
        entry = self._entry()
        if entry is not None:
            if not self._reentrant:
                counter("lock_rank_violations_total").inc()
                raise LockOrderViolation(
                    f"same-thread recursive acquire of non-reentrant "
                    f"lock {self.name} at {site} (first acquired at "
                    f"{entry.site}) — guaranteed deadlock"
                )
            if self._inner.acquire(blocking, timeout):
                entry.depth += 1
                return True
            return False
        self._check_rank(site)
        if not self._inner.acquire(blocking, timeout):
            return False
        entry = _Held(self, site)
        _held_stack().append(entry)
        self._cur = entry
        self._m_acquires.inc()
        return True

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            e = stack[i]
            if e.lock is self:
                if e.depth > 1:
                    e.depth -= 1
                else:
                    del stack[i]
                    self._cur = None
                    self._m_hold.observe(
                        (time.monotonic() - e.t0) * 1e6
                    )
                self._inner.release()
                return
        # not in this thread's stack: a plain Lock released by another
        # thread (signal usage).  Capture the holder's entry BEFORE
        # releasing (a new holder may acquire the instant the primitive
        # frees, and marking ITS live entry would blind the sanitizer
        # to it), release the primitive (an RLock raises for
        # non-owners, skipping any marking), then flag the captured
        # entry stale so the old holder's thread purges it at its next
        # lock op instead of carrying a phantom hold.
        cur = self._cur
        self._inner.release()
        if cur is not None:
            cur.released = True
            if self._cur is cur:
                self._cur = None

    def __enter__(self) -> "DebugLock":
        self.acquire(_site_depth=3)
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._entry() is not None

    # -- Condition integration ----------------------------------------------
    def _release_save(self):
        """Full release for ``Condition.wait``: close the hold period
        (observe hold time, pop the stack entry — PRESERVING its
        reentrant depth in the state token, so a wait under a nested
        hold restores the exact stack shape) and hand the inner state
        back."""
        stack = _held_stack()
        depth = 1
        for i in range(len(stack) - 1, -1, -1):
            e = stack[i]
            if e.lock is self:
                depth = e.depth
                del stack[i]
                self._cur = None
                self._m_hold.observe((time.monotonic() - e.t0) * 1e6)
                break
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        """Re-acquire after ``Condition.wait`` wakes: the lock was
        logically held across the wait, so no rank re-check — but a new
        hold period starts for the hold-time series, at the SAME
        reentrant depth the wait released."""
        inner_state, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        entry = _Held(self, _call_site(3))
        entry.depth = depth
        _held_stack().append(entry)
        self._cur = entry
        self._m_acquires.inc()

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._entry() is not None

    def __repr__(self) -> str:
        return f"DebugLock({self.name}, rank={self.rank})"


class LockFactory:
    """Hands out lock primitives: plain ``threading`` objects while
    ``enabled`` is False (zero overhead), rank-checked debug wrappers
    while True.  One process-global instance, flipped on by
    TpuShuffleManager when conf ``spark.shuffle.tpu.lockDebug`` is set
    — BEFORE any instrumented object creates its locks, mirroring the
    metrics registry's enable flow."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled

    def lock(self, name: str, rank: int):
        if not self.enabled:
            return threading.Lock()
        return DebugLock(name, rank, threading.Lock(), reentrant=False)

    def rlock(self, name: str, rank: int):
        if not self.enabled:
            return threading.RLock()
        return DebugLock(name, rank, threading.RLock(), reentrant=True)

    def condition(self, name: str, rank: int):
        if not self.enabled:
            return threading.Condition()
        return threading.Condition(
            DebugLock(name, rank, threading.RLock(), reentrant=True)
        )


GLOBAL_LOCK_FACTORY = LockFactory(enabled=False)


def get_lock_factory() -> LockFactory:
    return GLOBAL_LOCK_FACTORY


def dbg_lock(name: str, rank: int):
    """A mutex ranked ``rank`` in the canonical hierarchy (see README
    "Concurrency discipline"); tools/concheck.py reads the rank from
    this call, so no ``# lock-order`` comment is needed."""
    return GLOBAL_LOCK_FACTORY.lock(name, rank)


def dbg_rlock(name: str, rank: int):
    return GLOBAL_LOCK_FACTORY.rlock(name, rank)


def dbg_condition(name: str, rank: int):
    return GLOBAL_LOCK_FACTORY.condition(name, rank)


__all__ = [
    "DebugLock",
    "LockFactory",
    "LockOrderViolation",
    "dbg_condition",
    "dbg_lock",
    "dbg_rlock",
    "get_lock_factory",
    "held_locks",
]
