"""Columnar record batches: the unsafe-row analog for the record plane.

The reference keeps its map-side hot loop off slow object paths by
wrapping Spark's ``UnsafeShuffleWriter`` — records stay in serialized
row form end to end (RdmaWrapperShuffleWriter.scala:85-101).  The
TPU-native record plane gets the same property from columns: a
:class:`ColumnBatch` holds one batch of (key, value) records as two
parallel numpy arrays, so partitioning, serialization, combining, and
grouping are all vectorized numpy kernels instead of per-record Python.

Value columns may be any fixed-width dtype — numeric, ``|SN`` byte
strings (the classic 10-90 byte shuffle payload), or structured rows.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, List, Optional, Tuple

import numpy as np


class ColumnBatch:
    """One batch of records as parallel (keys, vals) columns.

    ``key_sorted`` marks a batch whose rows are already in ascending
    key order — writers set it after map-side bucket sorting, it rides
    the wire in the frame flags, and sorted-aware readers merge such
    runs with views instead of re-sorting (the gather is the record
    plane's most expensive kernel)."""

    __slots__ = ("keys", "vals", "key_sorted")

    def __init__(self, keys: np.ndarray, vals: np.ndarray,
                 key_sorted: bool = False):
        keys = np.asarray(keys)
        vals = np.asarray(vals)
        if keys.ndim != 1 or vals.ndim != 1 or keys.shape[0] != vals.shape[0]:
            raise ValueError(
                f"keys/vals must be equal-length 1-D columns, got "
                f"{keys.shape} / {vals.shape}"
            )
        if keys.dtype.hasobject or vals.dtype.hasobject:
            raise TypeError(
                "object-dtype columns defeat the columnar plane; use the "
                "tuple record path for non-fixed-width records"
            )
        if vals.dtype.kind == "S" and vals.dtype.itemsize:
            # numpy bytes-strings ('S') strip trailing NULs on every
            # element extraction, silently corrupting raw payloads;
            # reinterpret as void rows of the same width — exact bytes,
            # zero-copy.  (Keys keep 'S' semantics: their padded
            # comparison is what hashing and ordering want.)
            vals = vals.view(f"V{vals.dtype.itemsize}")
        self.keys = keys
        self.vals = vals
        self.key_sorted = key_sorted

    def __len__(self) -> int:
        return int(self.keys.shape[0])

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes + self.vals.nbytes)

    def __iter__(self) -> Iterator[Tuple]:
        """Record view (slow path, for plane interop): yields Python
        (key, value) scalars."""
        yield from zip(self.keys.tolist(), self.vals.tolist())

    @classmethod
    def from_records(
        cls,
        records: Iterable[Tuple],
        key_dtype=None,
        val_dtype=None,
    ) -> "ColumnBatch":
        """Pack an iterable of (k, v) tuples into columns (dtype
        inferred by numpy unless given)."""
        ks: List = []
        vs: List = []
        for k, v in records:
            ks.append(k)
            vs.append(v)
        keys = np.asarray(ks, dtype=key_dtype)
        vals = np.asarray(vs, dtype=val_dtype)
        return cls(keys, vals)


def concat_batches(batches: List[ColumnBatch]) -> Optional[ColumnBatch]:
    """Concatenate batches into one (None for an empty list)."""
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    return ColumnBatch(
        np.concatenate([b.keys for b in batches]),
        np.concatenate([b.vals for b in batches]),
    )


_REDUCE_UFUNCS = {
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
}


def take_rows(col: np.ndarray, idx: np.ndarray,
              out: Optional[np.ndarray] = None) -> np.ndarray:
    """``col[idx]`` through the native prefetching row gather when
    eligible (2.5-3x numpy on wide rows — the record plane's hottest
    kernel); falls back to ``np.take``."""
    from sparkrdma_tpu.memory.staging import native_row_gather

    if out is None:
        out = np.empty(idx.shape[0], col.dtype)
    if not native_row_gather(col, idx, out):
        np.take(col, idx, out=out)
    return out


def stable_key_order(keys: np.ndarray) -> np.ndarray:
    """Stable argsort choosing the fastest path: integer keys spanning
    < 2^16 values (partition ids, modest-cardinality group keys) rebase
    to uint16 where numpy's stable sort is RADIX — measured ~15x faster
    than the int64 timsort path (5.6ms vs 86ms per 1M); WIDE-range
    int64 keys (the TeraSort shape) ride the native 64-bit LSD radix
    argsort (~2.5x timsort) when the lib is built."""
    if len(keys) and np.issubdtype(keys.dtype, np.integer):
        kmin = keys.min()
        if int(keys.max()) - int(kmin) < (1 << 16):
            return np.argsort(
                (keys - kmin).astype(np.uint16), kind="stable"
            )
        if keys.dtype == np.int64 and len(keys) >= (1 << 14):
            from sparkrdma_tpu.memory.staging import (
                native_radix_argsort,
                native_rank_compress,
            )

            # wide RANGE but low CARDINALITY (the groupByKey shape):
            # compress keys to dense sorted uint16 ranks and ride the
            # radix path above — ~3x the 4-pass 64-bit radix; the
            # probe self-aborts in <1ms on high-cardinality columns
            res = native_rank_compress(keys)
            if res is not None:
                return np.argsort(res[0], kind="stable")
            order = native_radix_argsort(keys)
            if order is not None:
                return order
    return np.argsort(keys, kind="stable")


def _run_heads(sorted_keys: np.ndarray) -> np.ndarray:
    """Indices of the first row of each key run in a key-sorted column."""
    heads = np.empty(len(sorted_keys), bool)
    heads[0] = True
    np.not_equal(sorted_keys[1:], sorted_keys[:-1], out=heads[1:])
    return np.flatnonzero(heads)


def combine_columns(batch: ColumnBatch, kind: str) -> ColumnBatch:
    """Vectorized reduce-by-key over one batch: sort by key, then one
    ``ufunc.reduceat`` per run — the columnar combiner the tuple plane
    does per record through ``Aggregator.merge_value``."""
    if kind == "group":
        return batch  # grouping collects, nothing to reduce map-side
    ufunc = _REDUCE_UFUNCS[kind]
    if len(batch) == 0:
        return batch
    if batch.key_sorted:
        sk, sv = batch.keys, batch.vals
    else:
        order = stable_key_order(batch.keys)
        sk = take_rows(batch.keys, order)
        sv = take_rows(batch.vals, order)
    idx = _run_heads(sk)
    return ColumnBatch(sk[idx], ufunc.reduceat(sv, idx), key_sorted=True)


def group_columns(batch: ColumnBatch,
                  order: Optional[np.ndarray] = None
                  ) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Vectorized group-by-key: returns (unique_keys, per-key value
    arrays) — group_by_key's output with numpy arrays standing in for
    the tuple plane's Python lists.  A ``key_sorted`` batch skips the
    sort+gather entirely (value arrays are then VIEWS into the batch);
    callers holding a precomputed stable key order (e.g. the sorted-run
    merge over concatenated key-sorted blocks) pass it as ``order`` to
    skip just the sort."""
    if batch.key_sorted:
        sk, sv = batch.keys, batch.vals
    else:
        if order is None:
            order = stable_key_order(batch.keys)
        sk = take_rows(batch.keys, order)
        sv = take_rows(batch.vals, order)
    idx = _run_heads(sk)
    return sk[idx], np.split(sv, idx[1:])


def sorted_runs_order(batches, cat: ColumnBatch):
    """Stable merge order over ``cat`` = concat of the (key-sorted)
    ``batches`` via the native loser tree — None when ineligible (the
    caller falls back to a full sort).  A single sorted run is the
    identity order; K runs merge in K log K compares per row, ~2.8x
    the radix re-sort on this shape."""
    if not batches or not all(b.key_sorted for b in batches):
        return None
    if len(batches) == 1:
        return np.arange(len(cat.keys), dtype=np.int64)
    if cat.keys.dtype != np.int64:
        return None
    from sparkrdma_tpu.memory.staging import native_kway_merge

    offs = np.zeros(len(batches) + 1, np.int64)
    np.cumsum([len(b) for b in batches], out=offs[1:])
    return native_kway_merge(np.ascontiguousarray(cat.keys), offs)


def sort_batch(batch: ColumnBatch) -> ColumnBatch:
    """Stable key sort of one batch (gather per block — the unit the
    decode pipeline parallelizes across workers)."""
    if batch.key_sorted or len(batch) <= 1:
        return ColumnBatch(batch.keys, batch.vals, key_sorted=True)
    order = stable_key_order(batch.keys)
    return ColumnBatch(
        take_rows(batch.keys, order), take_rows(batch.vals, order),
        key_sorted=True,
    )


def iter_batch_records(batch: ColumnBatch,
                       chunk: int = 1 << 16) -> Iterator[Tuple]:
    """Chunked record view of one batch: (k, v) scalars materialize
    ``chunk`` rows at a time instead of two whole-column ``tolist``
    calls — the streaming surface the k-way merge yields through."""
    n = len(batch)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        yield from zip(
            batch.keys[lo:hi].tolist(), batch.vals[lo:hi].tolist()
        )


def iter_merged_sorted_batches(batches: List[ColumnBatch],
                               chunk: int = 1 << 16) -> Iterator[Tuple]:
    """Streaming k-way merge over per-block sorted runs — the read-side
    order-by stage without the materialize-then-sort: unsorted batches
    stable-sort ONCE per block (already done in the decode workers on
    the pipelined path), then the runs merge lazily.  int64 keys ride
    the native loser tree for the merge ORDER (keys-only concat) with
    the gather+tolist chunked, so peak residency is numpy columns plus
    one chunk of record objects instead of the whole partition's tuple
    list; other key dtypes heap-merge the chunked record iterators.
    The emitted sequence is bit-identical to a stable global sort of
    the concatenated batches (stable per-run sort + run-order-stable
    merge)."""
    import heapq

    nonempty = [
        b if b.key_sorted else sort_batch(b) for b in batches if len(b)
    ]
    if not nonempty:
        return
    if len(nonempty) == 1:
        yield from iter_batch_records(nonempty[0], chunk)
        return
    cat = concat_batches(nonempty)
    order = sorted_runs_order(nonempty, cat)
    if order is None and len(cat) > chunk:
        # no native loser tree for this key dtype: a vectorized stable
        # sort over the (run-structured) concat beats a Python-level
        # heap walk for anything sizable, and the stability argument
        # keeps the sequence identical either way
        order = stable_key_order(cat.keys)
    if order is not None:
        keys, vals = cat.keys, cat.vals
        for lo in range(0, len(order), chunk):
            ci = order[lo : lo + chunk]
            yield from zip(keys[ci].tolist(), vals[ci].tolist())
        return
    yield from heapq.merge(
        *[iter_batch_records(b, chunk) for b in nonempty],
        key=lambda kv: kv[0],
    )


def merge_sorted_groups(
    per_batch: List[Tuple[np.ndarray, List[np.ndarray]]],
) -> Iterator[Tuple[Any, np.ndarray]]:
    """Group-by-key over pre-grouped (unique_keys, value-views) runs —
    the read-side merge for KEY-SORTED blocks, skipping the global
    concat+gather (the record plane's most expensive kernel).  Worth it
    when total unique keys is modest (the per-key Python loop); callers
    guard on cardinality and fall back to ``group_columns`` over a
    concat otherwise."""
    groups: "dict" = {}
    for uk, splits in per_batch:
        for k, v in zip(uk.tolist(), splits):
            lst = groups.get(k)
            if lst is None:
                groups[k] = [v]
            else:
                lst.append(v)
    for k, vs in groups.items():
        yield k, (vs[0] if len(vs) == 1 else np.concatenate(vs))
