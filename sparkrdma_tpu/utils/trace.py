"""Lightweight span tracing (Chrome trace-event format).

The reference's only tracing is inline wall-clock logging
(SURVEY.md §5: connection latency at RdmaNode.java:279,307-308, fetch
timing at RdmaShuffleFetcherIterator.scala:110,140-148).  The rebuild
promotes that to a proper subsystem: nested spans collected per thread,
dumpable as a ``chrome://tracing`` / Perfetto JSON file, enabled by conf
(``spark.shuffle.tpu.trace``) or programmatically.

Zero overhead when disabled: ``span()`` returns a no-op context.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, List


class Tracer:
    def __init__(self, enabled: bool = False, process_name: str = "sparkrdma_tpu",
                 max_events: int = 1 << 20):
        self.enabled = enabled
        self.process_name = process_name
        self.max_events = max_events
        self.dropped = 0
        self._events: List[Dict] = []
        self._lock = threading.Lock()  # lock-order: 92
        self._t0 = time.perf_counter()

    def _append(self, event: Dict) -> None:
        """Bounded append: beyond max_events new events are counted but
        dropped, so an always-on trace can't grow without limit.  Drops
        were once silent (the count surfaced only in the dump's
        metadata); now they tick ``trace_dropped_total`` so a live
        scrape shows a saturated tracer while the run is still up."""
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                dropped = True
            else:
                self._events.append(event)
                dropped = False
        if dropped:
            # outside the tracer lock (92): the registry's stripe locks
            # rank higher but keeping inc() lock-free here is cheaper
            from sparkrdma_tpu.metrics import counter

            counter("trace_dropped_total").inc()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        ts = self._now_us()
        try:
            yield
        finally:
            dur = self._now_us() - ts
            self._append({
                "name": name, "ph": "X", "ts": ts, "dur": dur,
                "pid": 0, "tid": threading.get_ident() % 100000,
                "args": args or {},
            })

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        self._append({
            "name": name, "ph": "i", "ts": self._now_us(), "s": "t",
            "pid": 0, "tid": threading.get_ident() % 100000,
            "args": args or {},
        })

    def counter(self, name: str, **values) -> None:
        if not self.enabled:
            return
        self._append({
            "name": name, "ph": "C", "ts": self._now_us(),
            "pid": 0, "args": values,
        })

    @property
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def dump(self, path: str) -> None:
        """Write a chrome://tracing-compatible JSON file."""
        with self._lock:
            events = list(self._events)
        doc = {
            "traceEvents": events,
            "metadata": {
                "process_name": self.process_name,
                "dropped_events": self.dropped,
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


# process-global default tracer; managers enable it from conf
GLOBAL_TRACER = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return GLOBAL_TRACER
