"""Runtime resource-lifecycle sanitizer (conf resourceDebug).

The static gate (tools/flowcheck.py) proves the DECLARED lifecycle of
every credit/token/pin/fd resource balanced — each annotated acquire
has a release on all paths, no path releases twice, nothing releases
what it never owned.  This module validates the SAME lifecycles at
runtime, catching what statics cannot see: callback orderings, races,
chaos-test paths, and arithmetic bugs in the amounts.

``ResourceLedger`` is the dbglock/metrics-registry process-global
shape: disabled (the default) its :func:`ledger_acquire` hands out one
shared no-op ticket — zero steady-state overhead, identity-checkable
in tests; enabled (conf ``spark.shuffle.tpu.resourceDebug``, flipped
by TpuShuffleManager before it builds its node) every acquire returns
a live :class:`ResourceTicket` that

- records the acquisition site (a short caller-frame stack, the
  dbglock ``_call_site`` idiom),
- tracks the outstanding amount per resource
  (``resource_outstanding{resource=}`` gauge,
  ``resource_acquires_total`` counter),
- enforces one-shot release: releasing more than is outstanding,
  releasing a settled ticket again, or using a ticket after its
  ownership was transferred raises :class:`DoubleReleaseError` (and
  counts ``resource_double_release_total``),
- supports partial release down to zero and exactly-once ownership
  handoff (:meth:`ResourceTicket.transfer` — the annotated
  ``# owns: R -> target`` boundary, live-checked),

and :meth:`ResourceLedger.stop` renders the leak report: every ticket
still outstanding counts ``resource_leaked_total{resource=}``, logs
its acquisition-site stack at ERROR, and optionally raises
:class:`ResourceLeakError`.  ``tools/metrics_report.py`` renders the
resource series as a census table in snapshot diffs.

Tickets from a previous ledger epoch (the ledger was stopped/reset
since — e.g. a GC-tied tier pin whose weakref finalizer fires during
interpreter shutdown, after the manager already stopped) release as
silent no-ops: a late finalizer must never raise out of the GC.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Dict, List, Optional

from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.utils.statemachine import StateMachine

logger = logging.getLogger("sparkrdma_tpu.ledger")

_LIVE, _CLOSED, _TRANSFERRED = "live", "closed", "transferred"


class DoubleReleaseError(RuntimeError):
    """A resource was released twice (or past zero) on one path."""


class ResourceLeakError(RuntimeError):
    """Resources were still outstanding when the ledger stopped."""


def _acquire_site(limit: int = 4) -> str:
    """Short caller-frame stack ('a.py:12 < b.py:88'), skipping this
    module's own frames (the dbglock ``_call_site`` idiom, deepened —
    a leak report needs the chain, not just the innermost line)."""
    frames: List[str] = []
    depth = 1
    while len(frames) < limit:
        try:
            f = sys._getframe(depth)
        except ValueError:
            break
        depth += 1
        fname = f.f_code.co_filename
        if fname == __file__:
            continue
        frames.append(f"{fname.rsplit('/', 1)[-1]}:{f.f_lineno}")
    return " < ".join(frames) if frames else "<unknown>"


class ResourceTicket(StateMachine):
    """One outstanding acquisition of ``amount`` units of a resource."""

    __slots__ = ("_ledger", "resource", "outstanding", "site",
                 "_epoch", "_state")

    MACHINE = "ledger.ticket"
    STATES = (_LIVE, _CLOSED, _TRANSFERRED)
    INITIAL = _LIVE
    TERMINAL = (_CLOSED, _TRANSFERRED)
    TRANSITIONS = {
        _LIVE: (_CLOSED, _TRANSFERRED),
    }

    def __init__(self, ledger: "ResourceLedger", resource: str,
                 amount: int, site: str, epoch: int):
        self._ledger = ledger
        self.resource = resource
        self.outstanding = amount  # guarded-by: (ledger) _lock
        self.site = site
        self._epoch = epoch  # guarded-by: (ledger) _lock
        self._state = _LIVE  # state: ledger.ticket guarded-by: ResourceLedger._lock

    def release(self, amount: Optional[int] = None) -> None:
        """Return ``amount`` units (default: all still outstanding).
        Partial releases compose down to zero but leave the ticket
        OPEN — only the no-argument form settles (closes) it, exactly
        once, so a fully-progressed fetch's final ``release()`` is
        clean while a second one raises.  Over-release, releasing a
        settled/transferred ticket, or a negative amount raises
        :class:`DoubleReleaseError`.  ``release(0)`` is always a
        no-op (an idempotent settle path's empty remainder)."""
        self._ledger._release(self, amount)

    def transfer(self) -> "ResourceTicket":
        """Hand the outstanding entry to a new owner EXACTLY once:
        returns a fresh ticket for the same outstanding amount and
        dead-ends this one (any further release/transfer through it
        raises).  The runtime check behind the static
        ``# owns: R -> target`` annotation."""
        return self._ledger._transfer(self)

    def __repr__(self) -> str:
        return (f"ResourceTicket({self.resource}, "
                f"outstanding={self.outstanding}, site={self.site})")


class _NoopTicket:
    """The disabled ledger's shared ticket: every field static, every
    method a no-op — ``ledger_acquire`` is then one attribute check
    plus one return."""

    __slots__ = ()
    resource = ""
    outstanding = 0
    site = "<disabled>"

    def release(self, amount: Optional[int] = None) -> None:
        return None

    def transfer(self) -> "_NoopTicket":
        return self


NOOP_TICKET = _NoopTicket()


class ResourceLedger:
    """Process-global outstanding-resource tracker (see module doc)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.Lock()  # lock-order: 97
        self._tickets: set = set()  # guarded-by: _lock
        self._epoch = 0  # guarded-by: _lock
        self._double_releases = 0  # guarded-by: _lock
        self._owners = 0  # guarded-by: _lock

    def retain(self) -> None:
        """Register one owner (a manager enabling resourceDebug).  The
        ledger is process-global, so in a multi-manager process (the
        in-process cluster tests) the FIRST manager to stop must not
        flush it: the other managers' cached channels and pools are
        legitimately still holding resources.  Each owner's
        :meth:`stop` decrements; only the LAST one renders the leak
        report.  A ledger nobody retained (unit tests driving it
        directly) flushes on the first :meth:`stop` as before."""
        with self._lock:
            self._owners += 1

    # -- acquire -------------------------------------------------------------
    def acquire(self, resource: str, amount: int = 1):
        """Record an acquisition of ``amount`` units; returns the
        ticket whose ``release``/``transfer`` settle it.  Disabled,
        returns the shared no-op ticket (identity-testable)."""
        if not self.enabled:
            return NOOP_TICKET
        amount = int(amount)
        site = _acquire_site()
        with self._lock:
            t = ResourceTicket(self, resource, amount, site, self._epoch)
            self._tickets.add(t)
        counter("resource_acquires_total", resource=resource).inc()
        gauge("resource_outstanding", resource=resource).inc(amount)
        return t

    # -- ticket back-ends ----------------------------------------------------
    def _release(self, t: ResourceTicket, amount: Optional[int]) -> None:
        if amount is not None and int(amount) == 0:
            return
        err = None
        with self._lock:
            if t._epoch != self._epoch:  # noqa: CK03 - ledger lock guards tickets
                return  # stale epoch: late GC finalizer, silent no-op
            if t._state == _TRANSFERRED:
                err = (f"{t.resource}: release through a ticket whose "
                       f"ownership was already transferred "
                       f"(acquired at {t.site})")
            elif t._state == _CLOSED:
                err = (f"{t.resource}: double release — ticket already "
                       f"fully settled (acquired at {t.site})")
            else:
                n = t.outstanding if amount is None else int(amount)
                if n < 0:
                    err = (f"{t.resource}: negative release amount {n} "
                           f"(acquired at {t.site})")
                elif n > t.outstanding:
                    err = (f"{t.resource}: released {n} > outstanding "
                           f"{t.outstanding} (acquired at {t.site})")
                else:
                    t.outstanding -= n
                    # only the no-argument settle CLOSES the ticket:
                    # a partial release that drains to zero leaves it
                    # open, because the settle path still owes its
                    # exactly-once final release() (the reader's
                    # per-stripe progress + settle() pairing)
                    if amount is None:
                        t._transition(_CLOSED, frm=_LIVE)
                    if t.outstanding == 0:
                        self._tickets.discard(t)
            if err is not None:
                self._double_releases += 1
        if err is not None:
            counter("resource_double_release_total").inc()
            raise DoubleReleaseError(err)
        gauge("resource_outstanding", resource=t.resource).dec(n)

    def _transfer(self, t: ResourceTicket):
        err = None
        with self._lock:
            if t._epoch != self._epoch:  # noqa: CK03 - ledger lock guards tickets
                return NOOP_TICKET  # stale epoch: nothing left to own
            if t._state != _LIVE:
                err = (f"{t.resource}: ownership transfer of a "
                       f"{'transferred' if t._state == _TRANSFERRED else 'settled'} "
                       f"ticket (acquired at {t.site})")
                self._double_releases += 1
            else:
                t._transition(_TRANSFERRED, frm=_LIVE)
                self._tickets.discard(t)
                nt = ResourceTicket(self, t.resource, t.outstanding,
                                    t.site, self._epoch)
                self._tickets.add(nt)
        if err is not None:
            counter("resource_double_release_total").inc()
            raise DoubleReleaseError(err)
        return nt

    # -- introspection / teardown --------------------------------------------
    def outstanding(self) -> Dict[str, int]:
        """Per-resource outstanding totals over the live tickets."""
        out: Dict[str, int] = {}
        with self._lock:
            for t in self._tickets:
                out[t.resource] = out.get(t.resource, 0) + t.outstanding
        return out

    def double_releases(self) -> int:
        with self._lock:
            return self._double_releases

    def leak_report(self) -> List[str]:
        """One line per leaked ticket: resource, amount, site stack."""
        with self._lock:
            tickets = sorted(
                self._tickets, key=lambda t: (t.resource, t.site)
            )
            return [
                f"{t.resource}: {t.outstanding} outstanding, "
                f"acquired at {t.site}"
                for t in tickets
            ]

    def stop(self, raise_on_leak: bool = False) -> Dict[str, int]:
        """Close the ledger epoch and render the leak report: every
        still-outstanding ticket counts
        ``resource_leaked_total{resource=}`` and logs its
        acquisition-site stack at ERROR.  Tickets from this epoch
        become silent no-ops (late GC finalizers must not raise).
        With ``raise_on_leak`` (tests), leaks raise
        :class:`ResourceLeakError` carrying the report.

        With outstanding owners (see :meth:`retain`) a stop only
        drops one owner; the flush happens at the last one."""
        with self._lock:
            if self._owners > 0:
                self._owners -= 1
                if self._owners > 0:
                    return {}
        report = self.leak_report()
        with self._lock:
            leaked: Dict[str, int] = {}
            for t in self._tickets:
                leaked[t.resource] = (
                    leaked.get(t.resource, 0) + t.outstanding
                )
            self._tickets.clear()
            self._epoch += 1
        for resource, total in sorted(leaked.items()):
            counter("resource_leaked_total", resource=resource).inc(total)
            gauge("resource_outstanding", resource=resource).set(0)
            logger.error("resource leak: %s units of %s still "
                         "outstanding at ledger stop", total, resource)
        for line in report:
            logger.error("  leaked %s", line)
        if leaked:
            # a leak at ledger stop is a lifecycle bug — leave the
            # flight recorder's view of the run's tail next to the
            # leak report (obs/; lazy import keeps utils/ base-level)
            from sparkrdma_tpu.obs import RECORDER, fr_event

            if RECORDER.enabled:
                fr_event(
                    "faults", "ledger_leak",
                    resources=len(leaked), units=sum(leaked.values()),
                )
                RECORDER.auto_dump("ledger_leak")
        if leaked and raise_on_leak:
            raise ResourceLeakError(
                f"{sum(leaked.values())} unit(s) of "
                f"{len(leaked)} resource(s) leaked:\n  "
                + "\n  ".join(report)
            )
        return leaked

    def reset(self) -> None:
        """Drop every ticket and start a fresh epoch (tests)."""
        with self._lock:
            resources = {t.resource for t in self._tickets}
            self._tickets.clear()
            self._epoch += 1
            self._double_releases = 0
            self._owners = 0
        for resource in resources:
            gauge("resource_outstanding", resource=resource).set(0)


GLOBAL_RESOURCE_LEDGER = ResourceLedger(enabled=False)


def get_resource_ledger() -> ResourceLedger:
    return GLOBAL_RESOURCE_LEDGER


def ledger_acquire(resource: str, amount: int = 1):
    """Record an acquisition against the process-global ledger; the
    returned ticket's ``release``/``transfer`` settle it.  Call sites
    carry the matching ``# acquires:``/``# owns:`` annotations that
    tools/flowcheck.py checks statically."""
    return GLOBAL_RESOURCE_LEDGER.acquire(resource, amount)


__all__ = [
    "DoubleReleaseError",
    "NOOP_TICKET",
    "ResourceLedger",
    "ResourceLeakError",
    "ResourceTicket",
    "get_resource_ledger",
    "ledger_acquire",
]
