"""Cross-cutting utilities: ids, wire primitives, stats, clocks."""
