"""Runtime lifecycle state-machine validator + schedule shaker.

The runtime half of the state discipline (the static half is
tools/statecheck.py — the dbglock/ledger split applied to lifecycle
state).  Each lifecycle-bearing class declares its machine as class
attributes — ``MACHINE`` (registry name), ``STATES``, ``INITIAL``,
``TERMINAL``, and a ``TRANSITIONS`` table mapping each state to the
tuple of states reachable from it — and annotates the state field's
``__init__`` seeding line with ``# state: <machine>``.  Every state
change then flows through the declared ``_transition()`` helper
(:class:`StateMachine` provides the canonical one).

Off by default: ``_transition()`` is one module-global attribute read,
a false branch, and the plain assignment — identity-tested against raw
assignment.  ``spark.shuffle.tpu.stateDebug`` (the manager flips the
process-global :data:`GLOBAL_STATE_DEBUG` on BEFORE building its node,
the lockDebug/resourceDebug shape) validates every transition against
the table: an edge absent from ``TRANSITIONS`` raises
:class:`IllegalTransition` carrying both states and a 4-frame call
site, and every legal edge counts
``state_transitions_total{machine=,from=,to=}`` (terminal entries also
count ``state_terminal_total{machine=,state=}``) plus a flight-recorder
``state``-plane event when the recorder is armed.

On top of validation, ``spark.shuffle.tpu.schedShake=<seed>`` arms the
deterministic schedule shaker: at every validated transition a seeded
0–2ms yield/sleep widens the race window around exactly the points
where lifecycle races live.  Per-machine streams are seeded
``seed ^ crc32(machine)`` (the faults/injector.py shape), so a fixed
seed replays the same perturbation schedule run over run.

State values may be strings, ints, enums or booleans; validation maps
them to string tokens via :func:`state_token` (enum members by
lowercased name), so tables are written in readable lowercase tokens.
"""

from __future__ import annotations

import random
import sys
import threading
import time
import zlib
from typing import Dict, Optional, Tuple

from sparkrdma_tpu.metrics import counter


def _call_site(frames: int = 4, skip: int = 2) -> str:
    """Compact ``file:line`` chain of the transition call site (the
    dbglock idiom, deepened to 4 frames — lifecycle bugs usually sit
    one or two callers above the helper)."""
    out = []
    try:
        f = sys._getframe(skip)
    except (ValueError, AttributeError):
        return "<unknown>"
    while f is not None and len(out) < frames:
        out.append(
            f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
            f":{f.f_code.co_name}"
        )
        f = f.f_back
    return " <- ".join(out) if out else "<unknown>"


def state_token(value) -> str:
    """Canonical string token of one state value: strings pass
    through, enum members map to their lowercased name, booleans and
    ints stringify (tables for those machines use string states, so a
    raw int here is itself the drift being reported)."""
    if isinstance(value, str):
        return value
    name = getattr(value, "name", None)
    if isinstance(name, str):
        return name.lower()
    return str(value).lower()


class IllegalTransition(RuntimeError):
    """A state change absent from the machine's declared TRANSITIONS
    table (terminal states declare no outgoing edges, so a write after
    terminal raises here too)."""

    def __init__(self, machine: str, frm: str, to: str, site: str):
        super().__init__(
            f"illegal transition {machine}: {frm!r} -> {to!r} at {site}"
        )
        self.machine = machine
        self.frm = frm
        self.to = to
        self.site = site


class StateDebug:
    """Process-global validator/shaker state (the LockFactory shape):
    ``enabled`` flips validation on, ``shake_seed`` non-zero arms the
    schedule shaker on top of it."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.shake_seed = 0
        self._lock = threading.Lock()  # lock-order: 97
        self._rngs: Dict[str, random.Random] = {}  # guarded-by: _lock

    # -- validation (callers gate on .enabled) -------------------------------
    def check(self, obj, to, frm=None, *, name: str, field: str,
              transitions: Dict[str, Tuple[str, ...]],
              terminal: Tuple[str, ...] = ()) -> None:
        """Validate one proposed transition of ``obj``'s machine.
        Same-state re-assertions are legal no-ops (idempotent stop()
        patterns) and are neither counted nor shaken."""
        from sparkrdma_tpu.obs import RECORDER, fr_event

        cur = state_token(getattr(obj, field))
        dst = state_token(to)
        if frm is not None and state_token(frm) != cur:
            site = _call_site()
            counter("state_transitions_illegal_total", machine=name).inc()
            if RECORDER.enabled:
                fr_event("state", "illegal", machine=name, src=cur, dst=dst,
                         site=site)
            raise IllegalTransition(name, cur, dst,
                                    f"expected from={state_token(frm)!r} "
                                    f"saw {cur!r} at {site}")
        if dst == cur:
            return
        if dst not in transitions.get(cur, ()):
            site = _call_site()
            counter("state_transitions_illegal_total", machine=name).inc()
            if RECORDER.enabled:
                fr_event("state", "illegal", machine=name, src=cur, dst=dst,
                         site=site)
            raise IllegalTransition(name, cur, dst, site)
        counter("state_transitions_total", machine=name,
                **{"from": cur, "to": dst}).inc()
        if dst in terminal:
            counter("state_terminal_total", machine=name, state=dst).inc()
        if RECORDER.enabled:
            fr_event("state", "transition", machine=name, src=cur, dst=dst)
        if self.shake_seed:
            self._shake(name)

    # -- the schedule shaker -------------------------------------------------
    def _shake(self, machine: str) -> None:
        """One seeded 0–2ms yield/sleep AFTER a validated transition:
        three of four transitions bare-yield (releases the GIL, lets a
        racing thread in), the fourth sleeps up to 2ms — enough to
        reorder any two racing lifecycle paths without drowning the
        suite.  Deterministic per (seed, machine, call index)."""
        with self._lock:
            rng = self._rngs.get(machine)
            if rng is None:
                rng = self._rngs[machine] = random.Random(
                    self.shake_seed ^ (zlib.crc32(machine.encode()) &
                                       0x7FFFFFFF)
                )
            u = rng.random()
        if u < 0.75:
            time.sleep(0)  # bare yield
        else:
            time.sleep((u - 0.75) * 0.008)  # uniform 0–2ms

    def reset(self) -> None:
        """Drop the per-machine rng streams (tests re-seed between
        runs; a fresh arm must replay the same schedule)."""
        with self._lock:
            self._rngs.clear()


GLOBAL_STATE_DEBUG = StateDebug(enabled=False)


def get_state_debug() -> StateDebug:
    """The process-global validator the manager arms from conf."""
    return GLOBAL_STATE_DEBUG


class StateMachine:
    """Mixin providing the canonical ``_transition()`` helper.

    Subclasses declare the machine (``MACHINE``/``STATES``/``INITIAL``/
    ``TERMINAL``/``TRANSITIONS``, plus ``STATE_FIELD`` when the field
    is not ``_state``) and seed the field in ``__init__`` with a
    ``# state: <machine>`` annotation; every later write goes through
    ``_transition()``.  Empty ``__slots__`` so slotted value classes
    (descriptors, per-op records) can mix it in for free.

    A class hosting a SECOND machine (AsyncTcpChannel's recv machine
    next to the inherited lifecycle) declares the extra table under a
    prefix (``RX_STATES``...), binds it with ``# state: <machine>
    table: RX`` on the field, and routes writes through its own
    ``_transition_<suffix>`` helper calling :func:`check_named`.
    """

    __slots__ = ()

    MACHINE = ""
    STATES: Tuple[str, ...] = ()
    INITIAL: Optional[str] = None
    TERMINAL: Tuple[str, ...] = ()
    TRANSITIONS: Dict[str, Tuple[str, ...]] = {}
    STATE_FIELD = "_state"

    def _transition(self, to, frm=None) -> None:
        if GLOBAL_STATE_DEBUG.enabled:
            GLOBAL_STATE_DEBUG.check(
                self, to, frm, name=self.MACHINE, field=self.STATE_FIELD,
                transitions=self.TRANSITIONS, terminal=self.TERMINAL,
            )
        setattr(self, self.STATE_FIELD, to)


def check_named(obj, to, frm=None, *, name: str, field: str,
                transitions: Dict[str, Tuple[str, ...]],
                terminal: Tuple[str, ...] = ()) -> None:
    """Validation entry for hand-rolled ``_transition_<suffix>``
    helpers (second machines on one class).  Callers gate on
    ``GLOBAL_STATE_DEBUG.enabled`` and do their own assignment."""
    GLOBAL_STATE_DEBUG.check(obj, to, frm, name=name, field=field,
                             transitions=transitions, terminal=terminal)


def shake_confs_from_env(env=None) -> Dict[str, object]:
    """Conf overlay for the shaken harnesses (``make chaos-shake``):
    ``SCHED_SHAKE=<seed>`` in the environment layers
    ``schedShake`` (which implies ``stateDebug``) onto a soak's conf
    dict, so ONE env var turns any chaos soak or push drill into a
    shaken run without forking the test."""
    import os

    seed = (os.environ if env is None else env).get("SCHED_SHAKE", "")
    if not seed:
        return {}
    return {
        "spark.shuffle.tpu.stateDebug": True,
        "spark.shuffle.tpu.schedShake": seed,
    }
