"""Control-plane RPC messages and codecs."""

from sparkrdma_tpu.rpc.messages import (
    MSG_TYPES,
    AnnounceShuffleManagersMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    HelloMsg,
    PublishMapTaskOutputMsg,
    RpcMsg,
    WireField,
    WireFormatError,
    decode_msg,
    hex_context,
)

__all__ = [
    "RpcMsg",
    "HelloMsg",
    "AnnounceShuffleManagersMsg",
    "PublishMapTaskOutputMsg",
    "FetchMapStatusMsg",
    "FetchMapStatusResponseMsg",
    "WireField",
    "WireFormatError",
    "decode_msg",
    "hex_context",
    "MSG_TYPES",
]
