"""Control-plane RPC messages and codecs."""

from sparkrdma_tpu.rpc.messages import (
    MSG_TYPES,
    AnnounceShuffleManagersMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    HelloMsg,
    PublishMapTaskOutputMsg,
    RpcMsg,
    decode_msg,
)

__all__ = [
    "RpcMsg",
    "HelloMsg",
    "AnnounceShuffleManagersMsg",
    "PublishMapTaskOutputMsg",
    "FetchMapStatusMsg",
    "FetchMapStatusResponseMsg",
    "decode_msg",
    "MSG_TYPES",
]
