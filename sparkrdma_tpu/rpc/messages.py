"""Compact binary control-plane RPC messages, declared as wire schemas.

The reference frames every control message as ``4B length + 4B type``
followed by a type-specific payload, and *segments* large payloads into
recv-WR-sized registered buffers so they ride fixed-size RDMA SENDs
(reference: RdmaRpcMsg.scala:31-87, toRdmaByteBufferManagedBuffers).

Same scheme here: :meth:`RpcMsg.encode_segments` yields one or more
independently-decodable frames, each at most ``max_segment_size`` bytes.
Segmentable messages (announce / publish / fetch-status / response) split
their element lists across frames; each frame is a complete message of the
same type covering a sub-range, so the receiver just applies them in any
order (the publish path lands each sub-range via
``MapTaskOutput.put_range``).

Every message class declares its wire layout as a ``WIRE_SCHEMA`` — an
ordered tuple of :class:`WireField` specs (name, struct code,
variable-length section rule).  For fixed-layout messages the codec pair
(``_payload`` / ``_decode_payload``) is DERIVED from the schema, so
pack/unpack symmetry is true by construction; the one hand-written codec
(:class:`ExchangePlanMsg`, whose manifest nests rows) declares its
sections as ``custom`` fields and is checked for symmetry by the static
gate (tools/wirecheck.py, WC01).  Schema-driven decode validates every
count/length field against the received buffer BEFORE allocating or
looping (WC05's runtime contract), and all malformed input surfaces as
:class:`WireFormatError` — a ``ValueError`` carrying the message type
and a hexdump context, so one bad frame never costs more than itself.

The first five message types mirror the reference's set
(RdmaRpcMsg.scala:31-35); types 6-7 carry the failure-detection plane
the reference gets from RDMA CM DISCONNECTED events + Spark's
onBlockManagerRemoved listener (RdmaNode.java:176-189,
RdmaShuffleManager.scala:253-263), which have no transport-level analog
here:

====  =====================================  ===========================
type  class                                  direction
====  =====================================  ===========================
 1    HelloMsg                               executor → driver
 2    AnnounceShuffleManagersMsg             driver → all executors
 3    PublishMapTaskOutputMsg                executor → driver
 4    FetchMapStatusMsg                      executor → driver
 5    FetchMapStatusResponseMsg              driver → executor
 6    FetchMapStatusFailedMsg                driver → executor
 7    HeartbeatMsg                           driver ↔ executor
 8    FetchExchangePlanMsg                   executor → driver
 9    ExchangePlanMsg                        driver → executor
 10   PublishShuffleMetricsMsg               executor → driver
 11   PrefetchHintMsg                        reader → serving executor
 12   CleanShuffleMsg                        driver → all executors
 13   PushSubBlockMsg                        writer → merger executor
 14   FetchMergeStatusMsg                    reader → merger executor
 15   MergeStatusResponseMsg                 merger → reader
====  =====================================  ===========================

Types 8-9 carry the BULK-SYNCHRONOUS collective shuffle plan: after the
map phase, every participating host asks the driver for the globally
agreed (src host × dst host) stream-length matrix plus its own
destination manifest, so all hosts can launch ONE symmetric collective
exchange (SPMD needs identical shapes everywhere — SURVEY.md §7
"pull → collective inversion" across hosts).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple, Type

from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

_HEADER = struct.Struct("<ii")  # (frame_length, msg_type)
HEADER_SIZE = _HEADER.size

# The named structs every codec builds from — sizes always come from
# these (``.size``), never from integer literals (wirecheck WC04).
_I32 = struct.Struct("<i")
_Q64 = struct.Struct("<q")
_PAIR_II = struct.Struct("<ii")      # (map_id, reduce_id)
_PLAN_BLOCK = struct.Struct("<iiq")  # (map_id, reduce_id, length)
_PLAN_TAIL = struct.Struct("<iBi")   # (window, final, len(my_maps))

# Smallest possible serialized ShuffleManagerId (all-empty strings) —
# the count-validation floor for smid lists.
_SMID_MIN_SIZE = ShuffleManagerId(
    "", 0, BlockManagerId("", "", 0)
).serialized_length()


def hex_context(data, limit: int = 32) -> str:
    """First ``limit`` bytes as a hexdump fragment for error context."""
    view = bytes(memoryview(data)[:limit])
    dump = view.hex(" ")
    suffix = "…" if len(data) > limit else ""
    return f"{len(data)}B [{dump}{suffix}]"


class WireFormatError(ValueError):
    """A frame that violates the wire contract — truncated, oversized
    length field, unknown type.  Subclasses ``ValueError`` so existing
    decode-contract callers keep working; carries enough structure for
    the receive paths to count and scope the failure to ONE frame."""

    def __init__(self, message: str, *, msg_type=None,
                 unknown_type: bool = False):
        super().__init__(message)
        self.msg_type = msg_type
        self.unknown_type = unknown_type


def _require(view: memoryview, off: int, need: int) -> None:
    """Bounds guard: the next ``need`` bytes must exist at ``off``."""
    if need < 0 or off + need > len(view):
        raise WireFormatError(
            f"truncated payload: need {need}B at offset {off}, "
            f"have {len(view) - off}B"
        )


def _check_count(n: int, min_elem: int, view: memoryview,
                 off: int) -> int:
    """Validate a wire-supplied element count against the bytes that
    actually follow, BEFORE any allocation or loop sized by it — a
    lying count must cost nothing (no multi-GiB list from a 20-byte
    frame)."""
    if n < 0 or n * min_elem > len(view) - off:
        raise WireFormatError(
            f"bad element count {n} (×{min_elem}B min) with "
            f"{len(view) - off}B remaining"
        )
    return n


class WireField:
    """One field of a message's wire layout, in wire order.

    kind:
      ``scalar``      one struct code (e.g. ``<i``)
      ``bool``        struct-coded int carrying a bool
      ``smid``        a ShuffleManagerId (self-delimiting)
      ``list``        ``<i`` count + count elements (elem: ``smid``,
                      ``loc``, or a struct code like ``<ii``)
      ``bytes``       ``<i`` length + raw bytes
      ``str``         ``<i`` length + UTF-8, truncated to ``max_len``
      ``bytes_rest``  raw bytes to end of payload (last field only)
      ``custom``      hand-written codec section; ``code`` documents the
                      layout and wirecheck audits the methods (WC01/05)

    ``since`` > 1 marks an OPTIONAL-TAIL field added at that wire
    generation: scalar fields after every base field, defaulting to 0.
    The derived codec emits the tail only when the negotiated wire
    version allows it AND some tail value is non-zero — so a message
    with all-default tail values encodes byte-identically to its
    pre-tail generation (golden-frame pinned), and v1 peers never see
    bytes they would reject as trailing garbage.  Decode accepts the
    tail present or absent (absent → defaults), which is unambiguous
    because the last base field of any tailed schema is
    self-delimiting (count-prefixed).
    """

    __slots__ = ("name", "kind", "code", "st", "n_values", "max_len",
                 "since")

    def __init__(self, name: str, kind: str, code=None, max_len=None,
                 since: int = 1):
        self.name = name
        self.kind = kind
        self.code = code
        self.max_len = max_len
        self.since = since
        if since > 1 and kind != "scalar":
            raise ValueError(
                f"wire field {name!r}: optional-tail fields must be "
                f"scalars (got {kind!r})"
            )
        self.st = None
        self.n_values = 0
        if kind in ("scalar", "bool") or (
            kind == "list" and code not in ("smid", "loc")
        ):
            if not (isinstance(code, str) and code.startswith("<")):
                raise ValueError(
                    f"wire field {name!r}: struct code {code!r} must be "
                    f"explicit little-endian ('<'-prefixed)"
                )
            self.st = struct.Struct(code)
            self.n_values = len(self.st.unpack(bytes(self.st.size)))

    # -- readable constructors ----------------------------------------------
    @classmethod
    def i32(cls, name):
        return cls(name, "scalar", "<i")

    @classmethod
    def scalar(cls, name, code, since: int = 1):
        return cls(name, "scalar", code, since=since)

    @classmethod
    def bool_i32(cls, name):
        return cls(name, "bool", "<i")

    @classmethod
    def smid(cls, name):
        return cls(name, "smid")

    @classmethod
    def list(cls, name, elem):
        return cls(name, "list", elem)

    @classmethod
    def bytes_i32(cls, name):
        return cls(name, "bytes")

    @classmethod
    def str_i32(cls, name, max_len):
        return cls(name, "str", max_len=max_len)

    @classmethod
    def bytes_rest(cls, name):
        return cls(name, "bytes_rest")

    @classmethod
    def custom(cls, name, layout):
        return cls(name, "custom", layout)


F = WireField


def _schema_is_derived(schema) -> bool:
    return all(f.kind != "custom" for f in schema)


def _tail_fields(schema):
    """The optional-tail fields (``since`` > 1), validated to sit after
    every base field — a tail in the middle would be ambiguous."""
    tail = tuple(f for f in schema if f.since > 1)
    if tail and schema[-len(tail):] != tail:
        raise ValueError("optional-tail fields must be last in schema")
    return tail


def _emit_tail(msg: "RpcMsg", tail, wire_version) -> bool:
    """Whether to encode the optional tail: the negotiated generation
    must allow it (None → current) and some value must be non-zero."""
    if not tail:
        return False
    if wire_version is not None and any(
        wire_version < f.since for f in tail
    ):
        return False
    return any(getattr(msg, f.name) for f in tail)


def _encode_field(buf: bytearray, f: WireField, v) -> None:
    kind = f.kind
    if kind == "scalar":
        buf += f.st.pack(v)
    elif kind == "bool":
        buf += f.st.pack(int(bool(v)))
    elif kind == "smid":
        v.write(buf)
    elif kind == "list":
        buf += _I32.pack(len(v))
        if f.code in ("smid", "loc"):
            for e in v:
                e.write(buf)
        elif f.n_values == 1:
            for e in v:
                buf += f.st.pack(e)
        else:
            for e in v:
                buf += f.st.pack(*e)
    elif kind == "bytes":
        buf += _I32.pack(len(v))
        buf += v
    elif kind == "str":
        raw = v.encode("utf-8")[: f.max_len]
        buf += _I32.pack(len(raw))
        buf += raw
    elif kind == "bytes_rest":
        buf += v
    else:  # pragma: no cover - schema validated at class definition
        raise TypeError(f"cannot derive encoder for {f.kind!r} field")


def _field_size(f: WireField, v) -> int:
    kind = f.kind
    if kind in ("scalar", "bool"):
        return f.st.size
    if kind == "smid":
        return v.serialized_length()
    if kind == "list":
        if f.code == "smid":
            return _I32.size + sum(e.serialized_length() for e in v)
        if f.code == "loc":
            return _I32.size + LOCATION_ENTRY_SIZE * len(v)
        return _I32.size + f.st.size * len(v)
    if kind == "bytes":
        return _I32.size + len(v)
    if kind == "str":
        return _I32.size + len(v.encode("utf-8")[: f.max_len])
    if kind == "bytes_rest":
        return len(v)
    raise TypeError(f"cannot size {f.kind!r} field")  # pragma: no cover


def _decode_field(f: WireField, view: memoryview, off: int):
    """Decode one schema field at ``off``; returns (value, new offset).
    Every wire-supplied length/count is validated against the buffer
    before it sizes a read, loop, or allocation."""
    kind = f.kind
    if kind == "scalar":
        _require(view, off, f.st.size)
        vals = f.st.unpack_from(view, off)
        return (vals[0] if f.n_values == 1 else vals), off + f.st.size
    if kind == "bool":
        _require(view, off, f.st.size)
        (v,) = f.st.unpack_from(view, off)
        return bool(v), off + f.st.size
    if kind == "smid":
        return ShuffleManagerId.read(view, off)
    if kind == "list":
        _require(view, off, _I32.size)
        (n,) = _I32.unpack_from(view, off)
        off += _I32.size
        if f.code == "smid":
            _check_count(n, _SMID_MIN_SIZE, view, off)
            out = []
            for _ in range(n):
                e, off = ShuffleManagerId.read(view, off)
                out.append(e)
            return out, off
        if f.code == "loc":
            _check_count(n, LOCATION_ENTRY_SIZE, view, off)
            out = []
            for _ in range(n):
                out.append(BlockLocation.read(view, off))
                off += LOCATION_ENTRY_SIZE
            return out, off
        _check_count(n, f.st.size, view, off)
        out = []
        for _ in range(n):
            vals = f.st.unpack_from(view, off)
            out.append(vals[0] if f.n_values == 1 else vals)
            off += f.st.size
        return out, off
    if kind == "bytes":
        _require(view, off, _I32.size)
        (n,) = _I32.unpack_from(view, off)
        off += _I32.size
        _require(view, off, n)
        return bytes(view[off : off + n]), off + n
    if kind == "str":
        _require(view, off, _I32.size)
        (n,) = _I32.unpack_from(view, off)
        off += _I32.size
        _require(view, off, n)
        return bytes(view[off : off + n]).decode("utf-8", "replace"), off + n
    if kind == "bytes_rest":
        return bytes(view[off:]), len(view)
    raise TypeError(f"cannot decode {f.kind!r} field")  # pragma: no cover


class RpcMsg:
    """Base class: framing + segmentation + schema-derived codecs."""

    MSG_TYPE: int = 0
    WIRE_SCHEMA: Tuple[WireField, ...] = ()

    # -- schema-derived codec ------------------------------------------------
    def _payload(self, wire_version=None) -> bytes:
        """Serialize per the schema.  ``wire_version`` pins the target
        generation (None → current): optional-tail fields (``since`` >
        1) are emitted only when the generation allows them and some
        tail value is non-zero, keeping all-default encodings
        byte-identical across generations."""
        schema = type(self).WIRE_SCHEMA
        if not _schema_is_derived(schema):  # pragma: no cover
            raise NotImplementedError(
                f"{type(self).__name__} has custom wire sections and "
                f"must hand-write _payload"
            )
        tail = _emit_tail(self, _tail_fields(schema), wire_version)
        buf = bytearray()
        for f in schema:
            if f.since > 1 and not tail:
                continue
            _encode_field(buf, f, getattr(self, f.name))
        return bytes(buf)

    def _payload_size(self, wire_version=None) -> int:
        """Cheap payload-size estimate used to decide splitting without
        serializing — derived from the schema field by field."""
        schema = type(self).WIRE_SCHEMA
        tail = _emit_tail(self, _tail_fields(schema), wire_version)
        return sum(
            _field_size(f, getattr(self, f.name))
            for f in schema
            if f.since == 1 or tail
        )

    @classmethod
    def _decode_payload(cls, view: memoryview) -> "RpcMsg":
        schema = cls.WIRE_SCHEMA
        if not _schema_is_derived(schema):  # pragma: no cover
            raise NotImplementedError(
                f"{cls.__name__} has custom wire sections and must "
                f"hand-write _decode_payload"
            )
        kwargs = {}
        off = 0
        for f in schema:
            if f.since > 1 and off == len(view):
                # optional tail absent (an older-generation or
                # all-default frame): defaults apply
                kwargs[f.name] = 0
                continue
            kwargs[f.name], off = _decode_field(f, view, off)
        if off != len(view):
            raise WireFormatError(
                f"{cls.__name__}: {len(view) - off}B of trailing garbage"
            )
        return cls(**kwargs)

    def _split(self, max_payload: int) -> Sequence["RpcMsg"]:
        """Split into messages whose payloads each fit max_payload.
        Default: no splitting supported."""
        return (self,)

    # -- framing ------------------------------------------------------------
    def _frame(self, payload: bytes) -> bytes:
        return _HEADER.pack(HEADER_SIZE + len(payload), self.MSG_TYPE) + payload

    def encode(self, wire_version=None) -> bytes:
        return self._frame(self._payload(wire_version))

    def encode_segments(self, max_segment_size: int,
                        wire_version=None) -> List[bytes]:
        """Encode into frames each ≤ max_segment_size bytes.
        ``wire_version`` pins the peer's negotiated generation (None →
        current) so optional-tail fields stay off frames bound for
        older peers."""
        max_payload = max_segment_size - HEADER_SIZE
        if max_payload <= 0:
            raise ValueError(f"segment size too small: {max_segment_size}")
        size = self._payload_size(wire_version)
        if size <= max_payload:
            return [self._frame(self._payload(wire_version))]
        parts = self._split(max_payload)
        if len(parts) == 1:
            raise ValueError(
                f"{type(self).__name__} payload {size}B exceeds segment "
                f"size {max_segment_size}B and cannot be split further"
            )
        out: List[bytes] = []
        for p in parts:
            psize = p._payload_size(wire_version)
            if psize > max_payload:
                # an atomic element (e.g. one id with a very long hostname,
                # or a fixed header) alone exceeds the segment size
                raise ValueError(
                    f"{type(self).__name__} segment payload {psize}B still "
                    f"exceeds segment size {max_segment_size}B"
                )
            out.append(p._frame(p._payload(wire_version)))
        return out


def decode_msg(data: bytes) -> RpcMsg:
    """Decode one frame (dispatch by type header,
    reference: RdmaRpcMsg.scala:67-87).

    Every malformed input — truncated header, length mismatch, unknown
    type, bad field — raises :class:`WireFormatError` (a ``ValueError``),
    never anything the receive paths would mistake for an engine fault:
    the blast radius of a bad frame is exactly that frame."""
    if len(data) < HEADER_SIZE:
        raise WireFormatError(
            f"frame too short: {hex_context(data)}"
        )
    length, msg_type = _HEADER.unpack_from(data, 0)
    if length != len(data):
        raise WireFormatError(
            f"frame length {length} != buffer length {len(data)}",
            msg_type=msg_type,
        )
    cls = MSG_TYPES.get(msg_type)
    if cls is None:
        raise WireFormatError(
            f"unknown RPC message type {msg_type}: {hex_context(data)}",
            msg_type=msg_type, unknown_type=True,
        )
    try:
        return cls._decode_payload(memoryview(data)[HEADER_SIZE:])
    except WireFormatError as e:
        if e.msg_type is None:
            e.msg_type = msg_type
        raise
    except (struct.error, ValueError) as e:
        # malformed frames must surface as ValueError, the decode contract
        raise WireFormatError(
            f"malformed {cls.__name__} frame: {e}", msg_type=msg_type
        ) from e


# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HelloMsg(RpcMsg):
    """Executor advertises itself to the driver on startup
    (reference: RdmaShuffleManagerHelloRpcMsg, RdmaRpcMsg.scala:90-119)."""

    shuffle_manager_id: ShuffleManagerId
    channel_port: int  # port the driver should connect back to

    MSG_TYPE = 1
    WIRE_SCHEMA = (
        F.smid("shuffle_manager_id"),
        F.i32("channel_port"),
    )


@dataclass(frozen=True)
class AnnounceShuffleManagersMsg(RpcMsg):
    """Driver broadcasts the current membership so executors pre-connect
    the full mesh (reference: RdmaAnnounceRdmaShuffleManagersRpcMsg,
    RdmaRpcMsg.scala:121-180)."""

    shuffle_manager_ids: Tuple[ShuffleManagerId, ...]

    MSG_TYPE = 2
    WIRE_SCHEMA = (
        F.list("shuffle_manager_ids", "smid"),
    )

    def __init__(self, shuffle_manager_ids: Sequence[ShuffleManagerId]):
        object.__setattr__(self, "shuffle_manager_ids", tuple(shuffle_manager_ids))

    def _split(self, max_payload: int) -> Sequence["AnnounceShuffleManagersMsg"]:
        parts: List[AnnounceShuffleManagersMsg] = []
        cur: List[ShuffleManagerId] = []
        cur_len = _I32.size
        for smid in self.shuffle_manager_ids:
            n = smid.serialized_length()
            if cur and cur_len + n > max_payload:
                parts.append(AnnounceShuffleManagersMsg(cur))
                cur, cur_len = [], _I32.size
            cur.append(smid)
            cur_len += n
        if cur:
            parts.append(AnnounceShuffleManagersMsg(cur))
        return parts


@dataclass(frozen=True)
class PublishMapTaskOutputMsg(RpcMsg):
    """Executor publishes a map task's location table to the driver,
    possibly as several sub-range segments
    (reference: RdmaPublishMapTaskOutputRpcMsg, RdmaRpcMsg.scala:182-276).

    ``entries`` holds the raw 16-byte location entries for partitions
    [first_reduce_id, last_reduce_id] inclusive.  ``epoch`` tags which
    publish generation of the map task's table this segment belongs to
    (delta-sync: a republish after a location change ships only the
    changed runs at a higher epoch, and the driver's per-entry epoch
    guard keeps out-of-order segment application from resurrecting
    stale locations — MapTaskOutput.put_range).
    """

    shuffle_manager_id: ShuffleManagerId
    shuffle_id: int
    map_id: int
    total_num_partitions: int
    first_reduce_id: int
    last_reduce_id: int
    entries: bytes
    epoch: int = 0

    MSG_TYPE = 3
    WIRE_SCHEMA = (
        F.smid("shuffle_manager_id"),
        F.i32("shuffle_id"),
        F.i32("map_id"),
        F.i32("total_num_partitions"),
        F.i32("first_reduce_id"),
        F.i32("last_reduce_id"),
        F.i32("epoch"),
        F.bytes_rest("entries"),
    )

    def __post_init__(self):
        expect = (self.last_reduce_id - self.first_reduce_id + 1) * LOCATION_ENTRY_SIZE
        if len(self.entries) != expect:
            raise ValueError(
                f"entries {len(self.entries)}B != expected {expect}B for range "
                f"[{self.first_reduce_id},{self.last_reduce_id}]"
            )

    def _split(self, max_payload: int) -> Sequence["PublishMapTaskOutputMsg"]:
        fixed = self._payload_size() - len(self.entries)
        per_seg = max(1, (max_payload - fixed) // LOCATION_ENTRY_SIZE)
        parts: List[PublishMapTaskOutputMsg] = []
        first = self.first_reduce_id
        while first <= self.last_reduce_id:
            last = min(first + per_seg - 1, self.last_reduce_id)
            lo = (first - self.first_reduce_id) * LOCATION_ENTRY_SIZE
            hi = (last - self.first_reduce_id + 1) * LOCATION_ENTRY_SIZE
            parts.append(
                PublishMapTaskOutputMsg(
                    self.shuffle_manager_id,
                    self.shuffle_id,
                    self.map_id,
                    self.total_num_partitions,
                    first,
                    last,
                    self.entries[lo:hi],
                    self.epoch,
                )
            )
            first = last + 1
        return parts


@dataclass(frozen=True)
class FetchMapStatusMsg(RpcMsg):
    """Executor asks the driver for the locations of a set of
    (map_id, reduce_id) blocks served by one remote host; the response is
    routed through ``callback_id``
    (reference: RdmaFetchMapStatusRpcMsg, RdmaRpcMsg.scala:279-367).

    Wide requests split across segments: each segment is an independent
    request carrying ``total`` (the whole logical request's block count)
    and ``index`` (offset of this segment's first block), and the driver's
    per-segment responses reuse those so the requester reassembles one
    answer of ``total`` locations.
    """

    requester: ShuffleManagerId
    host: ShuffleManagerId  # whose map outputs we want
    shuffle_id: int
    callback_id: int
    block_ids: Tuple[Tuple[int, int], ...]  # (map_id, reduce_id) pairs
    total: int = -1  # blocks in the whole logical request; -1 → len(block_ids)
    index: int = 0   # offset of block_ids[0] within the logical request
    trace_id: int = 0  # v2 optional tail: distributed trace correlation
    span_id: int = 0

    MSG_TYPE = 4
    WIRE_SCHEMA = (
        F.smid("requester"),
        F.smid("host"),
        F.i32("shuffle_id"),
        F.i32("callback_id"),
        F.i32("total"),
        F.i32("index"),
        F.list("block_ids", "<ii"),
        F.scalar("trace_id", "<Q", since=2),
        F.scalar("span_id", "<Q", since=2),
    )

    def __init__(self, requester, host, shuffle_id, callback_id, block_ids,
                 total=-1, index=0, trace_id=0, span_id=0):
        object.__setattr__(self, "requester", requester)
        object.__setattr__(self, "host", host)
        object.__setattr__(self, "shuffle_id", shuffle_id)
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "block_ids", tuple(tuple(b) for b in block_ids))
        object.__setattr__(self, "total", len(self.block_ids) if total < 0 else total)
        object.__setattr__(self, "index", index)
        object.__setattr__(self, "trace_id", trace_id)
        object.__setattr__(self, "span_id", span_id)

    def _split(self, max_payload: int) -> Sequence["FetchMapStatusMsg"]:
        fixed = self._payload_size() - _PAIR_II.size * len(self.block_ids)
        per_seg = max(1, (max_payload - fixed) // _PAIR_II.size)
        parts: List[FetchMapStatusMsg] = []
        for start in range(0, len(self.block_ids), per_seg):
            parts.append(
                FetchMapStatusMsg(
                    self.requester, self.host, self.shuffle_id, self.callback_id,
                    self.block_ids[start : start + per_seg],
                    total=self.total, index=self.index + start,
                    trace_id=self.trace_id, span_id=self.span_id,
                )
            )
        return parts


@dataclass(frozen=True)
class FetchMapStatusResponseMsg(RpcMsg):
    """Driver's answer: one BlockLocation per requested block, in request
    order, split across segments when large.  ``index`` is the offset of
    this segment's first location within the full answer, ``total`` the
    full answer's length (reference: RdmaFetchMapStatusResponseRpcMsg,
    RdmaRpcMsg.scala:369-446)."""

    callback_id: int
    total: int
    index: int
    locations: Tuple[BlockLocation, ...]

    MSG_TYPE = 5
    WIRE_SCHEMA = (
        F.i32("callback_id"),
        F.i32("total"),
        F.i32("index"),
        F.list("locations", "loc"),
    )

    def __init__(self, callback_id, total, index, locations):
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "total", total)
        object.__setattr__(self, "index", index)
        object.__setattr__(self, "locations", tuple(locations))

    def _split(self, max_payload: int) -> Sequence["FetchMapStatusResponseMsg"]:
        fixed = self._payload_size() - LOCATION_ENTRY_SIZE * len(self.locations)
        per_seg = max(1, (max_payload - fixed) // LOCATION_ENTRY_SIZE)
        parts: List[FetchMapStatusResponseMsg] = []
        for start in range(0, len(self.locations), per_seg):
            parts.append(
                FetchMapStatusResponseMsg(
                    self.callback_id,
                    self.total,
                    self.index + start,
                    self.locations[start : start + per_seg],
                )
            )
        return parts


@dataclass(frozen=True)
class FetchMapStatusFailedMsg(RpcMsg):
    """Driver tells a requester its fetch-status CANNOT be answered —
    unregistered shuffle, or the publishing executor was lost before its
    table filled.  The requester converts this to a metadata fetch
    failure immediately instead of riding out the full location timeout
    (the fast stage-retry path; reference reducers discover the same
    condition only via FetchFailedException after timeouts)."""

    callback_id: int
    reason: str

    MSG_TYPE = 6
    WIRE_SCHEMA = (
        F.i32("callback_id"),
        F.str_i32("reason", max_len=1024),
    )


@dataclass(frozen=True)
class HeartbeatMsg(RpcMsg):
    """Liveness probe on the hello/announce plane: the driver pings
    each executor; the executor echoes with ``is_ack=True``.  A missed
    ack window (or an outright send failure) drives automatic
    ``remove_executor`` — the role RDMA CM DISCONNECTED events play in
    the reference (RdmaNode.java:176-189)."""

    shuffle_manager_id: ShuffleManagerId
    seq: int
    is_ack: bool

    MSG_TYPE = 7
    WIRE_SCHEMA = (
        F.smid("shuffle_manager_id"),
        F.i32("seq"),
        F.bool_i32("is_ack"),
    )


@dataclass(frozen=True)
class FetchExchangePlanMsg(RpcMsg):
    """Host asks the driver for the bulk-exchange plan of one shuffle.

    ``window == -1`` requests the legacy single plan (answered once
    EVERY registered map has published — the full barrier).  ``window
    >= 0`` requests incremental plan number ``window``: the driver
    answers once ``bulkWindowMaps`` new maps (or the remainder) have
    published AND filled, so reducers exchange early windows while
    stragglers still write (the collective analog of the reference's
    windowed fetch overlap,
    RdmaShuffleFetcherIterator.scala:241-251)."""

    requester: ShuffleManagerId
    shuffle_id: int
    callback_id: int
    window: int = -1

    MSG_TYPE = 8
    WIRE_SCHEMA = (
        F.smid("requester"),
        F.i32("shuffle_id"),
        F.i32("callback_id"),
        F.i32("window"),
    )


@dataclass(frozen=True)
class PublishShuffleMetricsMsg(RpcMsg):
    """Executor publishes one shuffle's telemetry snapshot (a flat
    ``{metric name: number}`` dict, JSON-encoded) to the driver at
    unregister time — riding the same control plane the map-output
    location publishes use, so the driver can aggregate per-shuffle
    write/read/fetch totals across hosts (metrics/ tentpole; no
    reference analog — RdmaShuffleReaderStats stays executor-local)."""

    shuffle_manager_id: ShuffleManagerId
    shuffle_id: int
    payload: bytes  # JSON {metric: number}

    MSG_TYPE = 10
    WIRE_SCHEMA = (
        F.smid("shuffle_manager_id"),
        F.i32("shuffle_id"),
        F.bytes_i32("payload"),
    )


@dataclass(frozen=True)
class PrefetchHintMsg(RpcMsg):
    """Reader → serving peer: the next block locations this reader's
    fetch plan will request, so the responder's tiered block store
    (memory/tier.py) can promote them from disk through its serve-pool
    credits BEFORE the read RPCs arrive — the reader-side half of the
    RdmaMappedFile ODP-prefetch sweep (RdmaMappedFile.java:158-168),
    inverted: the requester knows the plan, the responder owns the
    residency.  Purely advisory: a dropped/failed hint costs nothing
    but the hidden disk latency, and unknown mkeys are ignored."""

    shuffle_id: int
    locations: Tuple[BlockLocation, ...]
    trace_id: int = 0  # v2 optional tail: distributed trace correlation
    span_id: int = 0

    MSG_TYPE = 11
    WIRE_SCHEMA = (
        F.i32("shuffle_id"),
        F.list("locations", "loc"),
        F.scalar("trace_id", "<Q", since=2),
        F.scalar("span_id", "<Q", since=2),
    )

    def __init__(self, shuffle_id: int, locations, trace_id=0, span_id=0):
        object.__setattr__(self, "shuffle_id", shuffle_id)
        object.__setattr__(self, "locations", tuple(locations))
        object.__setattr__(self, "trace_id", trace_id)
        object.__setattr__(self, "span_id", span_id)

    def _split(self, max_payload: int) -> Sequence["PrefetchHintMsg"]:
        fixed = self._payload_size() - LOCATION_ENTRY_SIZE * len(self.locations)
        per_seg = max(1, (max_payload - fixed) // LOCATION_ENTRY_SIZE)
        return [
            PrefetchHintMsg(
                self.shuffle_id, self.locations[i : i + per_seg],
                trace_id=self.trace_id, span_id=self.span_id,
            )
            for i in range(0, len(self.locations), per_seg)
        ]


@dataclass(frozen=True)
class CleanShuffleMsg(RpcMsg):
    """Driver tells every executor one shuffle is unregistered, so each
    releases its OWN side of that shuffle — registered arena segments,
    block-store mkeys, QoS-admitted quota bytes.  Without this the
    executor's resources for a finished shuffle survive until manager
    stop (the resource ledger flagged exactly that: committed map
    segments outstanding long after the driver forgot the shuffle).
    The reference gets this for free — Spark's ContextCleaner invokes
    unregisterShuffle on every executor — but this control plane has
    no external cleaner, so the driver's unregister broadcasts."""

    shuffle_id: int

    MSG_TYPE = 12
    WIRE_SCHEMA = (
        F.i32("shuffle_id"),
    )


@dataclass(frozen=True)
class ExchangePlanMsg(RpcMsg):
    """The driver's bulk-exchange plan: the canonical host order, the
    full (src × dst) stream-length matrix every host must agree on, and
    the requester's destination manifest — for each source host, the
    (map_id, reduce_id, length) blocks concatenated into that source's
    stream toward the requester, in order.

    The manifest nests per-host rows, so this is the one HAND-WRITTEN
    codec: the ``custom`` schema fields document the layout, and
    tools/wirecheck.py audits encode/decode symmetry (WC01) and bounds
    discipline (WC05) instead of deriving them."""

    callback_id: int
    hosts: Tuple[ShuffleManagerId, ...]          # canonical order
    lengths: Tuple[int, ...]                     # row-major [E * E]
    manifest: Tuple[Tuple[Tuple[int, int, int], ...], ...]  # [E][blocks]
    window: int = -1            # -1: full-barrier plan; >=0: window no.
    final: bool = True          # True: no window follows this one
    my_maps: Tuple[int, ...] = ()  # requester's map_ids in this window

    MSG_TYPE = 9
    WIRE_SCHEMA = (
        F.custom("callback_id", "<i"),
        F.custom("hosts", "<i count + count × smid"),
        F.custom("lengths", "<{E*E}q row-major matrix, no count prefix"),
        F.custom("manifest", "per host row: <i count + count × <iiq"),
        F.custom("window", "<i (first of <iBi tail)"),
        F.custom("final", "<B (second of <iBi tail)"),
        F.custom("my_maps", "<i count (third of tail) + count × <i"),
    )

    def __init__(self, callback_id, hosts, lengths, manifest,
                 window: int = -1, final: bool = True, my_maps=()):
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "hosts", tuple(hosts))
        object.__setattr__(self, "lengths", tuple(int(x) for x in lengths))
        object.__setattr__(
            self, "manifest",
            tuple(tuple(tuple(b) for b in row) for row in manifest),
        )
        object.__setattr__(self, "window", int(window))
        object.__setattr__(self, "final", bool(final))
        object.__setattr__(
            self, "my_maps", tuple(int(m) for m in my_maps)
        )
        e = len(self.hosts)
        if len(self.lengths) != e * e or len(self.manifest) != e:
            raise ValueError(
                f"plan shape mismatch: {e} hosts, {len(self.lengths)} "
                f"lengths, {len(self.manifest)} manifest rows"
            )

    def _payload(self, wire_version=None) -> bytes:
        buf = bytearray(_PAIR_II.pack(self.callback_id, len(self.hosts)))
        for h in self.hosts:
            h.write(buf)
        for x in self.lengths:
            buf += _Q64.pack(x)
        for row in self.manifest:
            buf += _I32.pack(len(row))
            for map_id, reduce_id, length in row:
                buf += _PLAN_BLOCK.pack(map_id, reduce_id, length)
        buf += _PLAN_TAIL.pack(
            self.window, int(self.final), len(self.my_maps)
        )
        for m in self.my_maps:
            buf += _I32.pack(m)
        return bytes(buf)

    def _payload_size(self, wire_version=None) -> int:
        return (
            _PAIR_II.size
            + sum(h.serialized_length() for h in self.hosts)
            + _Q64.size * len(self.lengths)
            + sum(
                _I32.size + _PLAN_BLOCK.size * len(row)
                for row in self.manifest
            )
            + _PLAN_TAIL.size + _I32.size * len(self.my_maps)
        )

    @staticmethod
    def _decode_payload(view: memoryview) -> "ExchangePlanMsg":
        _require(view, 0, _PAIR_II.size)
        callback_id, e = _PAIR_II.unpack_from(view, 0)
        off = _PAIR_II.size
        _check_count(e, _SMID_MIN_SIZE, view, off)
        hosts = []
        for _ in range(e):
            h, off = ShuffleManagerId.read(view, off)
            hosts.append(h)
        _require(view, off, _Q64.size * e * e)
        lengths = struct.unpack_from(f"<{e * e}q", view, off) if e else ()
        off += _Q64.size * e * e
        manifest = []
        for _ in range(e):
            _require(view, off, _I32.size)
            (cnt,) = _I32.unpack_from(view, off)
            off += _I32.size
            _check_count(cnt, _PLAN_BLOCK.size, view, off)
            row = []
            for _ in range(cnt):
                m, r, n = _PLAN_BLOCK.unpack_from(view, off)
                off += _PLAN_BLOCK.size
                row.append((m, r, n))
            manifest.append(tuple(row))
        _require(view, off, _PLAN_TAIL.size)
        window, final, n_my = _PLAN_TAIL.unpack_from(view, off)
        off += _PLAN_TAIL.size
        _check_count(n_my, _I32.size, view, off)
        my_maps = struct.unpack_from(f"<{n_my}i", view, off) if n_my else ()
        off += _I32.size * n_my
        if off != len(view):
            raise WireFormatError(
                f"ExchangePlanMsg: {len(view) - off}B of trailing garbage"
            )
        return ExchangePlanMsg(
            callback_id, hosts, lengths, manifest,
            window=window, final=bool(final), my_maps=my_maps,
        )


#: Wire generation that introduced the push/merge messages (types
#: 13-15).  Senders gate on the channel's NEGOTIATED version — an older
#: peer never merges, every one of its blocks rides the pull path
#: (``wire_version`` 0 = unversioned/in-process = current build).
PUSH_MIN_WIRE_VERSION = 3


@dataclass(frozen=True)
class PushSubBlockMsg(RpcMsg):
    """Writer pushes one span of a map task's partition payload to that
    reduce partition's deterministic merger executor (the magnet idiom;
    lineage: the reference's RdmaShuffleWriter commits then serves pull
    reads — push inverts the data motion at the same commit point).

    The merger assembles purely by ``(offset, data)`` against
    ``total_len``: a message carries bytes ``[offset, offset+len(data))``
    of the partition's full payload, so re-segmentation (``_split``),
    duplicated frames from a retried map task, and out-of-order arrival
    all converge to the same assembled bytes.  NEW wire type (v3): sends
    are gated on the peer's negotiated wire version, and an old peer
    that somehow receives one drops it as an unknown-type frame —
    best-effort push, never a protocol error."""

    sender: ShuffleManagerId
    shuffle_id: int
    map_id: int
    reduce_id: int
    total_len: int
    offset: int
    data: bytes

    MSG_TYPE = 13
    WIRE_SCHEMA = (
        F.smid("sender"),
        F.i32("shuffle_id"),
        F.i32("map_id"),
        F.i32("reduce_id"),
        F.i32("total_len"),
        F.i32("offset"),
        F.bytes_rest("data"),
    )

    def __post_init__(self):
        if not (0 <= self.offset
                and self.offset + len(self.data) <= self.total_len):
            raise ValueError(
                f"push span [{self.offset},{self.offset + len(self.data)})"
                f" outside total_len {self.total_len}"
            )

    def _split(self, max_payload: int) -> Sequence["PushSubBlockMsg"]:
        fixed = self._payload_size() - len(self.data)
        per_seg = max(1, max_payload - fixed)
        parts: List[PushSubBlockMsg] = []
        for start in range(0, len(self.data), per_seg):
            parts.append(
                PushSubBlockMsg(
                    self.sender, self.shuffle_id, self.map_id,
                    self.reduce_id, self.total_len,
                    self.offset + start,
                    self.data[start : start + per_seg],
                )
            )
        return parts


@dataclass(frozen=True)
class FetchMergeStatusMsg(RpcMsg):
    """Reader asks a merger executor which of ``reduce_ids`` it holds
    merged spans for; the answer (one :class:`MergeStatusResponseMsg`
    per reduce id, or a :class:`FetchMapStatusFailedMsg`) is routed
    through ``callback_id``.  Querying seals the merged spans: the
    merger commits what it has and pushes arriving late sub-blocks to
    the pull path from then on."""

    requester: ShuffleManagerId
    shuffle_id: int
    callback_id: int
    reduce_ids: Tuple[int, ...]

    MSG_TYPE = 14
    WIRE_SCHEMA = (
        F.smid("requester"),
        F.i32("shuffle_id"),
        F.i32("callback_id"),
        F.list("reduce_ids", "<i"),
    )

    def __init__(self, requester, shuffle_id, callback_id, reduce_ids):
        object.__setattr__(self, "requester", requester)
        object.__setattr__(self, "shuffle_id", shuffle_id)
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "reduce_ids",
                           tuple(int(r) for r in reduce_ids))

    def _split(self, max_payload: int) -> Sequence["FetchMergeStatusMsg"]:
        fixed = self._payload_size() - _I32.size * len(self.reduce_ids)
        per_seg = max(1, (max_payload - fixed) // _I32.size)
        return [
            FetchMergeStatusMsg(
                self.requester, self.shuffle_id, self.callback_id,
                self.reduce_ids[i : i + per_seg],
            )
            for i in range(0, len(self.reduce_ids), per_seg)
        ]


@dataclass(frozen=True)
class MergeStatusResponseMsg(RpcMsg):
    """Merger's answer for ONE reduce partition, following the
    fetch-status response convention: ``total`` is the queried reduce-id
    count, ``index`` this answer's position, so the requester knows when
    the set is complete regardless of arrival order.  ``mkey == 0``
    means no merged data for this reduce id (everything pulls).
    ``provenance`` lists the merged span's constituent map outputs as
    ``(map_id, rel_off, rel_len)`` rows — relative to the span start —
    so the reader both knows which (map, reduce) blocks the span covers
    (the rest fall back to pull) and can slice the fetched span back
    into per-map blocks for the bit-exact k-way merge.

    Wide provenance splits across segments: every fragment repeats the
    fixed header and carries ``rows_total`` (the whole span's row
    count), so the requester accumulates rows until a reduce id's set
    is full — same sub-range scheme the publish path uses."""

    callback_id: int
    total: int
    index: int
    reduce_id: int
    mkey: int
    length: int
    provenance: Tuple[Tuple[int, int, int], ...]  # (map_id, rel_off, rel_len)
    rows_total: int = -1  # rows in the whole answer; -1 → len(provenance)

    MSG_TYPE = 15
    WIRE_SCHEMA = (
        F.i32("callback_id"),
        F.i32("total"),
        F.i32("index"),
        F.i32("reduce_id"),
        F.i32("mkey"),
        F.scalar("length", "<q"),
        F.i32("rows_total"),
        F.list("provenance", "<iqq"),
    )

    def __init__(self, callback_id, total, index, reduce_id, mkey,
                 length, provenance, rows_total=-1):
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "total", total)
        object.__setattr__(self, "index", index)
        object.__setattr__(self, "reduce_id", reduce_id)
        object.__setattr__(self, "mkey", mkey)
        object.__setattr__(self, "length", length)
        object.__setattr__(
            self, "provenance",
            tuple(tuple(int(x) for x in row) for row in provenance),
        )
        object.__setattr__(
            self, "rows_total",
            len(self.provenance) if rows_total < 0 else rows_total,
        )

    def _split(self, max_payload: int) -> Sequence["MergeStatusResponseMsg"]:
        st_size = struct.calcsize("<iqq")
        fixed = self._payload_size() - st_size * len(self.provenance)
        per_seg = max(1, (max_payload - fixed) // st_size)
        return [
            MergeStatusResponseMsg(
                self.callback_id, self.total, self.index, self.reduce_id,
                self.mkey, self.length,
                self.provenance[i : i + per_seg],
                rows_total=self.rows_total,
            )
            for i in range(0, len(self.provenance), per_seg)
        ]


MSG_TYPES: Dict[int, Type[RpcMsg]] = {
    cls.MSG_TYPE: cls
    for cls in (
        HelloMsg,
        AnnounceShuffleManagersMsg,
        PublishMapTaskOutputMsg,
        FetchMapStatusMsg,
        FetchMapStatusResponseMsg,
        FetchMapStatusFailedMsg,
        HeartbeatMsg,
        FetchExchangePlanMsg,
        ExchangePlanMsg,
        PublishShuffleMetricsMsg,
        PrefetchHintMsg,
        CleanShuffleMsg,
        PushSubBlockMsg,
        FetchMergeStatusMsg,
        MergeStatusResponseMsg,
    )
}
