"""Compact binary control-plane RPC messages.

The reference frames every control message as ``4B length + 4B type``
followed by a type-specific payload, and *segments* large payloads into
recv-WR-sized registered buffers so they ride fixed-size RDMA SENDs
(reference: RdmaRpcMsg.scala:31-87, toRdmaByteBufferManagedBuffers).

Same scheme here: :meth:`RpcMsg.encode_segments` yields one or more
independently-decodable frames, each at most ``max_segment_size`` bytes.
Segmentable messages (announce / publish / fetch-status / response) split
their element lists across frames; each frame is a complete message of the
same type covering a sub-range, so the receiver just applies them in any
order (the publish path lands each sub-range via
``MapTaskOutput.put_range``).

The first five message types mirror the reference's set
(RdmaRpcMsg.scala:31-35); types 6-7 carry the failure-detection plane
the reference gets from RDMA CM DISCONNECTED events + Spark's
onBlockManagerRemoved listener (RdmaNode.java:176-189,
RdmaShuffleManager.scala:253-263), which have no transport-level analog
here:

====  =====================================  ===========================
type  class                                  direction
====  =====================================  ===========================
 1    HelloMsg                               executor → driver
 2    AnnounceShuffleManagersMsg             driver → all executors
 3    PublishMapTaskOutputMsg                executor → driver
 4    FetchMapStatusMsg                      executor → driver
 5    FetchMapStatusResponseMsg              driver → executor
 6    FetchMapStatusFailedMsg                driver → executor
 7    HeartbeatMsg                           driver ↔ executor
 8    FetchExchangePlanMsg                   executor → driver
 9    ExchangePlanMsg                        driver → executor
 10   PublishShuffleMetricsMsg               executor → driver
 11   PrefetchHintMsg                        reader → serving executor
 12   CleanShuffleMsg                        driver → all executors
====  =====================================  ===========================

Types 8-9 carry the BULK-SYNCHRONOUS collective shuffle plan: after the
map phase, every participating host asks the driver for the globally
agreed (src host × dst host) stream-length matrix plus its own
destination manifest, so all hosts can launch ONE symmetric collective
exchange (SPMD needs identical shapes everywhere — SURVEY.md §7
"pull → collective inversion" across hosts).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple, Type

from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    ShuffleManagerId,
)

_HEADER = struct.Struct("<ii")  # (frame_length, msg_type)
HEADER_SIZE = _HEADER.size


class RpcMsg:
    """Base class: framing + segmentation."""

    MSG_TYPE: int = 0

    # -- subclass hooks -----------------------------------------------------
    def _payload(self) -> bytes:
        raise NotImplementedError

    def _payload_size(self) -> int:
        """Cheap payload-size estimate used to decide splitting without
        serializing (subclasses override with arithmetic)."""
        return len(self._payload())

    def _split(self, max_payload: int) -> Sequence["RpcMsg"]:
        """Split into messages whose payloads each fit max_payload.
        Default: no splitting supported."""
        return (self,)

    # -- framing ------------------------------------------------------------
    def _frame(self, payload: bytes) -> bytes:
        return _HEADER.pack(HEADER_SIZE + len(payload), self.MSG_TYPE) + payload

    def encode(self) -> bytes:
        return self._frame(self._payload())

    def encode_segments(self, max_segment_size: int) -> List[bytes]:
        """Encode into frames each ≤ max_segment_size bytes."""
        max_payload = max_segment_size - HEADER_SIZE
        if max_payload <= 0:
            raise ValueError(f"segment size too small: {max_segment_size}")
        size = self._payload_size()
        if size <= max_payload:
            return [self._frame(self._payload())]
        parts = self._split(max_payload)
        if len(parts) == 1:
            raise ValueError(
                f"{type(self).__name__} payload {size}B exceeds segment "
                f"size {max_segment_size}B and cannot be split further"
            )
        out: List[bytes] = []
        for p in parts:
            psize = p._payload_size()
            if psize > max_payload:
                # an atomic element (e.g. one id with a very long hostname,
                # or a fixed header) alone exceeds the segment size
                raise ValueError(
                    f"{type(self).__name__} segment payload {psize}B still "
                    f"exceeds segment size {max_segment_size}B"
                )
            out.append(p._frame(p._payload()))
        return out


def decode_msg(data: bytes) -> RpcMsg:
    """Decode one frame (dispatch by type header,
    reference: RdmaRpcMsg.scala:67-87)."""
    if len(data) < HEADER_SIZE:
        raise ValueError(f"frame too short: {len(data)}B")
    length, msg_type = _HEADER.unpack_from(data, 0)
    if length != len(data):
        raise ValueError(f"frame length {length} != buffer length {len(data)}")
    cls = MSG_TYPES.get(msg_type)
    if cls is None:
        raise ValueError(f"unknown RPC message type {msg_type}")
    try:
        return cls._decode_payload(memoryview(data)[HEADER_SIZE:])
    except struct.error as e:
        # malformed frames must surface as ValueError, the decode contract
        raise ValueError(f"malformed {cls.__name__} frame: {e}") from e


# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HelloMsg(RpcMsg):
    """Executor advertises itself to the driver on startup
    (reference: RdmaShuffleManagerHelloRpcMsg, RdmaRpcMsg.scala:90-119)."""

    shuffle_manager_id: ShuffleManagerId
    channel_port: int  # port the driver should connect back to

    MSG_TYPE = 1

    def _payload(self) -> bytes:
        buf = bytearray()
        self.shuffle_manager_id.write(buf)
        buf += struct.pack("<i", self.channel_port)
        return bytes(buf)

    def _payload_size(self) -> int:
        return self.shuffle_manager_id.serialized_length() + 4

    @staticmethod
    def _decode_payload(view: memoryview) -> "HelloMsg":
        smid, off = ShuffleManagerId.read(view, 0)
        (port,) = struct.unpack_from("<i", view, off)
        return HelloMsg(smid, port)


@dataclass(frozen=True)
class AnnounceShuffleManagersMsg(RpcMsg):
    """Driver broadcasts the current membership so executors pre-connect
    the full mesh (reference: RdmaAnnounceRdmaShuffleManagersRpcMsg,
    RdmaRpcMsg.scala:121-180)."""

    shuffle_manager_ids: Tuple[ShuffleManagerId, ...]

    MSG_TYPE = 2

    def __init__(self, shuffle_manager_ids: Sequence[ShuffleManagerId]):
        object.__setattr__(self, "shuffle_manager_ids", tuple(shuffle_manager_ids))

    def _payload(self) -> bytes:
        buf = bytearray(struct.pack("<i", len(self.shuffle_manager_ids)))
        for smid in self.shuffle_manager_ids:
            smid.write(buf)
        return bytes(buf)

    def _payload_size(self) -> int:
        return 4 + sum(s.serialized_length() for s in self.shuffle_manager_ids)

    def _split(self, max_payload: int) -> Sequence["AnnounceShuffleManagersMsg"]:
        parts: List[AnnounceShuffleManagersMsg] = []
        cur: List[ShuffleManagerId] = []
        cur_len = 4
        for smid in self.shuffle_manager_ids:
            n = smid.serialized_length()
            if cur and cur_len + n > max_payload:
                parts.append(AnnounceShuffleManagersMsg(cur))
                cur, cur_len = [], 4
            cur.append(smid)
            cur_len += n
        if cur:
            parts.append(AnnounceShuffleManagersMsg(cur))
        return parts

    @staticmethod
    def _decode_payload(view: memoryview) -> "AnnounceShuffleManagersMsg":
        (n,) = struct.unpack_from("<i", view, 0)
        off = 4
        smids = []
        for _ in range(n):
            smid, off = ShuffleManagerId.read(view, off)
            smids.append(smid)
        return AnnounceShuffleManagersMsg(smids)


@dataclass(frozen=True)
class PublishMapTaskOutputMsg(RpcMsg):
    """Executor publishes a map task's location table to the driver,
    possibly as several sub-range segments
    (reference: RdmaPublishMapTaskOutputRpcMsg, RdmaRpcMsg.scala:182-276).

    ``entries`` holds the raw 16-byte location entries for partitions
    [first_reduce_id, last_reduce_id] inclusive.  ``epoch`` tags which
    publish generation of the map task's table this segment belongs to
    (delta-sync: a republish after a location change ships only the
    changed runs at a higher epoch, and the driver's per-entry epoch
    guard keeps out-of-order segment application from resurrecting
    stale locations — MapTaskOutput.put_range).
    """

    shuffle_manager_id: ShuffleManagerId
    shuffle_id: int
    map_id: int
    total_num_partitions: int
    first_reduce_id: int
    last_reduce_id: int
    entries: bytes
    epoch: int = 0

    MSG_TYPE = 3

    def __post_init__(self):
        expect = (self.last_reduce_id - self.first_reduce_id + 1) * LOCATION_ENTRY_SIZE
        if len(self.entries) != expect:
            raise ValueError(
                f"entries {len(self.entries)}B != expected {expect}B for range "
                f"[{self.first_reduce_id},{self.last_reduce_id}]"
            )

    def _payload(self) -> bytes:
        buf = bytearray()
        self.shuffle_manager_id.write(buf)
        buf += struct.pack(
            "<iiiiii",
            self.shuffle_id,
            self.map_id,
            self.total_num_partitions,
            self.first_reduce_id,
            self.last_reduce_id,
            self.epoch,
        )
        buf += self.entries
        return bytes(buf)

    def _payload_size(self) -> int:
        return self.shuffle_manager_id.serialized_length() + 24 + len(self.entries)

    def _split(self, max_payload: int) -> Sequence["PublishMapTaskOutputMsg"]:
        fixed = self.shuffle_manager_id.serialized_length() + 24
        per_seg = max(1, (max_payload - fixed) // LOCATION_ENTRY_SIZE)
        parts: List[PublishMapTaskOutputMsg] = []
        first = self.first_reduce_id
        while first <= self.last_reduce_id:
            last = min(first + per_seg - 1, self.last_reduce_id)
            lo = (first - self.first_reduce_id) * LOCATION_ENTRY_SIZE
            hi = (last - self.first_reduce_id + 1) * LOCATION_ENTRY_SIZE
            parts.append(
                PublishMapTaskOutputMsg(
                    self.shuffle_manager_id,
                    self.shuffle_id,
                    self.map_id,
                    self.total_num_partitions,
                    first,
                    last,
                    self.entries[lo:hi],
                    self.epoch,
                )
            )
            first = last + 1
        return parts

    @staticmethod
    def _decode_payload(view: memoryview) -> "PublishMapTaskOutputMsg":
        smid, off = ShuffleManagerId.read(view, 0)
        shuffle_id, map_id, total, first, last, epoch = struct.unpack_from(
            "<iiiiii", view, off
        )
        off += 24
        return PublishMapTaskOutputMsg(
            smid, shuffle_id, map_id, total, first, last,
            bytes(view[off:]), epoch,
        )


@dataclass(frozen=True)
class FetchMapStatusMsg(RpcMsg):
    """Executor asks the driver for the locations of a set of
    (map_id, reduce_id) blocks served by one remote host; the response is
    routed through ``callback_id``
    (reference: RdmaFetchMapStatusRpcMsg, RdmaRpcMsg.scala:279-367).

    Wide requests split across segments: each segment is an independent
    request carrying ``total`` (the whole logical request's block count)
    and ``index`` (offset of this segment's first block), and the driver's
    per-segment responses reuse those so the requester reassembles one
    answer of ``total`` locations.
    """

    requester: ShuffleManagerId
    host: ShuffleManagerId  # whose map outputs we want
    shuffle_id: int
    callback_id: int
    block_ids: Tuple[Tuple[int, int], ...]  # (map_id, reduce_id) pairs
    total: int = -1  # blocks in the whole logical request; -1 → len(block_ids)
    index: int = 0   # offset of block_ids[0] within the logical request

    MSG_TYPE = 4

    def __init__(self, requester, host, shuffle_id, callback_id, block_ids,
                 total=-1, index=0):
        object.__setattr__(self, "requester", requester)
        object.__setattr__(self, "host", host)
        object.__setattr__(self, "shuffle_id", shuffle_id)
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "block_ids", tuple(tuple(b) for b in block_ids))
        object.__setattr__(self, "total", len(self.block_ids) if total < 0 else total)
        object.__setattr__(self, "index", index)

    def _payload(self) -> bytes:
        buf = bytearray()
        self.requester.write(buf)
        self.host.write(buf)
        buf += struct.pack(
            "<iiiii",
            self.shuffle_id, self.callback_id, self.total, self.index,
            len(self.block_ids),
        )
        for map_id, reduce_id in self.block_ids:
            buf += struct.pack("<ii", map_id, reduce_id)
        return bytes(buf)

    def _payload_size(self) -> int:
        return (
            self.requester.serialized_length()
            + self.host.serialized_length()
            + 20
            + 8 * len(self.block_ids)
        )

    def _split(self, max_payload: int) -> Sequence["FetchMapStatusMsg"]:
        fixed = (
            self.requester.serialized_length()
            + self.host.serialized_length()
            + 20
        )
        per_seg = max(1, (max_payload - fixed) // 8)
        parts: List[FetchMapStatusMsg] = []
        for start in range(0, len(self.block_ids), per_seg):
            parts.append(
                FetchMapStatusMsg(
                    self.requester, self.host, self.shuffle_id, self.callback_id,
                    self.block_ids[start : start + per_seg],
                    total=self.total, index=self.index + start,
                )
            )
        return parts

    @staticmethod
    def _decode_payload(view: memoryview) -> "FetchMapStatusMsg":
        requester, off = ShuffleManagerId.read(view, 0)
        host, off = ShuffleManagerId.read(view, off)
        shuffle_id, callback_id, total, index, n = struct.unpack_from(
            "<iiiii", view, off
        )
        off += 20
        blocks = []
        for _ in range(n):
            blocks.append(struct.unpack_from("<ii", view, off))
            off += 8
        return FetchMapStatusMsg(
            requester, host, shuffle_id, callback_id, blocks,
            total=total, index=index,
        )


@dataclass(frozen=True)
class FetchMapStatusResponseMsg(RpcMsg):
    """Driver's answer: one BlockLocation per requested block, in request
    order, split across segments when large.  ``index`` is the offset of
    this segment's first location within the full answer, ``total`` the
    full answer's length (reference: RdmaFetchMapStatusResponseRpcMsg,
    RdmaRpcMsg.scala:369-446)."""

    callback_id: int
    total: int
    index: int
    locations: Tuple[BlockLocation, ...]

    MSG_TYPE = 5

    def __init__(self, callback_id, total, index, locations):
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "total", total)
        object.__setattr__(self, "index", index)
        object.__setattr__(self, "locations", tuple(locations))

    def _payload(self) -> bytes:
        buf = bytearray(
            struct.pack("<iiii", self.callback_id, self.total, self.index,
                        len(self.locations))
        )
        for loc in self.locations:
            loc.write(buf)
        return bytes(buf)

    def _payload_size(self) -> int:
        return 16 + LOCATION_ENTRY_SIZE * len(self.locations)

    def _split(self, max_payload: int) -> Sequence["FetchMapStatusResponseMsg"]:
        per_seg = max(1, (max_payload - 16) // LOCATION_ENTRY_SIZE)
        parts: List[FetchMapStatusResponseMsg] = []
        for start in range(0, len(self.locations), per_seg):
            parts.append(
                FetchMapStatusResponseMsg(
                    self.callback_id,
                    self.total,
                    self.index + start,
                    self.locations[start : start + per_seg],
                )
            )
        return parts

    @staticmethod
    def _decode_payload(view: memoryview) -> "FetchMapStatusResponseMsg":
        callback_id, total, index, n = struct.unpack_from("<iiii", view, 0)
        off = 16
        locs = []
        for _ in range(n):
            locs.append(BlockLocation.read(view, off))
            off += LOCATION_ENTRY_SIZE
        return FetchMapStatusResponseMsg(callback_id, total, index, locs)


@dataclass(frozen=True)
class FetchMapStatusFailedMsg(RpcMsg):
    """Driver tells a requester its fetch-status CANNOT be answered —
    unregistered shuffle, or the publishing executor was lost before its
    table filled.  The requester converts this to a metadata fetch
    failure immediately instead of riding out the full location timeout
    (the fast stage-retry path; reference reducers discover the same
    condition only via FetchFailedException after timeouts)."""

    callback_id: int
    reason: str

    MSG_TYPE = 6

    def _payload(self) -> bytes:
        reason = self.reason.encode("utf-8")[:1024]
        return struct.pack("<ii", self.callback_id, len(reason)) + reason

    def _payload_size(self) -> int:
        return 8 + len(self.reason.encode("utf-8")[:1024])

    @staticmethod
    def _decode_payload(view: memoryview) -> "FetchMapStatusFailedMsg":
        callback_id, n = struct.unpack_from("<ii", view, 0)
        reason = bytes(view[8 : 8 + n]).decode("utf-8", "replace")
        return FetchMapStatusFailedMsg(callback_id, reason)


@dataclass(frozen=True)
class HeartbeatMsg(RpcMsg):
    """Liveness probe on the hello/announce plane: the driver pings
    each executor; the executor echoes with ``is_ack=True``.  A missed
    ack window (or an outright send failure) drives automatic
    ``remove_executor`` — the role RDMA CM DISCONNECTED events play in
    the reference (RdmaNode.java:176-189)."""

    shuffle_manager_id: ShuffleManagerId
    seq: int
    is_ack: bool

    MSG_TYPE = 7

    def _payload(self) -> bytes:
        buf = bytearray()
        self.shuffle_manager_id.write(buf)
        buf += struct.pack("<ii", self.seq, 1 if self.is_ack else 0)
        return bytes(buf)

    def _payload_size(self) -> int:
        return self.shuffle_manager_id.serialized_length() + 8

    @staticmethod
    def _decode_payload(view: memoryview) -> "HeartbeatMsg":
        smid, off = ShuffleManagerId.read(view, 0)
        seq, ack = struct.unpack_from("<ii", view, off)
        return HeartbeatMsg(smid, seq, bool(ack))


@dataclass(frozen=True)
class FetchExchangePlanMsg(RpcMsg):
    """Host asks the driver for the bulk-exchange plan of one shuffle.

    ``window == -1`` requests the legacy single plan (answered once
    EVERY registered map has published — the full barrier).  ``window
    >= 0`` requests incremental plan number ``window``: the driver
    answers once ``bulkWindowMaps`` new maps (or the remainder) have
    published AND filled, so reducers exchange early windows while
    stragglers still write (the collective analog of the reference's
    windowed fetch overlap,
    RdmaShuffleFetcherIterator.scala:241-251)."""

    requester: ShuffleManagerId
    shuffle_id: int
    callback_id: int
    window: int = -1

    MSG_TYPE = 8

    def _payload(self) -> bytes:
        buf = bytearray()
        self.requester.write(buf)
        buf += struct.pack(
            "<iii", self.shuffle_id, self.callback_id, self.window
        )
        return bytes(buf)

    def _payload_size(self) -> int:
        return self.requester.serialized_length() + 12

    @staticmethod
    def _decode_payload(view: memoryview) -> "FetchExchangePlanMsg":
        smid, off = ShuffleManagerId.read(view, 0)
        shuffle_id, callback_id, window = struct.unpack_from(
            "<iii", view, off
        )
        return FetchExchangePlanMsg(smid, shuffle_id, callback_id, window)


@dataclass(frozen=True)
class PublishShuffleMetricsMsg(RpcMsg):
    """Executor publishes one shuffle's telemetry snapshot (a flat
    ``{metric name: number}`` dict, JSON-encoded) to the driver at
    unregister time — riding the same control plane the map-output
    location publishes use, so the driver can aggregate per-shuffle
    write/read/fetch totals across hosts (metrics/ tentpole; no
    reference analog — RdmaShuffleReaderStats stays executor-local)."""

    shuffle_manager_id: ShuffleManagerId
    shuffle_id: int
    payload: bytes  # JSON {metric: number}

    MSG_TYPE = 10

    def _payload(self) -> bytes:
        buf = bytearray()
        self.shuffle_manager_id.write(buf)
        buf += struct.pack("<ii", self.shuffle_id, len(self.payload))
        buf += self.payload
        return bytes(buf)

    def _payload_size(self) -> int:
        return (
            self.shuffle_manager_id.serialized_length()
            + 8 + len(self.payload)
        )

    @staticmethod
    def _decode_payload(view: memoryview) -> "PublishShuffleMetricsMsg":
        smid, off = ShuffleManagerId.read(view, 0)
        shuffle_id, n = struct.unpack_from("<ii", view, off)
        off += 8
        return PublishShuffleMetricsMsg(
            smid, shuffle_id, bytes(view[off : off + n])
        )


@dataclass(frozen=True)
class PrefetchHintMsg(RpcMsg):
    """Reader → serving peer: the next block locations this reader's
    fetch plan will request, so the responder's tiered block store
    (memory/tier.py) can promote them from disk through its serve-pool
    credits BEFORE the read RPCs arrive — the reader-side half of the
    RdmaMappedFile ODP-prefetch sweep (RdmaMappedFile.java:158-168),
    inverted: the requester knows the plan, the responder owns the
    residency.  Purely advisory: a dropped/failed hint costs nothing
    but the hidden disk latency, and unknown mkeys are ignored."""

    shuffle_id: int
    locations: Tuple[BlockLocation, ...]

    MSG_TYPE = 11

    def __init__(self, shuffle_id: int, locations):
        object.__setattr__(self, "shuffle_id", shuffle_id)
        object.__setattr__(self, "locations", tuple(locations))

    def _payload(self) -> bytes:
        buf = bytearray(
            struct.pack("<ii", self.shuffle_id, len(self.locations))
        )
        for loc in self.locations:
            loc.write(buf)
        return bytes(buf)

    def _payload_size(self) -> int:
        return 8 + LOCATION_ENTRY_SIZE * len(self.locations)

    def _split(self, max_payload: int) -> Sequence["PrefetchHintMsg"]:
        per_seg = max(1, (max_payload - 8) // LOCATION_ENTRY_SIZE)
        return [
            PrefetchHintMsg(
                self.shuffle_id, self.locations[i : i + per_seg]
            )
            for i in range(0, len(self.locations), per_seg)
        ]

    @staticmethod
    def _decode_payload(view: memoryview) -> "PrefetchHintMsg":
        shuffle_id, n = struct.unpack_from("<ii", view, 0)
        off = 8
        locs = []
        for _ in range(n):
            locs.append(BlockLocation.read(view, off))
            off += LOCATION_ENTRY_SIZE
        return PrefetchHintMsg(shuffle_id, locs)


@dataclass(frozen=True)
class CleanShuffleMsg(RpcMsg):
    """Driver tells every executor one shuffle is unregistered, so each
    releases its OWN side of that shuffle — registered arena segments,
    block-store mkeys, QoS-admitted quota bytes.  Without this the
    executor's resources for a finished shuffle survive until manager
    stop (the resource ledger flagged exactly that: committed map
    segments outstanding long after the driver forgot the shuffle).
    The reference gets this for free — Spark's ContextCleaner invokes
    unregisterShuffle on every executor — but this control plane has
    no external cleaner, so the driver's unregister broadcasts."""

    shuffle_id: int

    MSG_TYPE = 12

    def _payload(self) -> bytes:
        return struct.pack("<i", self.shuffle_id)

    def _payload_size(self) -> int:
        return 4

    @staticmethod
    def _decode_payload(view: memoryview) -> "CleanShuffleMsg":
        (shuffle_id,) = struct.unpack_from("<i", view, 0)
        return CleanShuffleMsg(shuffle_id)


@dataclass(frozen=True)
class ExchangePlanMsg(RpcMsg):
    """The driver's bulk-exchange plan: the canonical host order, the
    full (src × dst) stream-length matrix every host must agree on, and
    the requester's destination manifest — for each source host, the
    (map_id, reduce_id, length) blocks concatenated into that source's
    stream toward the requester, in order."""

    callback_id: int
    hosts: Tuple[ShuffleManagerId, ...]          # canonical order
    lengths: Tuple[int, ...]                     # row-major [E * E]
    manifest: Tuple[Tuple[Tuple[int, int, int], ...], ...]  # [E][blocks]
    window: int = -1            # -1: full-barrier plan; >=0: window no.
    final: bool = True          # True: no window follows this one
    my_maps: Tuple[int, ...] = ()  # requester's map_ids in this window

    MSG_TYPE = 9

    def __init__(self, callback_id, hosts, lengths, manifest,
                 window: int = -1, final: bool = True, my_maps=()):
        object.__setattr__(self, "callback_id", callback_id)
        object.__setattr__(self, "hosts", tuple(hosts))
        object.__setattr__(self, "lengths", tuple(int(x) for x in lengths))
        object.__setattr__(
            self, "manifest",
            tuple(tuple(tuple(b) for b in row) for row in manifest),
        )
        object.__setattr__(self, "window", int(window))
        object.__setattr__(self, "final", bool(final))
        object.__setattr__(
            self, "my_maps", tuple(int(m) for m in my_maps)
        )
        e = len(self.hosts)
        if len(self.lengths) != e * e or len(self.manifest) != e:
            raise ValueError(
                f"plan shape mismatch: {e} hosts, {len(self.lengths)} "
                f"lengths, {len(self.manifest)} manifest rows"
            )

    def _payload(self) -> bytes:
        buf = bytearray(struct.pack("<ii", self.callback_id, len(self.hosts)))
        for h in self.hosts:
            h.write(buf)
        for x in self.lengths:
            buf += struct.pack("<q", x)
        for row in self.manifest:
            buf += struct.pack("<i", len(row))
            for map_id, reduce_id, length in row:
                buf += struct.pack("<iiq", map_id, reduce_id, length)
        buf += struct.pack(
            "<iBi", self.window, int(self.final), len(self.my_maps)
        )
        for m in self.my_maps:
            buf += struct.pack("<i", m)
        return bytes(buf)

    def _payload_size(self) -> int:
        return (
            8
            + sum(h.serialized_length() for h in self.hosts)
            + 8 * len(self.lengths)
            + sum(4 + 16 * len(row) for row in self.manifest)
            + 9 + 4 * len(self.my_maps)
        )

    @staticmethod
    def _decode_payload(view: memoryview) -> "ExchangePlanMsg":
        callback_id, e = struct.unpack_from("<ii", view, 0)
        off = 8
        hosts = []
        for _ in range(e):
            h, off = ShuffleManagerId.read(view, off)
            hosts.append(h)
        lengths = struct.unpack_from(f"<{e * e}q", view, off) if e else ()
        off += 8 * e * e
        manifest = []
        for _ in range(e):
            (cnt,) = struct.unpack_from("<i", view, off)
            off += 4
            row = []
            for _ in range(cnt):
                m, r, n = struct.unpack_from("<iiq", view, off)
                off += 16
                row.append((m, r, n))
            manifest.append(tuple(row))
        window, final, n_my = struct.unpack_from("<iBi", view, off)
        off += 9
        my_maps = struct.unpack_from(f"<{n_my}i", view, off) if n_my else ()
        return ExchangePlanMsg(
            callback_id, hosts, lengths, manifest,
            window=window, final=bool(final), my_maps=my_maps,
        )


MSG_TYPES: Dict[int, Type[RpcMsg]] = {
    cls.MSG_TYPE: cls
    for cls in (
        HelloMsg,
        AnnounceShuffleManagersMsg,
        PublishMapTaskOutputMsg,
        FetchMapStatusMsg,
        FetchMapStatusResponseMsg,
        FetchMapStatusFailedMsg,
        HeartbeatMsg,
        FetchExchangePlanMsg,
        ExchangePlanMsg,
        PublishShuffleMetricsMsg,
        PrefetchHintMsg,
        CleanShuffleMsg,
    )
}
