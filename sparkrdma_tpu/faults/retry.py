"""In-task fetch retry policy: exponential backoff + jitter under a
deadline budget (conf ``fetchRetryCount`` / ``fetchRetryWaitMs`` /
``fetchRetryMaxMs``).

The reference SparkRDMA converts the FIRST transport failure into a
``FetchFailedException`` and lets Spark recompute the stage; this
policy absorbs transient fabric faults in-task first, converting to
:class:`FetchFailedError` only when the attempt count or the deadline
budget exhausts.  ``count=0`` disables retry entirely — the reader's
first-failure path is then byte-identical to the pre-policy behavior.
"""

from __future__ import annotations

import random
from typing import Optional

from sparkrdma_tpu.transport.channel import is_transient  # noqa: F401


class RetryPolicy:
    """Backoff/deadline math for one fetch's retry attempts.

    ``attempts`` below is the number of failures already observed for
    the fetch (1 after the first failure).  A delay is granted while
    ``attempts <= count`` AND ``elapsed_ms < deadline_ms``; the delay
    doubles per attempt from ``wait_ms`` with equal jitter (half
    fixed, half uniform — decorrelates peers retrying in lockstep
    after a shared-fabric blip) and is clamped to the remaining
    deadline so the final sleep never overshoots the budget."""

    __slots__ = ("count", "wait_ms", "deadline_ms", "_rng")

    def __init__(self, count: int, wait_ms: float, deadline_ms: float,
                 rng: Optional[random.Random] = None):
        self.count = int(count)
        self.wait_ms = float(wait_ms)
        self.deadline_ms = float(deadline_ms)
        self._rng = rng if rng is not None else random.Random()

    @property
    def enabled(self) -> bool:
        return self.count > 0

    def next_delay_ms(self, attempts: int,
                      elapsed_ms: float) -> Optional[float]:
        """Delay before retry number ``attempts`` (1-based failure
        count), or ``None`` when the budget is exhausted."""
        if attempts < 1 or attempts > self.count:
            return None
        if elapsed_ms >= self.deadline_ms:
            return None
        base = self.wait_ms * (2.0 ** (attempts - 1))
        delay = base / 2.0 + self._rng.uniform(0.0, base / 2.0)
        return min(delay, self.deadline_ms - elapsed_ms)
