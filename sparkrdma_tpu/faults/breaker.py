"""Per-peer failure containment: circuit breaker + stripe health.

Both live on the Node keyed by peer (``node.peer_health``) — NOT on a
ReadGroup, which the failure path destroys (``invalidate_read_group``)
on every error, exactly when history must survive.

:class:`CircuitBreaker` — repeated fetch failures against one peer
trip the breaker OPEN; while open, remaining fetches to that peer fail
fast instead of serially burning the full backoff budget each.  After
``reset_ms`` the breaker goes HALF_OPEN and admits ONE probe fetch:
success closes it, failure re-opens (and restarts the clock).

:class:`StripeHealth` — repeated striped-lane failures demote the
peer's large reads to the unstriped small-read lane for a window
(PR 7's dry-pool fallback generalized to a health signal); a
successful read while not demoted clears the strike count.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Tuple

from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.statemachine import StateMachine


class CircuitBreaker(StateMachine):
    """Consecutive-failure breaker with half-open probing.

    ``failures=0`` disables the breaker: :meth:`allow` is always true
    and nothing ever trips.  ``clock`` is injectable for tests."""

    MACHINE = "faults.breaker"
    STATES = ("closed", "open", "half-open")
    INITIAL = "closed"
    TERMINAL = ()
    TRANSITIONS = {
        "closed": ("open",),          # strike budget burned: trip
        "open": ("half-open",),       # reset window elapsed: probe
        "half-open": ("closed", "open"),  # probe verdict
    }

    def __init__(self, failures: int, reset_ms: float, name: str = "",
                 clock: Callable[[], float] = time.monotonic):
        self.failures = int(failures)
        self.reset_s = float(reset_ms) / 1000.0
        self.name = name
        self._clock = clock
        self._lock = dbg_lock("faults.breaker", 47)
        self._state = "closed"  # state: faults.breaker guarded-by: _lock
        self._strikes = 0  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        self.trips = 0  # guarded-by: _lock

    def allow(self) -> bool:
        """May a fetch proceed?  OPEN past ``reset_ms`` transitions to
        HALF_OPEN and admits exactly one probe; a HALF_OPEN breaker
        with its probe outstanding refuses further fetches."""
        if self.failures <= 0:
            return True
        probe = False
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_s:
                    self._transition("half-open")
                    probe = True
                else:
                    return False
            elif self._state == "half-open":
                return False  # probe already out
        if probe:
            if RECORDER.enabled:
                fr_event("faults", "breaker_probe", peer=self.name)
            return True
        return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == "half-open":
                # the probe came back: close and forget the strikes
                self._strikes = 0
                self._transition("closed")
            elif self._state == "closed":
                self._strikes = 0
            # OPEN: a stale success from a fetch issued BEFORE the trip.
            # Closing here would skip the probe protocol entirely — the
            # peer gets the full parallel fetch load again off one
            # straggler response that predates its failure burst.  The
            # half-open probe is the only path back to closed.

    def record_failure(self) -> None:
        if self.failures <= 0:
            return
        tripped = False
        with self._lock:
            self._strikes += 1
            strikes = self._strikes
            if self._state == "half-open":
                # the probe failed: straight back to OPEN, clock restarts
                self._transition("open")
                self._opened_at = self._clock()
            elif self._state == "closed" and self._strikes >= self.failures:
                self._transition("open")
                self._opened_at = self._clock()
                self.trips += 1
                tripped = True
        if tripped:
            counter("transport_breaker_trips_total", peer=self.name).inc()
            if RECORDER.enabled:
                fr_event(
                    "faults", "breaker_trip",
                    peer=self.name, strikes=strikes,
                )
                # a tripped breaker means a peer just burned its whole
                # failure budget — snapshot the lead-up while the rings
                # still hold it
                RECORDER.auto_dump("breaker_trip")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state


class StripeHealth:
    """Consecutive striped-lane failure tracker driving demotion.

    ``failures=0`` disables demotion.  Demotion lasts ``demote_ms``;
    each demoted read counts ``transport_stripe_demotions_total``
    at the decision site (ReadGroup), not here."""

    def __init__(self, failures: int, demote_ms: float, name: str = "",
                 clock: Callable[[], float] = time.monotonic):
        self.failures = int(failures)
        self.demote_s = float(demote_ms) / 1000.0
        self.name = name
        self._clock = clock
        self._lock = dbg_lock("faults.stripe_health", 47)
        self._strikes = 0  # guarded-by: _lock
        self._demoted_until = 0.0  # guarded-by: _lock

    def note_lane_failure(self) -> None:
        if self.failures <= 0:
            return
        with self._lock:
            self._strikes += 1
            if self._strikes >= self.failures:
                self._demoted_until = self._clock() + self.demote_s
                self._strikes = 0

    def note_success(self) -> None:
        with self._lock:
            if self._clock() >= self._demoted_until:
                self._strikes = 0
                self._demoted_until = 0.0

    def demoted(self) -> bool:
        if self.failures <= 0:
            return False
        with self._lock:
            return self._clock() < self._demoted_until


class PeerHealth:
    """One peer's breaker + stripe health, built from conf knobs."""

    __slots__ = ("breaker", "stripes")

    def __init__(self, peer: Tuple[str, int], conf,
                 clock: Callable[[], float] = time.monotonic):
        name = f"{peer[0]}:{peer[1]}"
        self.breaker = CircuitBreaker(
            conf.fetch_breaker_failures, conf.fetch_breaker_reset_ms,
            name=name, clock=clock)
        self.stripes = StripeHealth(
            conf.stripe_demote_failures, conf.stripe_demote_ms,
            name=name, clock=clock)


class PeerHealthRegistry:
    """Node-resident ``peer -> PeerHealth`` map.  Lives on the Node
    (rank 43, below the per-health locks at 47) so health survives
    ReadGroup invalidation across retry attempts."""

    def __init__(self, conf):
        self._conf = conf
        self._lock = dbg_lock("node.peer_health", 43)
        self._peers: Dict[Tuple[str, int], PeerHealth] = {}  # guarded-by: _lock

    def get(self, peer: Tuple[str, int]) -> PeerHealth:
        with self._lock:
            h = self._peers.get(peer)
            if h is None:
                h = self._peers[peer] = PeerHealth(peer, self._conf)
            return h

    def clear(self) -> None:
        with self._lock:
            self._peers.clear()
