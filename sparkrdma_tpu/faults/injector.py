"""Deterministic fault injector (conf ``spark.shuffle.tpu.faultInject``).

Spec grammar — ``;``-separated clauses, each arming one named point::

    connect:p=0.1;read_resp:p=0.05;serve_delay:ms=30;lane_kill:nth=7;seed=42

* ``point:p=0.1``   — fire with probability 0.1 per call,
* ``point:nth=7``   — fire on every 7th call (1-based: calls 7, 14, …),
* ``point:ms=30``   — the action is a 30 ms delay instead of a raise
  (composes with ``p``/``nth``; alone it fires on every call),
* ``seed=N``        — a standalone clause seeding the whole schedule.

Determinism: each point draws from its own ``random.Random`` seeded
``seed ^ crc32(point)`` and keeps its own call counter, so the fault
schedule for a given (spec, per-point call sequence) is reproducible
across runs and independent of unrelated points — the property the
chaos soak's bit-exactness assertions stand on.  ``hash()`` is NOT
used anywhere (it is salted per process).

Call-site contract (the woven points)::

    if FAULTS.enabled:
        FAULTS.check("recv")        # raises FaultInjectedError / sleeps
    ...
    if FAULTS.enabled and FAULTS.fires("lane_kill"):
        victim.stop()               # decision points act themselves

Disabled, every point is a single attribute check — no call, no lock.
Each firing counts ``fault_injected_total{point=}``.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.obs import RECORDER, fr_event

# NOTE: transport.channel is imported at the BOTTOM of this module.
# The transport package's __init__ imports engines that import FAULTS
# from here; importing channel first would re-enter this module while
# FAULTS is still undefined.  Everything the engines need is defined
# before that import runs, which breaks the cycle in both directions.

#: Every fault point woven through the stack, for spec validation and
#: the README fault-point table.  Keep in lockstep with the call sites.
KNOWN_POINTS = (
    "connect",       # network connect entry (tcp + loopback)
    "hello",         # tcp handshake between socket and ack
    "send",          # channel post paths (tcp, async dispatcher, loopback)
    "recv",          # rx frame header (tcp read loop, async rx pump)
    "read_resp",     # read-response frame decode
    "serve",         # serve-side block resolution (both tcp engines + loopback)
    "serve_delay",   # serve-side latency injection (use with ms=)
    "stripe",        # per-lane post in a striped read
    "lane_kill",     # decision point: kill a data lane after its post
    "disk_read",     # tier cold-read from spill
    "decode",        # decode-pool worker
    "publish",       # executor -> driver map-output publish
    "location_rpc",  # reader -> driver location fetch
    "heartbeat",     # decision point: drop a driver heartbeat probe
    "push_merge",    # merger rx: drop an arriving pushed sub-block
    "merge_status",  # merger rx: fail a merge-status query (dead merger)
)


class FaultSpecError(ValueError):
    """Malformed ``faultInject`` spec string."""


class _Clause:
    """One armed point: firing rule + action."""

    __slots__ = ("point", "p", "nth", "ms", "rng", "calls", "fired")

    def __init__(self, point: str, p: Optional[float], nth: Optional[int],
                 ms: Optional[float], seed: int):
        self.point = point
        self.p = p
        self.nth = nth
        self.ms = ms
        self.rng = random.Random(seed ^ zlib.crc32(point.encode("ascii")))
        self.calls = 0  # guarded-by: (injector) _lock
        self.fired = 0  # guarded-by: (injector) _lock

    def decide(self) -> bool:
        """One call's firing decision (caller holds the injector lock)."""
        self.calls += 1
        if self.nth is not None:
            hit = self.calls % self.nth == 0
        elif self.p is not None:
            hit = self.rng.random() < self.p
        else:
            hit = True  # bare delay clause: every call
        if hit:
            self.fired += 1
        return hit


def parse_fault_spec(spec: str) -> Tuple[int, Dict[str, "_Clause"]]:
    """Parse a spec string into ``(seed, {point: clause})``.  Raises
    :class:`FaultSpecError` on unknown points/keys or bad values, so a
    typo'd conf fails the job at manager construction, not silently."""
    seed = 0
    raw: List[Tuple[str, Dict[str, str]]] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            try:
                seed = int(part[5:])
            except ValueError:
                raise FaultSpecError(f"bad seed in fault spec: {part!r}")
            continue
        if ":" not in part:
            raise FaultSpecError(
                f"fault clause {part!r} is not 'point:key=value[,...]'")
        point, _, body = part.partition(":")
        point = point.strip()
        if point not in KNOWN_POINTS:
            raise FaultSpecError(
                f"unknown fault point {point!r} "
                f"(known: {', '.join(KNOWN_POINTS)})")
        kv: Dict[str, str] = {}
        for item in body.split(","):
            k, sep, v = item.partition("=")
            if not sep:
                raise FaultSpecError(
                    f"fault clause {part!r}: {item!r} is not key=value")
            kv[k.strip()] = v.strip()
        raw.append((point, kv))
    clauses: Dict[str, _Clause] = {}
    for point, kv in raw:
        p = nth = ms = None
        for k, v in kv.items():
            try:
                if k == "p":
                    p = float(v)
                    if not 0.0 <= p <= 1.0:
                        raise ValueError
                elif k == "nth":
                    nth = int(v)
                    if nth < 1:
                        raise ValueError
                elif k == "ms":
                    ms = float(v)
                    if ms < 0:
                        raise ValueError
                else:
                    raise FaultSpecError(
                        f"fault point {point!r}: unknown key {k!r} "
                        f"(use p=, nth=, ms=)")
            except (ValueError, TypeError):
                raise FaultSpecError(
                    f"fault point {point!r}: bad value {k}={v!r}")
        clauses[point] = _Clause(point, p, nth, ms, seed)
    return seed, clauses


class FaultInjector:
    """Process-global deterministic fault plane (see module doc)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()  # lock-order: 91
        self._clauses: Dict[str, _Clause] = {}  # guarded-by: _lock
        self._owners = 0  # guarded-by: _lock
        self.seed = 0

    # -- arming --------------------------------------------------------------
    def arm(self, spec: str) -> None:
        """Compile and install a spec; ``enabled`` flips on iff any
        clause armed.  Re-arming with the SAME spec (a second manager
        in one process, the in-process cluster tests) keeps the live
        schedule — counters keep advancing, so the process-wide fault
        sequence stays one deterministic stream.  Each armer must pair
        with one :meth:`stop`; the last stop disarms."""
        seed, clauses = parse_fault_spec(spec)
        with self._lock:
            self._owners += 1
            if not self._clauses:
                self.seed = seed
                self._clauses = clauses
                self.enabled = bool(clauses)

    def stop(self) -> None:
        """Drop one armer; the last one disarms and clears the spec."""
        with self._lock:
            if self._owners > 0:
                self._owners -= 1
            if self._owners == 0:
                self._clauses = {}
                self.enabled = False

    def reset(self) -> None:
        """Disarm unconditionally and forget all owners (tests)."""
        with self._lock:
            self._clauses = {}
            self._owners = 0
            self.enabled = False

    # -- the woven points ----------------------------------------------------
    def fires(self, point: str) -> bool:
        """Decision-point form: did this call hit?  The caller acts
        (kill a lane, drop a probe) — nothing is raised here."""
        with self._lock:
            c = self._clauses.get(point)
            hit = c.decide() if c is not None else False
        if hit:
            counter("fault_injected_total", point=point).inc()
            if RECORDER.enabled:
                fr_event("faults", "fault_fired", point=point, form="fires")
        return hit

    def check(self, point: str) -> None:
        """Raise-or-delay form: a clause with ``ms=`` sleeps, any
        other firing clause raises :class:`FaultInjectedError`."""
        with self._lock:
            c = self._clauses.get(point)
            hit = c.decide() if c is not None else False
            ms = c.ms if hit else None
        if not hit:
            return
        counter("fault_injected_total", point=point).inc()
        if RECORDER.enabled:
            fr_event(
                "faults", "fault_fired", point=point,
                form="delay" if ms is not None else "raise",
            )
        if ms is not None:
            time.sleep(ms / 1000.0)
            return
        raise FaultInjectedError(point)

    # -- introspection -------------------------------------------------------
    def fired_counts(self) -> Dict[str, int]:
        """Per-point firing totals (tests; metrics-independent)."""
        with self._lock:
            return {p: c.fired for p, c in self._clauses.items() if c.fired}


FAULTS = FaultInjector()

# Deferred import — see the note at the top of the module.  By the time
# this line runs, FAULTS and the injector machinery above are fully
# defined, so the transport engines this import transitively pulls in
# can bind them safely.
from sparkrdma_tpu.transport.channel import TransportError  # noqa: E402


class FaultInjectedError(TransportError):
    """A fault point fired.  Transient by construction — the injector
    models fabric blips, exactly what the retry policy absorbs."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at point '{point}'")
        self.point = point
