"""Fault-injection plane + recovery policy (conf faultInject).

The injector is the metrics/dbglock/ledger process-global shape:
disabled (the default) every woven fault point is one attribute check
(``FAULTS.enabled``); armed (conf ``spark.shuffle.tpu.faultInject``,
flipped by TpuShuffleManager before it builds its node) the named
points fire deterministically from a seeded spec.  See injector.py
for the spec grammar, retry.py for the backoff/deadline policy, and
breaker.py for the per-peer circuit breaker + stripe health signal.
"""

from sparkrdma_tpu.faults.injector import (  # noqa: F401
    FAULTS,
    FaultInjectedError,
    FaultInjector,
    FaultSpecError,
    parse_fault_spec,
)
from sparkrdma_tpu.faults.retry import (  # noqa: F401
    RetryPolicy,
    is_transient,
)
from sparkrdma_tpu.faults.breaker import (  # noqa: F401
    CircuitBreaker,
    PeerHealth,
    StripeHealth,
)
