"""Job-level user API: the local[*] driver experience over the stack.

The reference is a plugin inside Spark — its users write
``rdd.reduceByKey`` / ``sortByKey`` and Spark's scheduler drives
registerShuffle / getWriter / getReader (SURVEY.md §3).  This module is
the standalone equivalent of that top layer so the framework is usable
without Spark: a :class:`TpuShuffleContext` owning one driver and N
executor managers (threads in-process by default, real processes over
:class:`TcpNetwork`), and a :class:`Dataset` with the classic wide and
narrow operations, every wide op running through the full
write → publish → resolve → fetch → read shuffle path.

    ctx = TpuShuffleContext(num_executors=3)
    ds = ctx.parallelize(range(10000), num_slices=6)
    counts = ds.map(lambda x: (x % 100, 1)).reduce_by_key(lambda a, b: a + b)
    out = counts.collect()
    ctx.stop()

Device-native workloads (TeraSorter / WordCounter, the MXU/ICI path)
are exposed as ``ctx.device_sort`` / ``ctx.device_count`` — the same
split the reference has between its record plane and the NIC bulk
plane.
"""

from __future__ import annotations

import itertools
import logging
import random
import threading
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import (
    Aggregator,
    ColumnarAggregator,
    TpuShuffleManager,
)
from sparkrdma_tpu.shuffle.partitioner import (
    HashPartitioner,
    Partitioner,
    RangePartitioner,
)
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.utils.columns import ColumnBatch

logger = logging.getLogger(__name__)


class TpuShuffleContext:
    """Driver + executor managers + a task pool per executor."""

    def __init__(
        self,
        num_executors: int = 2,
        conf: Optional[TpuShuffleConf] = None,
        network=None,
        base_port: int = 39000,
        tasks_per_executor: int = 4,
        stage_to_device: Optional[bool] = None,
        mesh=None,
    ):
        if num_executors <= 0:
            raise ValueError("num_executors must be > 0")
        self.conf = conf or TpuShuffleConf()
        if network is not None:
            self.network = network
        else:
            if self.conf.read_plane == "collective":
                # the opportunistic in-process coordinator is a test
                # fixture now (tests/collective_read_fixture.py): the
                # windowed plane is reactive AND multi-process, so
                # production configs route there (pass an explicit
                # CollectiveNetwork as ``network=`` to use the fixture)
                logger.warning(
                    "readPlane=collective is superseded by the unified "
                    "windowed plane; using readPlane=windowed"
                )
                self.conf.set("readPlane", "windowed")
            if self.conf.read_plane in ("bulk", "windowed"):
                import jax

                n_dev = len(jax.devices())
                if num_executors > n_dev:
                    raise ValueError(
                        f"{self.conf.read_plane} read plane: "
                        f"{num_executors} executors need "
                        f"{num_executors} mesh devices, have {n_dev}"
                    )
            self.network = LoopbackNetwork()
        # stage_to_device=None defers to TpuShuffleManager's
        # plane-aware default (resolved from the conf AFTER the
        # collective->windowed rewrite above)
        self.driver = TpuShuffleManager(
            self.conf, is_driver=True, network=self.network,
            port=self.conf.driver_port or base_port,
            stage_to_device=stage_to_device,
        )
        self.executors = [
            TpuShuffleManager(
                self.conf, is_driver=False, network=self.network,
                port=base_port + 100 + i * 10, executor_id=str(i),
                stage_to_device=stage_to_device,
            )
            for i in range(num_executors)
        ]
        if hasattr(self.network, "attach_executor"):
            n_dev = len(self.network.coordinator.devices)
            if num_executors > n_dev:
                raise ValueError(
                    f"collective read plane: {num_executors} executors "
                    f"need {num_executors} mesh devices, have {n_dev}"
                )
            for i, ex in enumerate(self.executors):
                self.network.attach_executor(ex, i)
        if self.conf.read_plane == "windowed":
            # in-process executors share ONE contribution barrier per
            # window (one collective, every executor's row aboard) —
            # across OS processes each manager's plane runs its own
            # exchange and the collective itself is the barrier
            from sparkrdma_tpu.parallel.exchange import TileExchange
            from sparkrdma_tpu.parallel.mesh import make_mesh
            from sparkrdma_tpu.shuffle.bulk import (
                BulkShuffleSession,
                WindowedReadPlane,
            )

            E = num_executors
            # the exchange mesh must carry exactly one device per
            # executor (streams are [E][E]); a caller-provided mesh of
            # any other size is for the device-native workloads, not
            # the shuffle session
            sess_mesh = mesh
            if sess_mesh is None or len(
                list(sess_mesh.devices.flat)
            ) != E:
                sess_mesh = make_mesh(E)
            session = BulkShuffleSession(
                TileExchange.from_conf(self.conf, sess_mesh),
                E,
                timeout_s=self.conf.bulk_barrier_timeout_ms / 1000.0,
                # destination rows recycle through a staging pool (the
                # executors share one process, so any executor's pool
                # serves; release rides view GC)
                out_alloc=self.executors[0].staging_pool.alloc_gc,
                window_rounds=self.conf.device_exchange_window_rounds,
            )
            for ex in self.executors:
                ex.windowed_plane = WindowedReadPlane(ex, session=session)
            if self.conf.lazy_staging:
                # the ODP analog on the production plane: host-lazy
                # commits, with ensure_staged/prefetch_shuffle faulting
                # them into a per-executor HBM arena under the original
                # mkey (reference useOdp + prefetch advise,
                # RdmaShuffleConf.scala:68-83,
                # RdmaMappedFile.java:158-168)
                from sparkrdma_tpu.memory.device_arena import DeviceArena

                arena_devices = list(sess_mesh.devices.flat)
                for i, ex in enumerate(self.executors):
                    if ex.device_arena is not None:
                        # a CollectiveNetwork.attach_executor already
                        # installed this executor's arena (possibly on
                        # a different mesh) — overwriting it would
                        # strand the coordinator's entry and force its
                        # _resolve onto the host fallback forever
                        continue
                    arena = DeviceArena(
                        self.conf.device_arena_bytes, arena_devices[i]
                    )
                    ex.device_arena = arena
                    ex.resolver.device_arena = arena
        self._pools = [
            ThreadPoolExecutor(
                max_workers=tasks_per_executor,
                thread_name_prefix=f"exec-{i}",
            )
            for i in range(num_executors)
        ]
        self._shuffle_ids = itertools.count()
        self._stopped = False

    # -- dataset creation ---------------------------------------------------
    def parallelize(self, data: Iterable[Any],
                    num_slices: Optional[int] = None) -> "Dataset":
        items = list(data)
        n = num_slices or len(self.executors) * 2
        n = max(1, min(n, max(1, len(items))))
        size = (len(items) + n - 1) // n
        parts = [items[i * size : (i + 1) * size] for i in range(n)]
        return Dataset(self, [p for p in parts])

    def parallelize_columns(self, keys, vals,
                            num_slices: Optional[int] = None) -> "Dataset":
        """Columnar dataset from parallel (keys, vals) arrays — the
        record plane's fast path (set conf ``serializer=columnar`` so
        the shuffle stays columnar end to end).  Wide ops on the result
        run as vectorized numpy kernels instead of per-record Python."""
        keys = np.asarray(keys)
        vals = np.asarray(vals)
        whole = ColumnBatch(keys, vals)  # validates shape/dtype
        n = num_slices or len(self.executors) * 2
        n = max(1, min(n, max(1, len(whole))))
        bounds = [(i * len(whole)) // n for i in range(n + 1)]
        parts = [
            ColumnBatch(keys[lo:hi], vals[lo:hi])
            for lo, hi in zip(bounds, bounds[1:])
        ]
        return Dataset(self, parts)

    # -- device-native workloads (the MXU/ICI plane) ------------------------
    def device_sort(self, keys, vals=None, mesh=None):
        """Global sortByKey on the device mesh (TeraSort path)."""
        from sparkrdma_tpu.models.terasort import TeraSorter

        return TeraSorter(mesh).sort(keys, vals)

    def device_count(self, keys, vals=None, mesh=None) -> Dict[int, int]:
        """reduceByKey(+) on the device mesh (WordCount path)."""
        from sparkrdma_tpu.models.wordcount import WordCounter

        return WordCounter(mesh).count(keys, vals)

    def device_aggregate(self, keys, vals, mesh=None):
        """aggregateByKey (sum/count/min/max/mean) on the device mesh."""
        from sparkrdma_tpu.models.aggregate import KeyedAggregator

        return KeyedAggregator(mesh).aggregate(keys, vals)

    def device_join(self, fact_keys, fact_vals, dim_keys, dim_vals,
                    broadcast: bool = False, mesh=None, how: str = "inner"):
        """Equi-join on the device mesh: exchange (hash) or broadcast
        schedule; ``how`` = inner|left_outer|semi|anti."""
        from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

        joiner = (BroadcastJoiner if broadcast else HashJoiner)(mesh)
        return joiner.join(fact_keys, fact_vals, dim_keys, dim_vals,
                           how=how)

    def device_top_k(self, keys, vals, k: int, mesh=None):
        """Grouped top-k on the device mesh (rank/LIMIT per group)."""
        from sparkrdma_tpu.models.topk import GroupedTopK

        return GroupedTopK(mesh).top_k(keys, vals, k)

    # -- task running -------------------------------------------------------
    def _run_tasks(self, tasks: Sequence[Tuple[int, Callable[[], Any]]]) -> List[Any]:
        """Run (executor_index, thunk) tasks on their executors' pools."""
        futs = [self._pools[e % len(self._pools)].submit(fn) for e, fn in tasks]
        return [f.result() for f in futs]

    # -- the wide operation: one full shuffle -------------------------------
    def run_shuffle(
        self,
        partitions: List[List[Tuple[Any, Any]]],
        partitioner: Partitioner,
        aggregator: Optional[Aggregator] = None,
        map_side_combine: bool = False,
        key_ordering: bool = False,
    ) -> List[List[Tuple[Any, Any]]]:
        """Shuffle ``partitions`` (lists of (k, v)) into
        ``partitioner.num_partitions`` output partitions through the full
        data plane; the scheduler role of Spark's DAGScheduler."""
        shuffle_id = next(self._shuffle_ids)
        handle = self.driver.register_shuffle(
            shuffle_id, len(partitions), partitioner,
            aggregator=aggregator, map_side_combine=map_side_combine,
            key_ordering=key_ordering,
        )
        E = len(self.executors)
        maps_by_host: Dict[Any, List[int]] = defaultdict(list)
        lock = threading.Lock()

        def map_task(map_id: int, records: List[Tuple[Any, Any]]):
            ex = self.executors[map_id % E]
            w = ex.get_writer(handle, map_id)
            w.write(records)
            w.stop(True)
            with lock:
                maps_by_host[ex.local_smid].append(map_id)

        self._run_tasks([
            (m % E, (lambda m=m, recs=recs: map_task(m, recs)))
            for m, recs in enumerate(partitions)
        ])
        mbh = dict(maps_by_host)

        if self.conf.read_plane == "bulk":
            out = self._bulk_reduce(handle, shuffle_id)
        else:
            if self.conf.read_plane == "windowed":
                # symmetric participation: an executor owning no
                # partition of this shuffle still joins every window's
                # collective
                for ex in self.executors:
                    if ex.windowed_plane is not None:
                        ex.windowed_plane.join(shuffle_id)

            def reduce_task(pid: int) -> List[Tuple[Any, Any]]:
                ex = self.executors[pid % E]
                reader = ex.get_reader(handle, pid, pid + 1, mbh)
                return list(reader.read())

            out = self._run_tasks([
                (p % E, (lambda p=p: reduce_task(p)))
                for p in range(partitioner.num_partitions)
            ])
        self.driver.unregister_shuffle(shuffle_id)
        for ex in self.executors:
            ex.unregister_shuffle(shuffle_id)
        return out

    def _bulk_reduce(self, handle, shuffle_id: int) -> List[List]:
        """readPlane=bulk: one plan barrier + ONE symmetric collective
        moves every stream (shuffle/bulk.py), then the read-side
        aggregate/sort stage runs per partition — the columnar
        vectorized kernels when the serializer supports them, exactly
        like the pull readers.  Executor order == canonical host order
        (ascending ports), so partition p belongs to executor p % E
        exactly like the pull path above."""
        from sparkrdma_tpu.parallel.exchange import TileExchange
        from sparkrdma_tpu.parallel.mesh import make_mesh
        from sparkrdma_tpu.shuffle.bulk import (
            BulkExchangeReader,
            BulkShuffleSession,
        )
        from sparkrdma_tpu.shuffle.reader import (
            postprocess_column_batches,
            postprocess_records,
        )

        E = len(self.executors)
        session = BulkShuffleSession(
            TileExchange.from_conf(self.conf, make_mesh(E)), E,
            timeout_s=self.conf.bulk_barrier_timeout_ms / 1000.0,
            out_alloc=self.executors[0].staging_pool.alloc_gc,
            window_rounds=self.conf.device_exchange_window_rounds,
        )

        def bulk_task(i: int):
            ex = self.executors[i]
            reader = BulkExchangeReader(ex, session=session)
            agg = handle.aggregator
            columnar = getattr(
                ex.serializer, "supports_columns", False
            ) and (agg is None or isinstance(agg, ColumnarAggregator))
            try:
                if columnar:
                    deser = ex.serializer.deserialize_columns
                    per_part: Dict[int, list] = {}
                    for rid, block in reader.read_partitioned_blocks(
                        shuffle_id
                    ):
                        per_part.setdefault(rid, []).extend(deser(block))
                    return {
                        p: list(postprocess_column_batches(bs, handle))
                        for p, bs in per_part.items()
                    }
                parts = reader.read_partitioned(shuffle_id)
                return {
                    p: list(postprocess_records(iter(recs), handle))
                    for p, recs in parts.items()
                }
            except BaseException as e:
                # poison the barrier: peers fail NOW instead of riding
                # out the 120s contribution timeout (and ctx.stop()
                # hanging on their pool threads)
                session.abort(e)
                raise

        results = self._run_tasks([
            (i, (lambda i=i: bulk_task(i))) for i in range(E)
        ])
        out: List[List] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]
        for res in results:
            for p, recs in res.items():
                out[p] = recs
        return out

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        # quiesce the driver's failure-detection plane FIRST: stopping
        # executors below is deliberate, not a failure to report
        self.driver.quiesce()
        for p in self._pools:
            self._trim_pool_scratch(p)
            p.shutdown(wait=True)
        for m in self.executors + [self.driver]:
            m.stop()
        if hasattr(self.network, "coordinator"):
            self.network.stop()

    @staticmethod
    def _trim_pool_scratch(pool: ThreadPoolExecutor) -> None:
        """Release per-thread native radix scratch on every worker of a
        retiring pool (the scratch is thread_local, so each worker must
        run the trim itself; a barrier makes each take exactly one)."""
        import threading

        from sparkrdma_tpu.memory.staging import native_radix_scratch_trim

        workers = len(pool._threads)
        if not workers:
            return
        barrier = threading.Barrier(workers)

        def _trim():
            try:
                barrier.wait(timeout=5)
            except threading.BrokenBarrierError:
                pass  # a busy/dead worker: trim whoever arrived
            native_radix_scratch_trim()

        for f in [pool.submit(_trim) for _ in range(workers)]:
            try:
                f.result(timeout=10)
            except Exception:
                break

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def _try_vectorized_pair(f, batch: "ColumnBatch",
                         elementwise: bool = True):
    """Apply ``f`` to the ``(keys, vals)`` column pair and accept the
    result only when it is a clean ``(keys', vals')`` column pair:
    a 2-tuple of 1-D non-object ndarrays of equal length (scalars
    broadcast against the other column).  ``elementwise`` additionally
    requires exactly ``len(batch)`` rows (map); without it any common
    length is accepted (flat_map, whose vectorized form must emit
    outputs in per-record concatenation order).  Returns a ColumnBatch
    or None — the caller re-applies ``f`` per record, so ``f`` must be
    pure."""
    n = len(batch)
    try:
        out = f((batch.keys, batch.vals))
    except Exception:
        return None
    if not (isinstance(out, tuple) and len(out) == 2):
        return None
    k, v = out
    k_arr = isinstance(k, np.ndarray)
    v_arr = isinstance(v, np.ndarray)
    if not (k_arr or v_arr):
        return None
    # ONLY plain Python literals broadcast (the (key, 1) wordcount
    # shape).  A numpy scalar is the result of a column REDUCTION
    # (kv[1].max() etc.) — broadcasting it would silently replace every
    # value with the partition aggregate, so reductions must fall back
    # to the per-record loop where they keep identity semantics.
    scalar_kinds = (bool, int, float, bytes, str)
    if not k_arr:
        if isinstance(k, np.generic) or not isinstance(k, scalar_kinds):
            return None
        k = np.full(len(v), k)
    if not v_arr:
        if isinstance(v, np.generic) or not isinstance(v, scalar_kinds):
            return None
        v = np.full(len(k), v)
    if k.ndim != 1 or v.ndim != 1 or k.shape != v.shape:
        return None
    if k.dtype.hasobject or v.dtype.hasobject:
        return None
    if elementwise and k.shape[0] != n:
        return None
    try:
        return ColumnBatch(k, v)
    except Exception:
        return None


def _try_vectorized(f, arg, n: int, kinds: str = ""):
    """Apply ``f`` to a whole column (or column pair) and accept the
    result only when it is a clean elementwise vector: an ndarray of
    exactly ``n`` rows, non-object dtype, optionally restricted to
    dtype ``kinds`` (numpy kind letters, space-separated groups
    allowed).  Returns None otherwise — the caller re-applies ``f``
    per record, so ``f`` must be pure."""
    try:
        out = f(arg)
    except Exception:
        return None
    if not isinstance(out, np.ndarray):
        return None
    if out.ndim != 1 or out.shape[0] != n or out.dtype.hasobject:
        return None
    if kinds and out.dtype.kind not in kinds.replace(" ", ""):
        return None
    return out


class Dataset:
    """Partitioned collection with Spark-shaped transformations.

    Narrow ops (map/filter/flat_map/map_partitions) are applied lazily
    and fused; wide ops run a real shuffle through the context."""

    def __init__(self, ctx: TpuShuffleContext, partitions: List[List[Any]],
                 transform: Optional[Callable[[List[Any]], List[Any]]] = None):
        self.ctx = ctx
        self._parts = partitions
        self._transform = transform  # fused narrow stage, applied per partition

    # -- narrow transformations (lazy, fused) --------------------------------
    def _chain(self, f: Callable[[List[Any]], List[Any]]) -> "Dataset":
        return self._chain_indexed(lambda part, _pidx, f=f: f(part))

    def _chain_indexed(
        self, f: Callable[[List[Any], int], List[Any]]
    ) -> "Dataset":
        """Chain a narrow transform that also receives the partition
        index (needed by index-seeded ops like sample).

        A transform carrying ``_columnar_ok = True`` promises to accept
        a ColumnBatch as well as a record list and return the same
        kind; a chain where EVERY stage promises this keeps partitions
        columnar end to end (the vectorized narrow plane), otherwise
        _materialize falls back to record lists."""
        prev = self._transform
        if prev is None:
            fused = f
        else:
            def fused(part, pidx, prev=prev, f=f):
                return f(prev(part, pidx), pidx)
            fused._columnar_ok = (
                getattr(prev, "_columnar_ok", False)
                and getattr(f, "_columnar_ok", False)
            )
        return Dataset(self.ctx, self._parts, fused)

    def map(self, f: Callable[[Any], Any]) -> "Dataset":
        """Columnar partitions first try ``f`` VECTORIZED over the
        ``(keys, vals)`` column pair: a key+value producing map like
        ``lambda kv: (kv[0] % 10, kv[1] * 2)`` runs as numpy passes and
        the chain STAYS columnar; anything that doesn't evaluate to a
        clean same-length column pair (including maps to non-pair
        records, e.g. ``keys()``) falls back to the per-record loop.
        ``f`` must be pure — the fallback re-applies it."""

        def m(part, _pidx, f=f):
            if isinstance(part, ColumnBatch):
                out = _try_vectorized_pair(f, part, elementwise=True)
                if out is not None:
                    return out
                part = list(part)
            return [f(x) for x in part]

        m._columnar_ok = True
        return self._chain_indexed(m)

    def filter(self, f: Callable[[Any], bool]) -> "Dataset":
        """Columnar partitions first try ``f`` VECTORIZED over the
        ``(keys, vals)`` column pair (tuple-indexing predicates like
        ``lambda kv: kv[1] > 5`` evaluate to a boolean mask in one
        numpy pass); anything that doesn't vectorize cleanly falls back
        to the per-record loop.  ``f`` must be pure — the fallback
        re-applies it."""

        def fl(part, _pidx, f=f):
            if isinstance(part, ColumnBatch):
                mask = _try_vectorized(f, (part.keys, part.vals),
                                       len(part), kinds="bui f")
                if mask is not None:
                    mask = mask.astype(bool, copy=False)
                    return ColumnBatch(
                        part.keys[mask], part.vals[mask],
                        key_sorted=part.key_sorted,
                    )
                part = list(part)
            return [x for x in part if f(x)]

        fl._columnar_ok = True
        return self._chain_indexed(fl)

    def flat_map(self, f: Callable[[Any], Iterable[Any]]) -> "Dataset":
        """Columnar partitions stay columnar when ``f`` returns a
        :class:`ColumnBatch` (e.g. ``lambda kv: ColumnBatch(
        np.repeat(kv[0], 2), np.repeat(kv[1], 2))``) — the ONE return
        shape whose semantics agree between the vectorized call (whole
        column pair in, batch out) and the per-record fallback
        (iterating a ColumnBatch yields its (key, value) records, so
        ``[y for x in part for y in f(x)]`` flattens to the same
        stream).  A plain tuple return is deliberately NOT treated as
        a column pair: the fallback would flatten it into its two
        elements, a different dataset.  ``f`` must be pure and emit
        outputs in per-record concatenation order."""

        def fm(part, _pidx, f=f):
            if isinstance(part, ColumnBatch):
                try:
                    out = f((part.keys, part.vals))
                except Exception:
                    out = None
                if isinstance(out, ColumnBatch):
                    return out
                part = list(part)
            return [y for x in part for y in f(x)]

        fm._columnar_ok = True
        return self._chain_indexed(fm)

    def map_partitions(self, f: Callable[[List[Any]], Iterable[Any]]) -> "Dataset":
        return self._chain(lambda part: list(f(part)))

    # -- materialization -----------------------------------------------------
    def cache(self) -> "Dataset":
        """Materialize the pending transform chain once and keep the
        result: later actions reuse it instead of re-running the chain
        (Spark's cache/persist at MEMORY_ONLY).  Returns self."""
        if self._transform is not None:
            self._parts = self._materialize()
            self._transform = None
        return self

    def _materialize(self) -> List[List[Any]]:
        if self._transform is None:
            return self._parts
        t = self._transform
        col_ok = getattr(t, "_columnar_ok", False)
        E = len(self.ctx.executors)

        def run(p, i):
            # a fully column-aware chain receives the ColumnBatch
            # itself (vectorized narrow plane); otherwise records
            if col_ok and isinstance(p, ColumnBatch):
                return t(p, i)
            return t(list(p), i)

        out = self.ctx._run_tasks([
            (i % E, (lambda p=p, i=i: run(p, i)))
            for i, p in enumerate(self._parts)
        ])
        return out

    def collect(self) -> List[Any]:
        return [x for part in self._materialize() for x in part]

    def count(self) -> int:
        return sum(len(p) for p in self._materialize())

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    # -- wide transformations ------------------------------------------------
    @property
    def _is_columnar(self) -> bool:
        """True when partitions are ColumnBatch columns and any pending
        narrow transform is fully column-aware (tuple-level transforms
        de-columnarize)."""
        return (
            (self._transform is None
             or getattr(self._transform, "_columnar_ok", False))
            and bool(self._parts)
            and all(isinstance(p, ColumnBatch) for p in self._parts)
        )

    def _shuffled(self, partitioner, **kw) -> "Dataset":
        parts = self._materialize()
        out = self.ctx.run_shuffle(parts, partitioner, **kw)
        return Dataset(self.ctx, out)

    def partition_by(self, num_partitions: int) -> "Dataset":
        return self._shuffled(HashPartitioner(num_partitions))

    def reduce_by_key(self, f,
                      num_partitions: Optional[int] = None) -> "Dataset":
        """``f`` is a binary combiner; a columnar dataset also accepts
        the vectorizable names ``"sum"``/``"min"``/``"max"`` (required
        to stay on the columnar fast path)."""
        n = num_partitions or self.num_partitions
        if isinstance(f, str):
            agg: Aggregator = ColumnarAggregator.reduce(f)
        else:
            agg = Aggregator(
                create_combiner=lambda v: v, merge_value=f, merge_combiners=f
            )
        return self._shuffled(
            HashPartitioner(n), aggregator=agg, map_side_combine=True
        )

    def group_by_key(self, num_partitions: Optional[int] = None) -> "Dataset":
        n = num_partitions or self.num_partitions
        if self._is_columnar:
            # no map-side combine: grouping collects rather than
            # reduces, so combining would only concatenate columns
            return self._shuffled(
                HashPartitioner(n), aggregator=ColumnarAggregator.group(),
            )
        agg = Aggregator(
            create_combiner=lambda v: [v],
            merge_value=lambda c, v: c + [v],
            merge_combiners=lambda a, b: a + b,
        )
        return self._shuffled(
            HashPartitioner(n), aggregator=agg, map_side_combine=True
        )

    def sort_by_key(self, num_partitions: Optional[int] = None,
                    sample_size: int = 400, seed: int = 0) -> "Dataset":
        """Range-partitioned global sort: concatenating the output
        partitions in order yields the sorted data."""
        parts = self._materialize()
        n = num_partitions or self.num_partitions
        rng = random.Random(seed)
        if parts and all(isinstance(p, ColumnBatch) for p in parts):
            all_keys = np.concatenate([p.keys for p in parts])
            if len(all_keys):
                idx = rng.sample(
                    range(len(all_keys)), min(sample_size, len(all_keys))
                )
                sample = all_keys[np.asarray(idx)].tolist()
            else:
                sample = []
        else:
            keys = [k for part in parts for k, _ in part]
            sample = (
                rng.sample(keys, min(sample_size, len(keys))) if keys else []
            )
        ds = Dataset(self.ctx, parts)
        return ds._shuffled(RangePartitioner(n, sample), key_ordering=True)

    def repartition_and_sort_within_partitions(
        self, partitioner=None,
        num_partitions: Optional[int] = None,
    ) -> "Dataset":
        """Spark's repartitionAndSortWithinPartitions: one shuffle that
        both routes rows by the partitioner AND leaves every output
        partition key-sorted (the columnar writer commits key-sorted
        blocks, so readers merge views — no extra sort pass)."""
        n = num_partitions or self.num_partitions
        part = partitioner or HashPartitioner(n)
        return self._shuffled(part, key_ordering=True)

    def map_values(self, f: Callable[[Any], Any]) -> "Dataset":
        """Columnar partitions first try ``f`` VECTORIZED over the
        whole value column (ufunc-style callables like ``lambda v:
        v * 2`` run in one numpy pass and the chain STAYS columnar);
        non-vectorizable callables fall back per record.  ``f`` must be
        pure — the fallback re-applies it."""

        def mv(part, _pidx, f=f):
            if isinstance(part, ColumnBatch):
                out = _try_vectorized(f, part.vals, len(part))
                if out is not None:
                    return ColumnBatch(
                        part.keys, out, key_sorted=part.key_sorted
                    )
                part = list(part)
            return [(k, f(v)) for k, v in part]

        mv._columnar_ok = True
        return self._chain_indexed(mv)

    def keys(self) -> "Dataset":
        return self.map(lambda kv: kv[0])

    def values(self) -> "Dataset":
        return self.map(lambda kv: kv[1])

    def union(self, other: "Dataset") -> "Dataset":
        """Narrow union: partitions of both datasets side by side."""
        return Dataset(
            self.ctx, self._materialize() + other._materialize()
        )

    def take(self, n: int) -> List[Any]:
        out: List[Any] = []
        for part in self._materialize():
            for rec in part:  # ColumnBatch iterates (key, val) records
                out.append(rec)
                if len(out) >= n:
                    return out
        return out

    def first(self) -> Any:
        got = self.take(1)
        if not got:
            raise ValueError("first() on an empty dataset")
        return got[0]

    def sample(self, fraction: float, seed: int = 0) -> "Dataset":
        """Bernoulli sample without replacement.

        Deterministic like Spark's seeded sample: the decision stream
        is re-derived from ``(seed, partition_index)`` on every
        materialization, so repeated actions on the same sampled
        dataset (count() then collect()) see identical rows."""
        if not (0.0 <= fraction <= 1.0):
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")

        def sample_part(part, pidx, seed=seed, fraction=fraction):
            if isinstance(part, ColumnBatch):
                # salt-free seed mix: str hashing is PYTHONHASHSEED-
                # salted and would break cross-process determinism;
                # SeedSequence keeps the FULL seed (no truncation)
                rng = np.random.default_rng(
                    np.random.SeedSequence(
                        [seed & ((1 << 64) - 1), pidx, 0xC0]
                    )
                )
                mask = rng.random(len(part)) < fraction
                return ColumnBatch(
                    part.keys[mask], part.vals[mask],
                    key_sorted=part.key_sorted,
                )
            rng = random.Random(hash((seed, pidx)))
            return [x for x in part if rng.random() < fraction]

        sample_part._columnar_ok = True
        return self._chain_indexed(sample_part)

    def top_k_per_key(self, k: int,
                      num_partitions: Optional[int] = None) -> "Dataset":
        """Top-k values per key, descending (the rank/LIMIT-per-group
        shape; device-plane analog: models/topk.py GroupedTopK)."""
        import heapq

        if k <= 0:
            raise ValueError(f"k must be positive: {k}")
        return self.group_by_key(num_partitions).map_values(
            lambda vs: heapq.nlargest(k, list(vs))
        )

    def combine_by_key(self, create_combiner, merge_value, merge_combiners,
                       num_partitions: Optional[int] = None) -> "Dataset":
        """The general combiner (Spark combineByKey; the reference's
        read-path Aggregator, RdmaShuffleReader.scala:82-97):
        map-side combine with ``create_combiner``/``merge_value``,
        reduce-side merge with ``merge_combiners``."""
        n = num_partitions or self.num_partitions
        agg = Aggregator(
            create_combiner=create_combiner,
            merge_value=merge_value,
            merge_combiners=merge_combiners,
        )
        return self._shuffled(
            HashPartitioner(n), aggregator=agg, map_side_combine=True
        )

    def count_by_key(self) -> Dict[Any, int]:
        """Action: {key: occurrence count} (one reduce_by_key pass)."""
        return dict(
            self.map(lambda kv: (kv[0], 1))
            .reduce_by_key(lambda a, b: a + b)
            .collect()
        )

    def distinct(self, num_partitions: Optional[int] = None) -> "Dataset":
        """Distinct elements via a hash-partitioned nil-value shuffle
        (co-locates duplicates, keeps one per partition)."""
        n = num_partitions or self.num_partitions
        keyed = self.map(lambda x: (x, None))
        return (
            keyed.reduce_by_key(lambda a, b: a, num_partitions=n)
            .map(lambda kv: kv[0])
        )

    def _cogrouped(self, other: "Dataset",
                   num_partitions: Optional[int] = None) -> "Dataset":
        """(k, ([vs], [ws])) — both sides tagged and grouped in ONE
        shuffle (the cogroup narrow dependency)."""
        n = num_partitions or max(self.num_partitions, other.num_partitions)
        tagged = Dataset(
            self.ctx,
            self.map(lambda kv: (kv[0], (0, kv[1])))._materialize()
            + other.map(lambda kv: (kv[0], (1, kv[1])))._materialize(),
        )
        grouped = tagged.group_by_key(n)

        def split(part):
            out = []
            for k, tagged_vals in part:
                left = [v for t, v in tagged_vals if t == 0]
                right = [w for t, w in tagged_vals if t == 1]
                out.append((k, (left, right)))
            return out

        return grouped.map_partitions(split)

    def cogroup(self, other: "Dataset",
                num_partitions: Optional[int] = None) -> "Dataset":
        """Spark cogroup: (k, ([vs], [ws])) for every key on either
        side."""
        return self._cogrouped(other, num_partitions)

    def join(self, other: "Dataset",
             num_partitions: Optional[int] = None,
             how: str = "inner") -> "Dataset":
        """Equi-join: (k, v) ⋈ (k, w) — the exchange shuffle of the
        reference's SQL workloads (BASELINE configs).  ``how`` is
        inner (→ (k, (v, w))), left_outer (w may be None),
        right_outer (v may be None), full_outer (either may be None),
        semi (→ (k, v) where a match exists), or anti (→ (k, v)
        where none does) — the record-plane analog of the device
        joins (models/join.py JOIN_HOWS)."""
        hows = ("inner", "left_outer", "right_outer", "full_outer",
                "semi", "anti")
        if how not in hows:
            raise ValueError(f"unsupported join how={how!r}")
        cg = self._cogrouped(other, num_partitions)

        def emit(part):
            out = []
            for k, (left, right) in part:
                if how == "semi":
                    if right:
                        out.extend((k, v) for v in left)
                elif how == "anti":
                    if not right:
                        out.extend((k, v) for v in left)
                else:
                    ls = left or (
                        [None] if how in ("right_outer", "full_outer")
                        else []
                    )
                    rs = right or (
                        [None] if how in ("left_outer", "full_outer")
                        else []
                    )
                    for v in ls:
                        out.extend((k, (v, w)) for w in rs)
            return out

        return cg.map_partitions(emit)

    def aggregate_by_key(self, zero, seq_func, comb_func,
                         num_partitions: Optional[int] = None
                         ) -> "Dataset":
        """Spark aggregateByKey: fold each key's values into a fresh
        copy of ``zero`` with ``seq_func`` map-side, merge partials
        with ``comb_func`` (one combine_by_key shuffle)."""
        import copy as _copy

        return self.combine_by_key(
            lambda v: seq_func(_copy.deepcopy(zero), v),
            seq_func,
            comb_func,
            num_partitions=num_partitions,
        )

    def fold_by_key(self, zero, func,
                    num_partitions: Optional[int] = None) -> "Dataset":
        """Spark foldByKey: aggregate_by_key with one function for
        both the fold and the merge."""
        return self.aggregate_by_key(
            zero, func, func, num_partitions=num_partitions
        )

    def subtract_by_key(self, other: "Dataset",
                        num_partitions: Optional[int] = None
                        ) -> "Dataset":
        """Spark subtractByKey: pairs whose key has NO entry in
        ``other`` (one cogroup shuffle — the anti-join over pairs)."""
        return self.join(other, num_partitions=num_partitions, how="anti")
