"""Tenant registry + admission control (the QoS control plane).

One process-global :class:`TenantRegistry` (the metrics-registry /
lock-factory shape: managers flip ``enabled`` from conf ``qosEnabled``
before building their node, so every pool created after that consults
it).  A tenant is a named share of the node's resources:

- **weight** — its proportion of every brokered byte-credit budget
  under weighted max-min sharing (qos/broker.py),
- **priority class** — ``interactive`` work dequeues ahead of ``bulk``
  on the serve pool and borrows stripe lanes from the reserved slice
  of the lane pool,
- **quotas** — ``max_bytes`` caps the tenant's registered (committed)
  map-output bytes and ``max_inflight`` its brokered in-flight fetch
  bytes; :meth:`TenantRegistry.admit` makes an over-quota tenant QUEUE
  briefly for capacity and then DEGRADE (narrower stripes, cold-tier
  serves — see stripe.py/tier.py) rather than OOM the node.

Shuffles bind to tenants (``bind_shuffle``; conf
``spark.shuffle.tpu.tenant``, default one tenant per shuffle), and the
serve path resolves the tenant of an incoming read from the target
mkey through the node's block stores (``Node.tenant_of_mkey``), so the
responder applies the owner's policy without any wire change.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.utils.ledger import ledger_acquire

#: priority classes on the scheduling edges (qos/broker.py)
INTERACTIVE = "interactive"
BULK = "bulk"


class Tenant:
    """One tenant's policy + live accounting.  ``degraded`` is read
    lock-free on hot paths (a racy read only delays the mode flip by
    one operation — the flag is sticky until admission pressure
    clears)."""

    __slots__ = ("name", "weight", "priority", "max_bytes",
                 "max_inflight", "registered_bytes", "degraded")

    def __init__(self, name: str):
        self.name = name
        self.weight = 1
        self.priority = BULK
        self.max_bytes = 0      # 0 = unlimited registered bytes
        self.max_inflight = 0   # 0 = unlimited brokered in-flight bytes
        self.registered_bytes = 0  # guarded-by: (registry) _cv
        self.degraded = False

    @property
    def interactive(self) -> bool:
        return self.priority == INTERACTIVE

    def __repr__(self) -> str:
        return (
            f"Tenant({self.name!r}, w={self.weight}, {self.priority}"
            f"{', degraded' if self.degraded else ''})"
        )


class TenantRegistry:
    """Process-global tenant table: get-or-create tenants, shuffle →
    tenant bindings, and registered-byte admission control."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        # admission waiters block on this condition only (never under
        # another lock); ranked with the other leaf bookkeeping locks
        self._cv = threading.Condition()  # lock-order: 95
        self._tenants: Dict[str, Tenant] = {}  # guarded-by: _cv
        self._shuffle_tenant: Dict[int, str] = {}  # guarded-by: _cv
        # shuffle → admitted registered bytes (released at unregister)
        self._admitted: Dict[int, int] = {}  # guarded-by: _cv
        # resource: qos.admitted_bytes (per-shuffle admitted quota bytes)
        self._admit_tkts: Dict[int, list] = {}  # guarded-by: _cv

    # -- tenants -------------------------------------------------------------
    def tenant(self, name: str, weight: Optional[int] = None,
               priority: Optional[str] = None,
               max_bytes: Optional[int] = None,
               max_inflight: Optional[int] = None) -> Tenant:
        """Get-or-create ``name``; explicit parameters update the
        tenant (last writer wins — re-registration with new weights is
        how a tenant's policy changes at runtime)."""
        with self._cv:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(name)
            if weight is not None:
                t.weight = max(1, int(weight))
            if priority is not None:
                t.priority = (
                    INTERACTIVE if str(priority).lower() == INTERACTIVE
                    else BULK
                )
            if max_bytes is not None:
                t.max_bytes = max(0, int(max_bytes))
            if max_inflight is not None:
                t.max_inflight = max(0, int(max_inflight))
            return t

    def tenants(self) -> List[Tenant]:
        with self._cv:
            return list(self._tenants.values())

    def bind_shuffle(self, shuffle_id: int, tenant: Tenant) -> None:
        with self._cv:
            self._shuffle_tenant[shuffle_id] = tenant.name

    def tenant_of_shuffle(self, shuffle_id) -> Optional[Tenant]:
        if shuffle_id is None:
            return None
        with self._cv:
            name = self._shuffle_tenant.get(shuffle_id)
            return self._tenants.get(name) if name is not None else None

    # -- admission control ---------------------------------------------------
    def admit(self, shuffle_id: int, tenant: Tenant, nbytes: int,
              wait_s: float = 0.0) -> bool:
        """Admit ``nbytes`` of committed map output under ``tenant``'s
        registered-byte quota.  Over quota the caller QUEUES up to
        ``wait_s`` for earlier shuffles to release, then proceeds in
        DEGRADED mode (the output still commits — refusing it would
        fail the map task; degrading sheds the tenant's resource
        appetite instead: stripes narrow and the tier stops promoting
        its blocks).  Returns True when admitted within quota."""
        nbytes = max(int(nbytes), 0)
        with self._cv:
            if tenant.max_bytes > 0:
                deadline = time.monotonic() + max(wait_s, 0.0)
                while (tenant.registered_bytes + nbytes > tenant.max_bytes
                       and not tenant.degraded):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    counter("qos_admission_waits_total",
                            tenant=tenant.name).inc()
                    self._cv.wait(left)
            over = (tenant.max_bytes > 0
                    and tenant.registered_bytes + nbytes > tenant.max_bytes)
            tenant.registered_bytes += nbytes
            self._admitted[shuffle_id] = (
                self._admitted.get(shuffle_id, 0) + nbytes
            )
            # the admitted quota rides the shuffle until unregister
            # owns: qos.admitted_bytes -> release_shuffle
            # owns: qos.admitted_bytes -> reset
            self._admit_tkts.setdefault(shuffle_id, []).append(
                ledger_acquire("qos.admitted_bytes", nbytes)
            )  # acquires: qos.admitted_bytes
            # an admit IS a binding: release_shuffle must find the
            # tenant even if bind_shuffle never ran in this process
            self._shuffle_tenant.setdefault(shuffle_id, tenant.name)
            if over:
                tenant.degraded = True
                counter("qos_admission_rejections_total",
                        tenant=tenant.name).inc()
            gauge("qos_tenant_registered_bytes",
                  tenant=tenant.name).set(tenant.registered_bytes)
            gauge("qos_tenant_degraded", tenant=tenant.name).set(
                1 if tenant.degraded else 0
            )
        return not over

    def release_shuffle(self, shuffle_id: int) -> None:
        """Unregister hook: return the shuffle's admitted bytes and
        clear its binding; a tenant back under quota leaves degraded
        mode and queued admissions re-check."""
        with self._cv:
            nbytes = self._admitted.pop(shuffle_id, 0)
            tkts = self._admit_tkts.pop(shuffle_id, ())
            name = self._shuffle_tenant.pop(shuffle_id, None)
            t = self._tenants.get(name) if name is not None else None
            if t is not None:
                t.registered_bytes = max(0, t.registered_bytes - nbytes)
                if t.degraded and (
                    t.max_bytes <= 0 or t.registered_bytes <= t.max_bytes
                ):
                    t.degraded = False
                gauge("qos_tenant_registered_bytes",
                      tenant=t.name).set(t.registered_bytes)
                gauge("qos_tenant_degraded", tenant=t.name).set(
                    1 if t.degraded else 0
                )
                self._cv.notify_all()
        for tkt in tkts:
            tkt.release()  # releases: qos.admitted_bytes

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict:
        """JSON-able view for the scrape endpoint's ``/tenants``."""
        with self._cv:
            return {
                "enabled": self.enabled,
                "tenants": [
                    {
                        "name": t.name,
                        "weight": t.weight,
                        "priority": t.priority,
                        "max_bytes": t.max_bytes,
                        "max_inflight": t.max_inflight,
                        "registered_bytes": t.registered_bytes,
                        "degraded": t.degraded,
                    }
                    for t in self._tenants.values()
                ],
                "shuffles": dict(self._shuffle_tenant),
            }

    def reset(self) -> None:
        """Drop every tenant and binding (tests)."""
        with self._cv:
            self._tenants.clear()
            self._shuffle_tenant.clear()
            self._admitted.clear()
            tkts = [t for ts in self._admit_tkts.values() for t in ts]
            self._admit_tkts.clear()
            self._cv.notify_all()
        for tkt in tkts:
            tkt.release()  # releases: qos.admitted_bytes


# the process-global registry; managers enable it from conf qosEnabled
GLOBAL_QOS = TenantRegistry(enabled=False)


def get_qos() -> TenantRegistry:
    return GLOBAL_QOS
