"""Multi-tenant QoS: weighted credit brokering, lane/serve priority
classes, admission control, and the live metrics endpoint.

"Millions of users" means many concurrent shuffles sharing one node's
serve pool, decode pool, lane pool and registered memory — and every
one of those credit pools was a single global FIFO, so one bulk tenant
could park all serve credits and starve a latency-sensitive tenant's
RPCs (ROADMAP item 5).  *RDMAvisor* (PAPERS.md) argues a shared RDMA
fabric needs a mediating service layer with per-consumer resource
policy; *fabric-lib* ships priority-aware transfer scheduling.  This
package is that layer over the credit-pool pattern PRs 3/5/7/8
established:

- :mod:`~sparkrdma_tpu.qos.registry` — the process-global
  :class:`TenantRegistry`: every shuffle registers under a tenant id
  (conf ``spark.shuffle.tpu.tenant``, default per-shuffle) with a
  weight and priority class, plus admission control on registration
  (``qosTenantMaxBytes`` — an over-quota tenant queues briefly, then
  DEGRADES: narrower stripes, cold-tier serves — never an OOM).
- :mod:`~sparkrdma_tpu.qos.broker` — :class:`WeightedCreditBroker`
  and :class:`CreditLedger`: the byte-credit pools (serve pool,
  decode pool, reader ``maxBytesInFlight``, tier hot budget) acquire
  credits through a weighted max-min ledger with work-conservation
  (idle tenants' shares are borrowable, reclaimed on demand) and FIFO
  handoff within (class, tenant); :class:`ClassedTaskQueue` dequeues
  interactive-class work (RPC frames, small reads — PR 3's dedicated
  small-read lane, generalized) ahead of bulk with anti-starvation
  aging.
- :mod:`~sparkrdma_tpu.qos.http` — :class:`MetricsHttpServer`: the
  stop-time Prometheus dump as an always-on HTTP scrape endpoint
  (conf ``metricsHttpPort``), with per-tenant labels on the brokered
  instruments.

All policy is off by default: with ``qosEnabled=false`` the brokers
compile down to the existing pools (plain FIFO credits, unclassed
queues — A/B-able), and the only behavioral delta from the pre-QoS
tree is the serve pool's explicit FIFO credit handoff (the starvation
fix an oversized clamped serve needed regardless of QoS).
"""

from sparkrdma_tpu.qos.broker import (
    BULK,
    INTERACTIVE,
    ClassedTaskQueue,
    CreditLedger,
    WeightedCreditBroker,
)
from sparkrdma_tpu.qos.registry import (
    Tenant,
    TenantRegistry,
    get_qos,
)

__all__ = [
    "BULK",
    "INTERACTIVE",
    "ClassedTaskQueue",
    "CreditLedger",
    "Tenant",
    "TenantRegistry",
    "WeightedCreditBroker",
    "get_qos",
]
