"""Weighted credit brokering + priority-classed dequeue.

The byte-credit pools PRs 3/5/7/8 built (serve pool, decode pool,
reader ``maxBytesInFlight``, tier hot budget) all shared one shape: a
global budget, FIFO waiters, first-come-first-served grants.  Correct
for one consumer, starvation-prone for many: a bulk tenant that keeps
the budget saturated parks every other tenant's work behind its own.
This module is the mediation layer (the RDMAvisor "RDMA as a service"
idiom) those pools now acquire through:

- :class:`CreditLedger` — the caller-locked policy core: per-tenant
  usage against **weighted max-min shares** with work-conservation
  (an idle tenant's share is borrowable; a borrower is reclaimed on
  demand — its further grants pause while a deprived tenant waits),
  plus the per-tenant in-flight quota (``qosTenantMaxInFlight``).
- :class:`WeightedCreditBroker` — the blocking facade over a ledger:
  explicit **FIFO handoff** (grants go to waiters in arrival order —
  within one (class, tenant) stream nothing bypasses the head, so a
  clamped oversized acquisition cannot be starved by a stream of
  small ones), interactive-before-bulk classing with anti-starvation
  **aging** (a bulk waiter older than ``qosAgingMs`` is promoted),
  and release pumps for non-blocking acquirers (the reader window).
- :class:`ClassedTaskQueue` — the pool-worker dequeue with the same
  class/aging policy (the serve pool's FIFO generalized; PR 3's
  dedicated small-read lane was the precedent).

With QoS off (no tenant registry attached) both collapse to plain
FIFO semantics over a single budget — byte-for-byte the pre-QoS
behavior, except that credit handoff is now explicitly FIFO (the
serve-pool fairness fix).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.qos.registry import BULK, INTERACTIVE, Tenant

_EMPTY = object()


def weighted_shares(budget: int, qos, usage: Dict[str, int],
                    extra: Optional[Dict[str, Tenant]] = None
                    ) -> Dict[str, float]:
    """THE weighted max-min share formula, shared by every brokered
    budget (credit ledgers, the tier's hot budget): shares split
    ``budget`` by weight over the ACTIVE tenants only — usage > 0 or
    present in ``extra`` (waiters/requesters) — so idle tenants don't
    dilute the split, which is exactly what makes idle shares
    borrowable."""
    active: Dict[str, int] = {}
    if qos is not None:
        for t in qos.tenants():
            if usage.get(t.name, 0) > 0:
                active[t.name] = t.weight
        for name, t in (extra or {}).items():
            active.setdefault(name, t.weight)
    total = sum(active.values())
    if total <= 0:
        return {}
    return {name: budget * w / total for name, w in active.items()}


class CreditLedger:
    """Per-tenant credit accounting over one byte budget.  NOT
    self-locking: every method runs under the owning pool's condition
    (the broker's injected cv, or the decode pool's own) — the ledger
    is the policy, the caller owns the mutual exclusion."""

    __slots__ = ("name", "budget", "free", "qos", "quota_inflight",
                 "_used")

    def __init__(self, name: str, budget: int, qos=None,
                 quota_inflight: bool = False):
        self.name = name
        self.budget = max(int(budget), 1)
        self.free = self.budget
        # the tenant registry when QoS policy is on; None = plain
        # single-budget FIFO credits (the pre-QoS pools)
        self.qos = qos
        # enforce Tenant.max_inflight on this ledger (the reader
        # window's broker; serve/decode budgets have no per-tenant cap)
        self.quota_inflight = quota_inflight
        self._used: Dict[str, int] = {}

    def used(self, tenant: Optional[Tenant]) -> int:
        if tenant is None:
            return self.budget - self.free
        return self._used.get(tenant.name, 0)

    def shares(self, waiting: Optional[Dict[str, Tenant]] = None
               ) -> Dict[str, float]:
        """This budget's weighted max-min shares (see
        :func:`weighted_shares`); ``waiting`` marks tenants active."""
        return weighted_shares(self.budget, self.qos, self._used,
                               waiting)

    def can_take(self, tenant: Optional[Tenant], cost: int,
                 waiting: Optional[Dict[str, Tenant]] = None) -> bool:
        """Grant policy for one acquisition.  Work-conserving: a
        tenant under its share (or with nothing in flight — every
        tenant can always run ONE item) takes freely; a tenant over
        its share may keep borrowing only while no OTHER tenant is
        deprived (waiting with usage below its own share) — that
        pause is the reclaim-on-demand."""
        if self.free < cost:
            return False
        if self.qos is None or tenant is None:
            return True
        used = self._used.get(tenant.name, 0)
        if (self.quota_inflight and tenant.max_inflight > 0
                and used + cost > max(tenant.max_inflight, cost)):
            return False
        shares = self.shares(waiting)
        if used == 0 or used + cost <= shares.get(tenant.name, 0):
            return True
        for name, t in (waiting or {}).items():
            if name == tenant.name:
                continue
            if self._used.get(name, 0) < shares.get(name, 0):
                return False  # reclaim: the deprived waiter goes first
        return True

    def take(self, tenant: Optional[Tenant], cost: int) -> None:
        self.free -= cost
        if tenant is not None:
            self._used[tenant.name] = (
                self._used.get(tenant.name, 0) + cost
            )
            counter("qos_granted_bytes_total", pool=self.name,
                    tenant=tenant.name).inc(cost)
            gauge("qos_in_flight_bytes", pool=self.name,
                  tenant=tenant.name).inc(cost)

    def put(self, tenant: Optional[Tenant], cost: int) -> None:
        self.free = min(self.budget, self.free + cost)
        if tenant is not None:
            left = max(0, self._used.get(tenant.name, 0) - cost)
            if left:
                self._used[tenant.name] = left
            else:
                self._used.pop(tenant.name, None)
            gauge("qos_in_flight_bytes", pool=self.name,
                  tenant=tenant.name).dec(cost)


class _Waiter:
    __slots__ = ("cost", "tenant", "cls", "t0", "granted")

    def __init__(self, cost: int, tenant: Optional[Tenant], cls: str):
        self.cost = cost
        self.tenant = tenant
        self.cls = cls
        self.t0 = time.monotonic()
        self.granted = False


class WeightedCreditBroker:
    """Blocking credit gate over a :class:`CreditLedger` with explicit
    FIFO handoff, priority classes, and aging.  The condition variable
    is INJECTED by the owning pool (node.py / manager.py create it via
    ``dbg_condition`` so the rank lands in the caller's hierarchy)."""

    def __init__(self, name: str, budget: int, cv, qos=None,
                 classed: bool = False, aging_ms: int = 100,
                 quota_inflight: bool = False, wait_counter=None):
        self.name = name
        self.ledger = CreditLedger(
            name, budget, qos=qos, quota_inflight=quota_inflight
        )
        self._cv = cv
        self._classed = bool(classed) and qos is not None
        self._aging_s = max(aging_ms, 0) / 1000.0
        self._waiters: List[_Waiter] = []
        self._pumps: List = []
        self._stopped = False
        # bumped on every release: a NON-BLOCKING acquirer that was
        # denied compares this across its deny-and-requeue window to
        # detect a release whose pump ran before the requeue was
        # visible (the lost-wakeup race), and retries itself
        self.release_seq = 0
        # the owning pool's legacy credit-wait counter (kept so the
        # pre-QoS series keep reporting), plus per-tenant wait time
        self._wait_counter = wait_counter

    @property
    def budget(self) -> int:
        return self.ledger.budget

    @property
    def free(self) -> int:
        with self._cv:
            return self.ledger.free

    def clamp(self, cost: int) -> int:
        """An acquisition larger than the whole budget clamps to it
        and runs alone rather than deadlocking (every pool's
        oversized-item contract)."""
        return min(max(int(cost), 0), self.ledger.budget)

    # -- blocking acquire ---------------------------------------------------
    def acquire(self, cost: int, tenant: Optional[Tenant] = None,
                cls: str = BULK) -> bool:
        """Block until granted (FIFO within (class, tenant), classes
        and shares permitting) or the broker stops; returns False only
        on stop.  Safe to call with no other lock held ONLY."""
        cost = self.clamp(cost)
        waited_t0 = None
        with self._cv:
            w = _Waiter(cost, tenant, cls)
            self._waiters.append(w)
            self._grant_locked()
            while not w.granted and not self._stopped:
                if waited_t0 is None:
                    waited_t0 = time.monotonic()
                    if self._wait_counter is not None:
                        self._wait_counter.inc()
                    if RECORDER.enabled:
                        fr_event(
                            "qos", "credit_block",
                            pool=self.name, bytes=cost,
                            tenant=tenant.name if tenant else "",
                        )
                self._cv.wait(timeout=0.5)
                self._grant_locked()  # periodic re-scan drives aging
            self._waiters.remove(w)
            if w.granted and self._stopped:
                # stop raced the grant: nothing will run — return it
                self.ledger.put(tenant, cost)
                return False
            granted = w.granted
        if waited_t0 is not None and tenant is not None:
            counter("qos_credit_wait_ms_total", pool=self.name,
                    tenant=tenant.name).inc(
                int((time.monotonic() - waited_t0) * 1000)
            )
        return granted

    def try_acquire(self, cost: int, tenant: Optional[Tenant] = None,
                    cls: str = BULK) -> bool:
        """Non-blocking acquire: joins the waiter list for one grant
        scan (so it cannot bypass an earlier waiter of its own class +
        tenant) and leaves immediately if not granted."""
        cost = self.clamp(cost)
        with self._cv:
            if self._stopped:
                return False
            w = _Waiter(cost, tenant, cls)
            self._waiters.append(w)
            self._grant_locked()
            self._waiters.remove(w)
            granted = w.granted
        if not granted and RECORDER.enabled:
            fr_event(
                "qos", "credit_block",
                pool=self.name, bytes=cost,
                tenant=tenant.name if tenant else "",
            )
        return granted

    def release(self, cost: int, tenant: Optional[Tenant] = None) -> None:
        with self._cv:
            self.ledger.put(tenant, self.clamp(cost))
            self.release_seq += 1
            self._grant_locked()
            pumps = list(self._pumps)
        # pumps run OUTSIDE the broker lock: a non-blocking acquirer
        # (the reader window) re-pumps its pending queue from here
        for fn in pumps:
            try:
                fn()
            except BaseException:  # pump must never poison a release
                pass

    # -- pumps (non-blocking acquirers) -------------------------------------
    def add_pump(self, fn) -> None:
        with self._cv:
            if fn not in self._pumps:
                self._pumps.append(fn)

    def remove_pump(self, fn) -> None:
        with self._cv:
            try:
                self._pumps.remove(fn)
            except ValueError:
                pass

    # -- grant scan (cv held) ------------------------------------------------
    def _effective_hi(self, w: _Waiter, now: float) -> bool:
        return w.cls == INTERACTIVE or (
            self._aging_s > 0 and now - w.t0 >= self._aging_s
        )

    def _grant_locked(self) -> None:
        if not self._waiters:
            return
        now = time.monotonic()
        if self._classed:
            hi = [w for w in self._waiters if self._effective_hi(w, now)]
            lo = [w for w in self._waiters
                  if not self._effective_hi(w, now)]
            order = hi + lo
        else:
            order = self._waiters
        waiting = {
            w.tenant.name: w.tenant
            for w in self._waiters
            if w.tenant is not None and not w.granted
        }
        blocked: set = set()
        granted_any = False
        for w in order:
            if w.granted:
                continue
            if self.ledger.qos is None:
                key = ""  # plain mode: STRICT FIFO — no bypass at all
            else:
                # FIFO within the DECLARED (class, tenant) stream —
                # aging must not change the key, or an aged bulk
                # waiter would stop blocking fresh same-stream
                # waiters and could be bypassed forever (the exact
                # starvation this broker exists to fix)
                key = (
                    w.cls if self._classed else "",
                    w.tenant.name if w.tenant is not None else "",
                )
            if key in blocked:
                continue  # FIFO within (class, tenant)
            if self.ledger.can_take(w.tenant, w.cost, waiting):
                self.ledger.take(w.tenant, w.cost)
                w.granted = True
                granted_any = True
                if w.tenant is not None:
                    waiting.pop(w.tenant.name, None)
            else:
                blocked.add(key)
                if (self._effective_hi(w, now)
                        and self.ledger.free < w.cost):
                    # an AGED (or interactive) head short of raw
                    # credits becomes a barrier: nothing behind it may
                    # drain the freed credits it is accumulating —
                    # bounded starvation for clamped oversized work.
                    # Policy blocks (over-share while a deprived
                    # tenant waits) deliberately do NOT barrier: the
                    # deprived waiter behind must stay grantable or
                    # the reclaim could livelock.
                    break
        if granted_any:
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class ClassedTaskQueue:
    """Pool-worker task queue with interactive-before-bulk dequeue and
    anti-starvation aging; unclassed (the default) it is a plain FIFO
    — byte-identical ordering to the ``queue.Queue`` it replaces.
    ``None`` items are worker-stop sentinels and always dequeue LAST
    (after real work drains), like the pools' stop paths expect.  The
    condition is injected by the owner (rank lands at its creation
    site)."""

    def __init__(self, cv, classed: bool = False, aging_ms: int = 100):
        self._cv = cv
        self._classed = bool(classed)
        self._aging_s = max(aging_ms, 0) / 1000.0
        self._hi: deque = deque()
        self._lo: deque = deque()
        self._sentinels = 0

    def put(self, item, cls: str = BULK) -> None:
        if item is None:
            self.put_sentinel()
            return
        with self._cv:
            q = (
                self._hi if (self._classed and cls == INTERACTIVE)
                else self._lo
            )
            q.append((time.monotonic(), item))
            self._cv.notify_all()

    def put_sentinel(self) -> None:
        with self._cv:
            self._sentinels += 1
            self._cv.notify_all()

    def get(self):
        """Pop the next task by class policy; ``None`` = stop."""
        with self._cv:
            while True:
                item = self._pop_locked()
                if item is not _EMPTY:
                    return item
                if self._sentinels > 0:
                    self._sentinels -= 1
                    return None
                self._cv.wait()

    def _pop_locked(self):
        if self._classed and self._lo and self._aging_s > 0:
            # aged bulk head outranks fresh interactive work: bulk
            # class never starves behind a steady interactive stream
            if time.monotonic() - self._lo[0][0] >= self._aging_s:
                return self._lo.popleft()[1]
        if self._hi:
            return self._hi.popleft()[1]
        if self._lo:
            return self._lo.popleft()[1]
        return _EMPTY

    def drain_nowait(self) -> list:
        """Pop every queued task without blocking (pool stop path)."""
        with self._cv:
            items = [it for _t, it in self._hi]
            items += [it for _t, it in self._lo]
            self._hi.clear()
            self._lo.clear()
            return items

    def qsize(self) -> int:
        with self._cv:
            return len(self._hi) + len(self._lo)


__all__ = [
    "BULK",
    "INTERACTIVE",
    "ClassedTaskQueue",
    "CreditLedger",
    "WeightedCreditBroker",
]
