"""Live metrics scrape endpoint (conf ``metricsHttpPort``).

PR 1's observability plane dumped the registry ONCE, at manager stop
(``metricsPromPath``/``metricsJsonPath``) — useless for watching a
live node's tenants contend.  This module serves the same exporters
over HTTP for the node's lifetime:

- ``GET /metrics``       — Prometheus text exposition (the scrape
  target; ``metrics/export.to_prometheus``), per-tenant labels on the
  brokered instruments included,
- ``GET /metrics.json``  — the registry snapshot as JSON (what
  ``tools/metrics_report.py`` renders),
- ``GET /tenants``       — the QoS tenant registry snapshot (weights,
  priorities, quotas, degraded flags),
- ``GET /health``        — liveness probe: 200 with uptime/pid JSON,
- ``GET /flightrecorder`` — on-demand flight-recorder snapshot
  (obs/recorder.py), the same JSON shape the automatic failure dumps
  write.

One daemon thread (``metrics-http-<port>``) runs a plain
``http.server`` loop — scrapes serialize, which is exactly right for
an exposition endpoint; the server binds in the constructor (port 0 =
ephemeral, for tests and one-off runs) and ``stop()`` shuts it down
synchronously so ``transport_census`` sees no leaked thread after
manager teardown.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Optional, Tuple

from sparkrdma_tpu.metrics import get_registry
from sparkrdma_tpu.metrics.export import to_prometheus
from sparkrdma_tpu.obs import RECORDER
from sparkrdma_tpu.qos.registry import get_qos

logger = logging.getLogger(__name__)


class _ScrapeHandler(BaseHTTPRequestHandler):
    # close per request: a scraper holding keep-alive open would pin
    # the single serving thread and starve the next scrape
    protocol_version = "HTTP/1.0"

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        path = self.path.split("?", 1)[0]
        try:
            if path in ("/metrics", "/"):
                body = to_prometheus(get_registry()).encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = json.dumps(
                    get_registry().snapshot(), indent=1
                ).encode("utf-8")
                ctype = "application/json"
            elif path == "/tenants":
                body = json.dumps(
                    get_qos().snapshot(), indent=1
                ).encode("utf-8")
                ctype = "application/json"
            elif path == "/health":
                started = getattr(self.server, "started_at", None)
                body = json.dumps({
                    "status": "ok",
                    "pid": os.getpid(),
                    "uptime_s": round(
                        time.time() - started, 3
                    ) if started is not None else None,
                }).encode("utf-8")
                ctype = "application/json"
            elif path == "/flightrecorder":
                snap = RECORDER.snapshot() if RECORDER.enabled else {
                    "enabled": False, "planes": {},
                }
                body = json.dumps(snap).encode("utf-8")
                ctype = "application/json"
            else:
                self.send_error(404, "unknown path")
                return
        except BaseException:
            logger.exception("metrics scrape failed")
            self.send_error(500, "scrape failed")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args) -> None:
        logger.debug("metrics-http: " + fmt, *args)


class MetricsHttpServer:
    """Always-on scrape endpoint over the process-global registries.
    Binds in the constructor (raises ``OSError`` on a taken port so
    the caller can log-and-continue); ``stop()`` is synchronous and
    idempotent."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._server = HTTPServer((host, port), _ScrapeHandler)
        self._server.started_at = time.time()  # /health uptime anchor
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.2},
            daemon=True,
            name=f"metrics-http-{self.address[1]}",
        )
        self._thread.start()
        logger.info(
            "metrics scrape endpoint on http://%s:%d/metrics",
            self.address[0], self.address[1],
        )

    @property
    def port(self) -> int:
        return self.address[1]

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.address[0]}:{self.address[1]}{path}"

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._server.shutdown()
        t.join(timeout=5.0)
        self._server.server_close()


__all__ = ["MetricsHttpServer"]
