"""Streaming write-side sketches: partition balance and hot keys.

Both sketches live on the writer's record path, so the budget is a few
integer ops per record (or per ``skewSampleStride`` records for the key
sketch).  Neither allocates proportionally to the data: the partition
sketch is two flat arrays indexed by partition id, and the heavy-hitter
sketch is classic Misra-Gries — ``k`` counters guarantee any key with
frequency share above ``1/(k+1)`` of the sampled stream survives, which
is exactly the "is one KEY responsible for this hot partition?"
question the telemetry wants answered.  Sizes are not sketched here:
the writer already knows exact per-partition byte counts at commit
(they become block lengths), so only record counts and key identity
need streaming treatment.
"""

from __future__ import annotations

from array import array
from typing import Any, Dict, List, Optional, Tuple


class PartitionSketch:
    """Per-partition record counters for one map task (single-threaded
    writer path — no lock)."""

    __slots__ = ("num_partitions", "_records", "total_records")

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions
        self._records = array("q", [0]) * num_partitions
        self.total_records = 0

    def add(self, partition_id: int, n: int = 1) -> None:
        self._records[partition_id] += n
        self.total_records += n

    def records(self) -> List[int]:
        return list(self._records)

    def max_records(self) -> int:
        return max(self._records) if self.num_partitions else 0


class HeavyHitterSketch:
    """Misra-Gries top-k frequency sketch over (sampled) keys.

    ``add`` is O(1) amortised; the decrement sweep fires only when all
    ``k`` slots are full and an unseen key arrives.  ``top`` reports
    estimated shares of the SAMPLED stream — with a uniform
    ``skewSampleStride`` the share is an unbiased estimate of the true
    key share, and the classic error bound (count undercounts by at
    most ``sampled/(k+1)``) keeps the reported share within ``1/(k+1)``
    of truth.
    """

    __slots__ = ("capacity", "_counts", "sampled")

    def __init__(self, capacity: int = 32):
        self.capacity = max(1, capacity)
        self._counts: Dict[Any, int] = {}
        self.sampled = 0

    def add(self, key: Any, weight: int = 1) -> None:
        self.sampled += weight
        c = self._counts
        if key in c:
            c[key] += weight
            return
        if len(c) < self.capacity:
            c[key] = weight
            return
        # decrement-all: evict keys whose counter hits zero
        dec = min(weight, min(c.values()))
        for k in list(c):
            c[k] -= dec
            if c[k] <= 0:
                del c[k]
        if weight > dec:
            c[key] = weight - dec

    def top(self, n: int = 5) -> List[Tuple[Any, float]]:
        """The ``n`` heaviest keys as (key, estimated share of sampled
        stream), heaviest first."""
        if not self.sampled:
            return []
        items = sorted(self._counts.items(), key=lambda kv: -kv[1])[:n]
        return [(k, v / self.sampled) for k, v in items]

    def top_share(self) -> float:
        """Estimated share of the single hottest key (0.0 if nothing
        sampled)."""
        t = self.top(1)
        return t[0][1] if t else 0.0


def median(values: List[int]) -> Optional[int]:
    """Median of a small list (lower of the two middles for even
    length — a conservative skew denominator). None on empty."""
    if not values:
        return None
    s = sorted(values)
    return s[(len(s) - 1) // 2]
