"""Skew-adaptive partitioning: hot-partition detection at write time,
sub-block splitting at serializer frame boundaries, and balanced fetch.

Every bench in the suite shuffles uniform keys, but real traffic is
Zipfian — one hot partition serializes the whole reduce side no matter
how fast the fabric is (ROADMAP item 4).  The striped transport (PR 3),
delta-synced block locations (PR 7) and the k-way merge (PR 5) already
provide every mechanism a mitigation layer needs; this package is the
subsystem that DETECTS skew and DRIVES them — the same "mediate above
the transport, don't change the wire" posture as *RDMAvisor*
(PAPERS.md), with *RDMAbox*'s hot/cold load-balancing instincts:

- :mod:`~sparkrdma_tpu.skew.registry` — the process-global
  :class:`SkewRegistry` (the qos/metrics registry shape, flipped by
  conf ``spark.shuffle.tpu.skewEnabled`` before the manager builds its
  node): enablement plus per-shuffle detection/split accounting for
  ``metrics_report.py``'s skew table.
- :mod:`~sparkrdma_tpu.skew.sketch` — the writer's streaming
  per-partition size/record sketch (cheap counters on the existing
  write path) and a Misra-Gries heavy-hitter sketch sampling keys on
  aggregating shuffles (hot-KEY attribution in telemetry).
- :mod:`~sparkrdma_tpu.skew.splitter` — commit-time classification
  (``skewSplitThreshold`` absolute bytes, or ``skewSplitFactor`` x the
  map output's median non-empty partition) and the frame-boundary
  split: ``frame_spans`` (PR 5) walks serializer headers only, so a
  hot partition's payload splits into N independently-deserializable,
  independently-SORTED sub-ranges of the committed segment — zero data
  movement, zero wire-format change.

Sub-blocks register as distinct entries in the map-output table past
the logical partition space (the split partition's own entry becomes a
marker naming its sub-entry range), ship over the PR 7 epoch/delta-sync
publish plane unchanged, and ride the reader's stripe/lane machinery as
ordinary blocks — interleaved across the fetch plan so hot-partition
bytes spread over lanes and serve-pool credits instead of queueing
behind one giant read.  The k-way merge treats each sub-block as one
more sorted run, in sub-index order, so output is bit-exact with the
unsplit path by the PR 5 stable-merge argument.  ``skewEnabled=false``
is an identity no-op (the qosEnabled=false precedent).
"""

from sparkrdma_tpu.skew.registry import SkewRegistry, get_skew
from sparkrdma_tpu.skew.sketch import HeavyHitterSketch, PartitionSketch
from sparkrdma_tpu.skew.splitter import (
    SPLIT_MKEY,
    collapse_sub_locations,
    is_split_marker,
    plan_commit_splits,
    split_targets,
    sub_spans,
)

__all__ = [
    "SPLIT_MKEY",
    "HeavyHitterSketch",
    "PartitionSketch",
    "SkewRegistry",
    "collapse_sub_locations",
    "get_skew",
    "is_split_marker",
    "plan_commit_splits",
    "split_targets",
    "sub_spans",
]
