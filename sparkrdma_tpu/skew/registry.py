"""Process-global skew registry (the skew control plane).

One :class:`SkewRegistry` per process (the metrics-registry /
tenant-registry shape): managers flip ``enabled`` from conf
``skewEnabled`` before building their node, writers consult it at
commit, and it accumulates per-shuffle detection/split accounting for
``tools/metrics_report.py``'s skew table and the tests.  All state is
bookkeeping — the split decisions themselves live in
:mod:`~sparkrdma_tpu.skew.splitter` (pure functions of sizes + conf),
so disabled runs never take this module's lock on a hot path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from sparkrdma_tpu.metrics import counter, histogram


class SkewRegistry:
    """Enablement + per-shuffle split accounting."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.Lock()  # lock-order: 93
        # shuffle_id -> accumulated split stats across its map tasks
        self._shuffles: Dict[int, Dict[str, float]] = {}  # guarded-by: _lock

    def record_commit(
        self, shuffle_id: int, sizes: List[int],
        split_plan: Optional[Dict[int, list]] = None,
        hot_key_share: float = 0.0,
        records: Optional[List[int]] = None,
    ) -> Dict[str, float]:
        """Fold one map task's commit into the shuffle's skew stats:
        partition-size distribution (the detection histogram), split
        decisions, and the aggregating writer's hot-key share.  Returns
        the per-task snapshot so the caller can ship it as telemetry."""
        nonzero = [n for n in sizes if n > 0]
        h = histogram("skew_partition_bytes")
        for n in nonzero:
            h.observe(n)
        split_plan = split_plan or {}
        split_bytes = sum(
            sizes[pid] for pid in split_plan if pid < len(sizes)
        )
        sub_blocks = sum(len(v) for v in split_plan.values())
        snap: Dict[str, float] = {
            "partitions": len(sizes),
            "partitions_nonzero": len(nonzero),
            "partition_bytes_sum": sum(nonzero),
            "max_partition_bytes": max(nonzero) if nonzero else 0,
            "partitions_split": len(split_plan),
            "sub_blocks": sub_blocks,
            "split_bytes": split_bytes,
            "max_hot_key_share_pct": round(hot_key_share * 100, 2),
        }
        if records is not None:
            snap["max_partition_records"] = max(records) if records else 0
        if split_plan:
            counter("skew_partitions_split_total").inc(len(split_plan))
            counter("skew_sub_blocks_total").inc(sub_blocks)
            counter("skew_split_bytes_total").inc(split_bytes)
            hf = histogram("skew_split_fanout")
            for subs in split_plan.values():
                hf.observe(len(subs))
        with self._lock:
            d = self._shuffles.setdefault(shuffle_id, {})
            for k, v in snap.items():
                if k.startswith("max_"):
                    d[k] = max(d.get(k, 0), v)
                else:
                    d[k] = d.get(k, 0) + v
        return snap

    def shuffle_stats(self, shuffle_id: int) -> Dict[str, float]:
        with self._lock:
            return dict(self._shuffles.get(shuffle_id, {}))

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "shuffles": {
                    sid: dict(d) for sid, d in self._shuffles.items()
                },
            }

    def release_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.pop(shuffle_id, None)

    def reset(self) -> None:
        """Drop all accounting (tests)."""
        with self._lock:
            self._shuffles.clear()


# the process-global registry; managers enable it from conf skewEnabled
GLOBAL_SKEW = SkewRegistry(enabled=False)


def get_skew() -> SkewRegistry:
    return GLOBAL_SKEW
