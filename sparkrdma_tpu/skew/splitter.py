"""Commit-time sub-block planning: which partitions split, and where.

All pure functions of (sizes, payload bytes, conf) — the writer calls
:func:`plan_commit_splits` once per commit and hands the resulting span
plan to the resolver, which registers each span as its own map-output
entry.  Splits land ONLY at serializer frame boundaries
(``Serializer.frame_spans``, a header-only walk), so every sub-block is
an independently-deserializable, independently-sorted contiguous range
of the already-committed segment: no bytes move, and the reader's
k-way merge can treat each one as an ordinary sorted run.

Table encoding (zero wire change — the publish plane just sees a wider
table): a split partition's primary entry becomes a MARKER
``BlockLocation(address=aux_start_index, length=num_subs,
mkey=SPLIT_MKEY)`` and the real sub-block locations occupy aux table
rows ``[aux_start_index, aux_start_index + num_subs)`` past the logical
partition count.  mkey 0 is reserved-invalid and real mkeys are
non-negative, so ``SPLIT_MKEY = -2`` can never collide with a
registered memory region; ``length=num_subs >= 2`` keeps markers
distinct from empty entries (``length == 0``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.skew.sketch import median
from sparkrdma_tpu.utils.types import BlockLocation

# Marker mkey for a split partition's primary table entry.  Never a
# valid memory-region key (those are >= 0; 0 itself means "empty").
SPLIT_MKEY = -2

Span = Tuple[int, int]  # (relative offset, length) within the partition


def is_split_marker(loc: BlockLocation) -> bool:
    """True when a map-output entry is a sub-block marker rather than a
    fetchable block: address = first aux row, length = sub count."""
    return loc.mkey == SPLIT_MKEY


def make_marker(aux_start: int, num_subs: int) -> BlockLocation:
    return BlockLocation(address=aux_start, length=num_subs,
                         mkey=SPLIT_MKEY)


def split_targets(
    sizes: Sequence[int], threshold: int, factor: float, max_subs: int,
) -> List[int]:
    """Partition ids classified hot at commit: at or over the absolute
    ``threshold`` bytes, or at or over ``factor`` x the median
    non-empty partition size (relative detection disabled when
    ``factor <= 0``)."""
    if max_subs < 2 or threshold <= 0:
        return []
    med = median([n for n in sizes if n > 0])
    rel_cutoff = int(factor * med) if (factor > 0 and med) else None
    return [
        pid for pid, n in enumerate(sizes)
        if n > 0 and (
            n >= threshold
            or (rel_cutoff is not None and n >= rel_cutoff)
        )
    ]


def sub_spans(
    frame_spans: Sequence[Tuple[int, int]], target: int, max_subs: int,
) -> Optional[List[Span]]:
    """Group a partition's serializer frames into contiguous sub-block
    spans of at most ``target`` bytes each (a frame larger than the
    target gets a span of its own — frames are indivisible).  Greedy
    left-to-right packing; once ``max_subs - 1`` spans are cut, the
    final span absorbs the remainder.  Returns None when the payload
    cannot yield at least two sub-blocks (single frame, or everything
    fits one target)."""
    if len(frame_spans) < 2 or max_subs < 2 or target <= 0:
        return None
    out: List[Span] = []
    run_start = frame_spans[0][0]
    run_end = run_start
    for (a, b) in frame_spans:
        if (
            run_end > run_start
            and run_end - run_start + (b - a) > target
            and len(out) < max_subs - 1
        ):
            out.append((run_start, run_end - run_start))
            run_start = a
        run_end = b
    out.append((run_start, run_end - run_start))
    if len(out) < 2:
        return None
    return out


def plan_commit_splits(
    serializer, payloads: Dict[int, object], sizes: Sequence[int], conf,
) -> Dict[int, List[Span]]:
    """The writer's one-call commit hook: classify hot partitions from
    exact committed ``sizes``, frame-walk only those payloads, and
    return ``{partition_id: [(rel_off, rel_len), ...]}`` for every
    partition that actually yields >= 2 sub-blocks.

    ``payloads`` maps partition id to the final contiguous bytes/view
    being committed; partitions absent from it (e.g. chunked or
    file-backed payloads) are never split.  The sub-block target is
    ``skewSplitThreshold`` clamped to half the partition, so a
    relative-detected partition below the absolute cutoff still splits
    in two.  Unparseable payloads are skipped, never fatal — an unsplit
    hot partition is correct, just slow."""
    threshold = conf.skew_split_threshold
    max_subs = conf.skew_max_sub_blocks
    targets = split_targets(
        sizes, threshold, conf.skew_split_factor, max_subs,
    )
    plan: Dict[int, List[Span]] = {}
    for pid in targets:
        payload = payloads.get(pid)
        if payload is None:
            continue
        try:
            frames = serializer.frame_spans(payload)
        except (ValueError, IndexError):
            continue
        target = min(threshold, -(-sizes[pid] // 2))
        spans = sub_spans(frames, target, max_subs)
        if spans is not None:
            plan[pid] = spans
    return plan


def collapse_sub_locations(subs: Sequence[BlockLocation]) -> BlockLocation:
    """Collapse a marker's sub-block entries back into one whole-span
    location for LOCAL reads: sub-spans tile the partition payload
    contiguously within one segment, so the original block is simply
    (first sub's address, total length).  Remote readers never need
    this — they fetch sub-blocks individually on purpose."""
    total = sum(s.length for s in subs)
    return BlockLocation(address=subs[0].address, length=total,
                         mkey=subs[0].mkey)
