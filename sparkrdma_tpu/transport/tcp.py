"""TCP transport: the real multi-process control/data plane backend.

Same connector interface as :class:`LoopbackNetwork`, over real sockets,
so driver and executors can live in separate processes or hosts.  On a
TPU pod the BULK shuffle plane rides ICI collectives (the TileExchange);
this backend carries what remains host-side — the control plane (the
five RPC message types) and the block-fetch path for executors outside
the mesh (spill-over, debugging, CPU-only deployments).

Mapping to the reference (RdmaNode.java / RdmaChannel.java):

- connect() plays the RDMA CM handshake: a 9-byte hello carrying the
  channel type and the caller's listening port, acked by the acceptor
  (CONNECT_REQUEST/ESTABLISHED, RdmaNode.java:114-214).
- OP_RPC frames are the two-sided SEND/RECV class; TCP supplies
  ordering and (via its window) flow control, so the software credit
  scheme of the loopback backend is not re-implemented here.
- OP_READ_REQ/RESP is the one-sided READ class: the acceptor serves
  registered-memory reads on the node's dedicated bulk pool — the
  application's receive listener is never involved, preserving the
  "remote CPU does not run app code to serve reads" split (the NIC's
  role in RdmaChannel.java:441-474; here dedicated service threads,
  kept off both the reader loop and the control-plane dispatcher).

Framing: every message is ``1B opcode + 4B LE length + payload``.
Read requests carry ``8B req_id + 4B count + count × (8B address,
4B length, 4B mkey)``; responses carry ``8B req_id + 1B status`` then
either ``count × (4B len + bytes)`` or an error string.

The 9-byte connect hello carries the protocol version
(``WIRE_VERSION``): ``4B magic + 1B channel type + 2B src port +
2B version``.  A version mismatch is rejected STRUCTURALLY — the
acceptor answers ``\\x00`` plus ``<HH`` (its version, the hello's
version) instead of the ``\\x01`` ack, so both sides can name both
versions in the error instead of desyncing mid-stream.
"""

from __future__ import annotations

import errno
import logging
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter, gauge, histogram
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    TransportError,
    decode_remote_error,
    encode_remote_error,
)
from sparkrdma_tpu.transport.node import Address, Node
from sparkrdma_tpu.utils import wiredbg
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)

_MAGIC = b"STPU"
_HDR = struct.Struct("<BI")          # opcode, payload length
_HELLO = struct.Struct("<4sBHH")     # magic, channel type, src port, version
_HELLO_REJ = struct.Struct("<HH")    # (acceptor's version, hello's version)
_REQ_HDR = struct.Struct("<QI")      # req_id, location count
_LOC = struct.Struct("<QII")         # address, length, mkey
_RESP_HDR = struct.Struct("<QB")     # req_id, status
_LEN = struct.Struct("<I")
_TRACE_CTX = struct.Struct("<QQ")    # optional read-req tail: trace, span id

#: Wire protocol generation carried in the connect hello.  Bump on any
#: incompatible change to framing or message layout.  v2 adds the
#: OPTIONAL trace-context tail to read requests and the trace fields on
#: fetch-status/prefetch RPCs (rpc/messages.py ``since=2`` fields).
#: v3 adds the push-based merged shuffle messages (PushSubBlockMsg /
#: FetchMergeStatusMsg / MergeStatusResponseMsg, types 13-15): push
#: senders gate on the channel's negotiated generation, so pre-v3
#: peers simply never merge and every block rides the pull path.
#: Acceptors take any hello in [MIN_WIRE_VERSION, WIRE_VERSION]; a
#: hello above/below that range is rejected STRUCTURALLY with both
#: versions named (pre-versioning peers sent 0 in this slot, so they
#: reject cleanly too).  The connector, NAKed by an older acceptor
#: whose version it can still speak, re-dials at the acceptor's
#: generation — the negotiated fallback — and records the channel's
#: ``wire_version`` so v2-only bytes stay off that channel.
WIRE_VERSION = 3

#: Oldest wire generation this build still speaks (for both accepting
#: older hellos and downgrading its own).
MIN_WIRE_VERSION = 1

OP_RPC = 1
OP_READ_REQ = 2
OP_READ_RESP = 3

_TYPE_BY_INDEX = list(ChannelType)

# what the acceptor's side of each connection is called
_PAIRED = {
    ChannelType.RPC_REQUESTOR: ChannelType.RPC_RESPONDER,
    ChannelType.RPC_WRAPPER: ChannelType.RPC_WRAPPER,
    ChannelType.READ_REQUESTOR: ChannelType.READ_RESPONDER,
}

_MAX_FRAME = 1 << 30

# iovec batch per sendmsg call (IOV_MAX is ≥1024 on Linux; stay well
# under it — grouped fetches of many blocks produce many segments)
_IOV_MAX = 256


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed by peer")
        buf += chunk
    return bytes(buf)


def _discard_exact(sock: socket.socket, n: int) -> None:
    """Consume and drop n payload bytes (a response whose request raced
    teardown) without materializing the frame."""
    while n:
        chunk = sock.recv(min(n, 1 << 16))
        if not chunk:
            raise TransportError("connection closed by peer")
        n -= len(chunk)


def _as_view(buf) -> memoryview:
    """Flat byte view over any contiguous buffer (bytes, bytearray,
    uint8 ndarray, memoryview) — what sendmsg/recv_into consume."""
    v = buf if isinstance(buf, memoryview) else memoryview(buf)
    if v.format != "B" or v.ndim != 1:
        v = v.cast("B")
    return v


def build_read_response_parts(node, payload: bytes, peer) -> Optional[List]:
    """Resolve one OP_READ_REQ into the scatter-gather response parts
    (header + length prefixes + the resolved block VIEWS — registered
    memory is never copied into an intermediate buffer), or the scoped
    error reply.  Returns None when not even a req_id is parseable
    (logged; the channel stays healthy).  Shared by the threaded serve
    path and the async dispatcher's completion-driven one."""
    try:
        req_id, count = _REQ_HDR.unpack_from(payload, 0)
    except Exception:
        logger.warning(
            "malformed read request from %s (%dB)", peer, len(payload),
        )
        return None
    try:
        # the count must agree byte-for-byte with the payload BEFORE it
        # sizes the location loop — a lying count becomes a scoped
        # error reply, not a struct.error mid-parse.  v2 requests may
        # carry the optional trace-context tail after the locations.
        base = _REQ_HDR.size + count * _LOC.size
        if count < 0 or len(payload) not in (base, base + _TRACE_CTX.size):
            raise ValueError(
                f"read request count {count} disagrees with payload "
                f"{len(payload)}B"
            )
        locs = []
        off = _REQ_HDR.size
        for _ in range(count):
            addr, length, mkey = _LOC.unpack_from(payload, off)
            off += _LOC.size
            locs.append(BlockLocation(addr, length, mkey))
        if FAULTS.enabled:
            FAULTS.check("serve_delay")
            FAULTS.check("serve")
        t0 = time.monotonic()
        blocks = node.read_local_blocks(locs)
        if RECORDER.enabled:
            ctx = _req_trace(payload)
            fr_event(
                "transport", "serve_read",
                trace_id=ctx[0] if ctx else 0,
                span_id=ctx[1] if ctx else 0,
                blocks=len(locs),
                us=int((time.monotonic() - t0) * 1e6),
            )
        parts: List = [_RESP_HDR.pack(req_id, 0)]
        for b in blocks:
            v = _as_view(b)
            parts.append(_LEN.pack(v.nbytes))
            parts.append(v)
    except BaseException as e:
        parts = [
            _RESP_HDR.pack(req_id, 1),
            encode_remote_error(e).encode("utf-8", "replace"),
        ]
    return parts


def _req_cost(payload: bytes) -> int:
    """Total requested bytes of one OP_READ_REQ — the serve pool's
    admission cost (credits bound resident serve memory).  Runs on the
    channel reader thread, so a malformed request must cost 0, not
    kill the channel — the serve path answers it with a scoped error
    reply (or logs, when even the req_id is unparseable)."""
    try:
        _req_id, count = _REQ_HDR.unpack_from(payload, 0)
        off = _REQ_HDR.size
        total = 0
        for _ in range(count):
            total += _LOC.unpack_from(payload, off)[1]
            off += _LOC.size
        return total
    except Exception:
        return 0


def _req_trace(payload: bytes) -> Optional[Tuple[int, int]]:
    """The (trace_id, span_id) tail of one OP_READ_REQ, or None — v1
    frames, trace-off requesters, and malformed payloads all land on
    None (the tail is strictly optional on the wire)."""
    try:
        _req_id, count = _REQ_HDR.unpack_from(payload, 0)
        base = _REQ_HDR.size + count * _LOC.size
        if count < 0 or len(payload) != base + _TRACE_CTX.size:
            return None
        tid, sid = _TRACE_CTX.unpack_from(payload, base)
        return (tid, sid) if tid else None
    except Exception:
        return None


def _req_mkey(payload: bytes):
    """First target mkey of one OP_READ_REQ — the serve pool resolves
    the owning QoS tenant from it (every location of one grouped read
    belongs to one shuffle's output, so the first is representative).
    None for malformed/empty requests."""
    try:
        _req_id, count = _REQ_HDR.unpack_from(payload, 0)
        if count <= 0:
            return None
        return _LOC.unpack_from(payload, _REQ_HDR.size)[2]
    except Exception:
        return None


class TcpChannel(Channel):
    """One TCP connection; either endpoint can carry RPC frames, the
    acceptor side additionally serves block reads."""

    supports_scatter = True

    def __init__(self, channel_type: ChannelType, node: Node,
                 peer: Address, sock: socket.socket):
        super().__init__(channel_type, node.conf.send_queue_depth)
        self.node = node
        self.peer = peer
        # resource: tcp.fds (one socket fd per live channel)
        self._sock = sock
        # owns: tcp.fds -> _close_sock
        self._fd_tkt = ledger_acquire("tcp.fds")  # acquires: tcp.fds
        self._sg = (
            node.conf.transport_scatter_gather
            and hasattr(sock, "sendmsg")
        )
        self._send_lock = dbg_lock("tcp.send", 70)
        self._next_req = 1  # guarded-by: _reads_lock
        # req_id -> (count, listener, post time, dest, on_progress)
        self._reads: Dict[int, Tuple] = {}  # guarded-by: _reads_lock
        self._reads_lock = dbg_lock("tcp.reads", 68)
        self._reader: Optional[threading.Thread] = None
        self._m_bytes_sent = counter(
            "transport_bytes_sent_total", transport="tcp")
        self._m_bytes_recv = counter(
            "transport_bytes_received_total", transport="tcp")
        self._m_msgs_sent = counter(
            "transport_msgs_sent_total", transport="tcp")
        self._m_msgs_recv = counter(
            "transport_msgs_received_total", transport="tcp")
        self._m_read_rtt = histogram(
            "transport_read_rtt_ms", transport="tcp")
        self._m_fail_outstanding = counter(
            "transport_fail_outstanding_total", transport="tcp")
        self._m_sendmsg_bytes = counter(
            "transport_sendmsg_bytes_total", transport="tcp")
        self._m_sendall_bytes = counter(
            "transport_sendall_bytes_total", transport="tcp")

    # -- lifecycle ----------------------------------------------------------
    def start_reader(self) -> None:
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"tcp-{self.peer[0]}:{self.peer[1]}",
        )
        self._reader.start()

    def _close_sock(self) -> None:
        """Settle this channel's fd exactly once — ``stop()`` and the
        reader loop's peer-close path can both get here (the socket
        object makes the second ``close()`` harmless; the ledger ticket
        must still settle once, under the reads lock)."""
        with self._reads_lock:
            tkt, self._fd_tkt = self._fd_tkt, NOOP_TICKET
        tkt.release()  # releases: tcp.fds  # one-shot
        try:
            self._sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._close_sock()
        err = TransportError("channel stopped")
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
        for entry in reads:
            self._safe_fail(entry[1], err)
        super().stop()

    # -- sending ------------------------------------------------------------
    def _send_msg(self, opcode: int, parts) -> None:
        """Send one frame as a scatter-gather iovec — header, length
        prefixes and block views go to the socket WITHOUT being
        concatenated into an intermediate buffer (``parts`` is a
        sequence of buffer-likes).  ``transportScatterGather=off``
        falls back to the legacy concat+sendall wire path."""
        if FAULTS.enabled:
            FAULTS.check("send")
        views = [v for v in map(_as_view, parts) if v.nbytes]
        length = sum(v.nbytes for v in views)
        hdr = _HDR.pack(opcode, length)
        # blocking socket writes under _send_lock are THE POINT here:
        # this per-channel mutex serializes whole frames onto the wire
        # (interleaved sendmsg calls would shear frames).  It ranks
        # last among the TRANSPORT locks (70) so no transport lock can
        # be requested while a send is in flight (the 80+ ranks above
        # it are memory/metrics leaves).
        with self._send_lock:
            if self._sg:
                self._sendmsg_all([memoryview(hdr)] + views)  # noqa: CK02
            else:
                self._send_concat(hdr, views)  # noqa: CK02
        self._m_msgs_sent.inc()
        self._m_bytes_sent.inc(_HDR.size + length)

    def _sendmsg_all(self, views: List[memoryview]) -> None:
        """writev the iovec list, advancing across partial sends."""
        i = 0
        while i < len(views):
            n = self._sock.sendmsg(views[i:i + _IOV_MAX])
            if n <= 0:
                raise TransportError("sendmsg made no progress")
            self._m_sendmsg_bytes.inc(n)
            while n and i < len(views):
                v = views[i]
                if n >= v.nbytes:
                    n -= v.nbytes
                    i += 1
                else:
                    views[i] = v[n:]
                    n = 0

    def _send_concat(self, hdr: bytes, views: List[memoryview]) -> None:
        # pre-scatter-gather wire path (one concatenation copy +
        # sendall), kept behind transportScatterGather=off for A/B
        # measurement and exotic sockets without sendmsg
        payload = bytearray(hdr)
        for v in views:
            payload += v
        self._sock.sendall(payload)
        self._m_sendall_bytes.inc(len(payload))

    def _post_rpc(self, frames: List[bytes], listener: CompletionListener) -> None:
        def run():
            try:
                for frame in frames:
                    self._send_msg(OP_RPC, (frame,))
            except BaseException as e:
                self._error(e)
                self._fail(listener, e)
            else:
                self._complete(listener, None)
            finally:
                self._release_budget()

        self.node.submit(run)

    def _post_read(self, locations: List[BlockLocation],
                   listener: CompletionListener,
                   dest=None, on_progress=None, ctx=None) -> None:
        with self._reads_lock:
            req_id = self._next_req
            self._next_req += 1
            self._reads[req_id] = (
                len(locations), listener, time.monotonic(), dest,
                on_progress,
            )
        payload = bytearray(_REQ_HDR.pack(req_id, len(locations)))
        for loc in locations:
            payload += _LOC.pack(loc.address, loc.length, loc.mkey)
        if ctx is not None and self.wire_version != 1:
            # optional v2 tail; suppressed on channels negotiated down
            payload += _TRACE_CTX.pack(ctx[0], ctx[1])

        def run():
            try:
                self._send_msg(OP_READ_REQ, (payload,))
                if ctx is not None and RECORDER.enabled:
                    fr_event(
                        "transport", "wire_send",
                        trace_id=ctx[0], span_id=ctx[1],
                        locs=len(locations),
                    )
            except BaseException as e:
                with self._reads_lock:
                    self._reads.pop(req_id, None)
                self._error(e)
                self._fail(listener, e)
                self._release_budget()
            # budget released when the response (or teardown) arrives

        self.node.submit(run)

    # -- receiving ----------------------------------------------------------
    def _read_loop(self) -> None:
        g = gauge("transport_threads", role="tcp_reader")
        g.inc()
        try:
            while True:
                opcode, length = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
                if FAULTS.enabled:
                    # a recv fault models a desynced/cut stream: the
                    # channel dies, outstanding reads fail structured
                    FAULTS.check("recv")
                if length > _MAX_FRAME:
                    raise TransportError(f"oversized frame: {length}B")
                if wiredbg.wire_debug_enabled():
                    herr = wiredbg.header_error("tcp", opcode, length)
                    if herr is not None:
                        raise TransportError(f"wireDebug: {herr}")
                self._m_msgs_recv.inc()
                self._m_bytes_recv.inc(_HDR.size + length)
                if opcode == OP_READ_RESP:
                    # structured scatter receive: the frame is never
                    # materialized whole — blocks land in registered
                    # dest buffers (striped reassembly) or ONE pooled
                    # buffer (BufferReleasingInputStream analog via
                    # alloc_gc)
                    self._recv_read_resp(length)
                    continue
                payload = _recv_exact(self._sock, length) if length else b""
                if opcode == OP_RPC:
                    if (wiredbg.wire_debug_enabled()
                            and not wiredbg.rpc_frame_ok("tcp", payload)):
                        continue  # counted + logged; ONE frame dropped
                    self.node.dispatch_frame(self, payload)
                elif opcode == OP_READ_REQ:
                    # serve OFF the reader thread: one large read must
                    # not head-of-line-block further frames on this
                    # channel (the reference's CQ model has no such
                    # serialization — the NIC serves reads).  The serve
                    # pool, not the dispatcher: multi-MB serves must
                    # never starve heartbeat/RPC dispatch, and its
                    # byte credits bound resident serve memory
                    self.node.submit_serve(
                        self._serve_read, (payload, time.monotonic()),
                        _req_cost(payload), mkey=_req_mkey(payload),
                    )
                else:
                    # an unknown opcode means the byte stream is
                    # desynced — the CHANNEL must die (there is no way
                    # to find the next frame boundary), but it is
                    # counted and scoped: outstanding reads fail with
                    # a structured error and the node stays up
                    counter(
                        "wire_unknown_frames_total",
                        engine="tcp", kind="opcode",
                    ).inc()
                    raise TransportError(f"unknown opcode {opcode}")
        except BaseException as e:
            if self.state not in (ChannelState.STOPPED,):
                self._error(e)
                self._fail_outstanding(e)
                # a peer-initiated close (e.g. the requester evicting
                # its end) must not leak THIS end's fd until node
                # teardown: the reader thread is the socket's only
                # consumer, so it owns the close on its way out
                self._close_sock()
            # and a dead channel must not pin cache slots, the passive
            # list, or a stale read group for the node's lifetime
            self.node.on_channel_dead(self)
        finally:
            g.dec()

    def _recv_read_resp(self, length: int) -> None:
        """Receive one read response.  Striped reads (``dest`` buffers
        registered at post time) scatter straight into their
        destination row via ``recv_into`` — reassembly happens in the
        kernel copy, with no intermediate frame buffer; plain reads
        land in one pooled buffer and complete as zero-copy slices."""
        if FAULTS.enabled:
            FAULTS.check("read_resp")
        if length < _RESP_HDR.size:
            raise TransportError(f"short read response: {length}B")
        req_id, status = _RESP_HDR.unpack(
            _recv_exact(self._sock, _RESP_HDR.size)
        )
        body = length - _RESP_HDR.size
        with self._reads_lock:
            entry = self._reads.pop(req_id, None)
        if entry is None:
            _discard_exact(self._sock, body)  # raced with teardown
            return
        count, listener, t0, dest, on_progress = entry
        # the entry left _reads above, so _fail_outstanding no longer
        # covers it: ANY failure while the body is still on the wire
        # must fail this listener HERE, then re-raise so the read loop
        # tears the (now desynced) channel down
        try:
            if status != 0:
                reason = _recv_exact(self._sock, body).decode(
                    "utf-8", "replace"
                )
                err: BaseException = decode_remote_error(reason)
            elif dest is None:
                payload = self._recv_payload(body)
                blocks, off, err = [], 0, None
                for _ in range(count):
                    (n,) = _LEN.unpack_from(payload, off)
                    off += _LEN.size
                    if n > len(payload) - off:
                        # a lying length prefix must fail loudly, not
                        # silently truncate the block (bounds
                        # discipline: every wire length is checked
                        # against the bytes actually received)
                        raise TransportError(
                            f"block length {n}B exceeds response "
                            f"remainder {len(payload) - off}B"
                        )
                    blocks.append(payload[off: off + n])
                    off += n
                    if on_progress is not None:
                        self._safe_progress(on_progress, n)
            else:
                blocks, err, remaining = [], None, body
                for i in range(count):
                    if remaining < _LEN.size:
                        raise TransportError(
                            f"short read response: {remaining}B left "
                            f"before block {i} of {count}"
                        )
                    (n,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
                    remaining -= _LEN.size
                    if n > remaining:
                        # without this check a lying prefix would read
                        # INTO the next frame's bytes (or hang waiting
                        # for bytes that never come) — the frame's
                        # declared length is the hard bound
                        raise TransportError(
                            f"block length {n}B exceeds response "
                            f"remainder {remaining}B"
                        )
                    remaining -= n
                    d = dest[i] if i < len(dest) else None
                    if d is None:
                        blocks.append(self._recv_payload(n))
                    else:
                        view = _as_view(d)
                        if view.nbytes != n:
                            raise TransportError(
                                f"stripe length mismatch: {n}B payload "
                                f"for {view.nbytes}B dest buffer"
                            )
                        self._recv_into(view)
                        blocks.append(d)
                    if on_progress is not None:
                        self._safe_progress(on_progress, n)
        except BaseException as e:
            self._fail(listener, e)
            self._release_budget()
            raise
        # RTT covers the WHOLE transfer including the body (the
        # loopback series measures through data landing — keep the
        # tcp/loopback series comparable)
        self._m_read_rtt.observe((time.monotonic() - t0) * 1000.0)
        if err is not None:
            self._fail(listener, err)
        else:
            self._complete(listener, blocks)
        self._release_budget()

    @staticmethod
    def _safe_progress(on_progress, n: int) -> None:
        try:
            on_progress(n)
        except BaseException:
            logger.exception("read progress callback raised")

    def _recv_into(self, view: memoryview) -> None:
        got, n = 0, view.nbytes
        while got < n:
            r = self._sock.recv_into(view[got:], n - got)
            if r == 0:
                raise TransportError("connection closed by peer")
            got += r

    def _recv_payload(self, length: int):
        """Receive a bulk payload, preferring a pooled staging buffer
        (zero-copy slices for the consumer, pool reuse on release)."""
        pool = getattr(self.node, "staging_pool", None)
        if pool is not None and length > 0:
            try:
                arr = pool.alloc_gc(length)
            except MemoryError:
                arr = None
            if arr is not None:
                self._recv_into(memoryview(arr)[:length])
                out = arr[:length]
                out.flags.writeable = False
                return out
        return _recv_exact(self._sock, length) if length else b""

    def _fail_outstanding(self, err: BaseException) -> None:
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
        self._m_fail_outstanding.inc()
        for entry in reads:
            self._fail(entry[1], err)
            self._release_budget()

    def _serve_read(self, payload: bytes, t_enq=None) -> None:
        """The one-sided READ service: runs on the node's bounded serve
        pool (posted by the reader loop) against the registered block
        stores — never via the application receive listener, and never
        on the reader thread itself (a large serve must not
        head-of-line-block the channel).  The response goes out as one
        scatter-gather frame of header + length prefixes + the
        resolved block VIEWS — registered memory is never copied into
        an intermediate response buffer."""
        ctx = None
        if RECORDER.enabled:
            # t_enq → now spans the serve queue AND credit wait (the
            # pool admits, then runs this on a worker)
            ctx = _req_trace(payload)
            fr_event(
                "transport", "serve_admit",
                trace_id=ctx[0] if ctx else 0,
                span_id=ctx[1] if ctx else 0,
                wait_us=0 if t_enq is None
                else int((time.monotonic() - t_enq) * 1e6),
                bytes=_req_cost(payload),
            )
        parts = build_read_response_parts(self.node, payload, self.peer)
        if parts is None:
            # not even a req_id to scope an error reply to — dropped
            # (logged); the channel itself stays healthy
            return
        try:
            t0 = time.monotonic()
            self._send_msg(OP_READ_RESP, parts)
            if ctx is not None and RECORDER.enabled:
                fr_event(
                    "transport", "serve_send",
                    trace_id=ctx[0], span_id=ctx[1],
                    us=int((time.monotonic() - t0) * 1e6),
                )
        except BaseException:
            # a response the requester will never see — and possibly a
            # half-written frame desyncing the byte stream.  The
            # channel must die (the wire blast-radius contract): the
            # peer's read loop sees the cut and fails its outstanding
            # reads promptly, which is exactly the signal the in-task
            # retry plane recovers from.  Swallowing this would strand
            # the requester's fetch forever on a healthy-looking
            # socket.
            logger.warning(
                "read response to %s failed — closing channel", self.peer
            )
            self.stop()

    def reply_channel(self) -> Channel:
        """Replies ride the same socket."""
        return self


class TcpNetwork:
    """Listener + connector over real sockets (one instance per process).

    ``transportAsyncDispatcher`` (per NODE, default on) decides which
    engine a node's sockets run on: the completion-driven selector loop
    (transport/dispatcher.py — the listener and every channel ride one
    event-loop thread) or the legacy thread-per-channel blocking path.
    The wire format is identical, so mixed-mode deployments
    interoperate."""

    def __init__(self, listen_backlog: int = 128):
        self.listen_backlog = listen_backlog
        # addr -> (server socket, accept thread | Acceptor | None, node)
        self._listeners: Dict[
            Address, Tuple[socket.socket, object, Node]
        ] = {}  # guarded-by: _lock
        self._lock = dbg_lock("tcp.network", 57)

    # -- membership ---------------------------------------------------------
    def register(self, node: Node) -> None:
        host, port = node.address
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host, port))
        except OSError as e:
            srv.close()
            raise TransportError(f"bind failed at {host}:{port}: {e}") from e
        srv.listen(self.listen_backlog)
        if node.conf.transport_async_dispatcher:
            # the listener rides the node's event loop — no accept thread
            from sparkrdma_tpu.transport.dispatcher import Acceptor

            srv.setblocking(False)
            try:
                disp = node.get_dispatcher()
                acc = Acceptor(disp, node, srv)
                disp.post(acc.loop_register)
            except TransportError:
                srv.close()
                raise
            with self._lock:
                self._listeners[node.address] = (srv, acc, node)
            return
        t = threading.Thread(
            target=self._accept_loop, args=(srv, node), daemon=True,
            name=f"tcp-accept-{host}:{port}",
        )
        with self._lock:
            self._listeners[node.address] = (srv, t, node)
        t.start()

    def unregister(self, node: Node) -> None:
        with self._lock:
            entry = self._listeners.pop(node.address, None)
        if entry is not None:
            srv, owner, _n = entry
            close_fn = getattr(owner, "request_close", None)
            if close_fn is not None:
                # async acceptor: the LOOP must unregister before the
                # fd closes (a direct close here could let a reused fd
                # number collide with the stale selector key)
                close_fn()
                return
            try:
                srv.close()
            except OSError:
                pass

    # -- acceptor (the CM listener thread analog; threaded mode only) -------
    def _accept_loop(self, srv: socket.socket, node: Node) -> None:
        g = gauge("transport_threads", role="accept")
        g.inc()
        try:
            self._accept_forever(srv, node)
        finally:
            g.dec()

    def _accept_forever(self, srv: socket.socket, node: Node) -> None:
        while True:
            try:
                sock, addr = srv.accept()
            except OSError as e:
                if srv.fileno() == -1 or e.errno in (
                    errno.EBADF, errno.EINVAL, errno.ENOTSOCK
                ):
                    return  # listener closed
                # transient: ECONNABORTED (peer reset before accept)
                # or fd/buffer pressure — exiting here would orphan
                # the still-open listener and strand every future
                # connect in its backlog.  Back off briefly so fd
                # exhaustion does not become a hot spin.
                counter("transport_accept_transient_errors_total").inc()
                time.sleep(0.01)
                continue
            try:
                magic, type_idx, src_port, version = _HELLO.unpack(
                    _recv_exact(sock, _HELLO.size)
                )
                if magic != _MAGIC or type_idx >= len(_TYPE_BY_INDEX):
                    raise TransportError(f"bad hello from {addr}")
                if not (MIN_WIRE_VERSION <= version <= WIRE_VERSION):
                    # structured rejection: NAK byte + both versions,
                    # so the connector's error can name them (old
                    # pre-versioning hellos carry 0 here)
                    sock.sendall(  # noqa: PY10 - 5B one-shot handshake NAK
                        b"\x00" + _HELLO_REJ.pack(WIRE_VERSION, version)
                    )
                    counter("wire_version_rejects_total").inc()
                    raise TransportError(
                        f"protocol version mismatch from {addr}: hello "
                        f"spoke wire version {version}, this node "
                        f"accepts {MIN_WIRE_VERSION}..{WIRE_VERSION}"
                    )
                req_type = _TYPE_BY_INDEX[type_idx]
                sock.sendall(b"\x01")  # ack (ESTABLISHED)
            except BaseException:
                logger.warning("handshake with %s failed", addr, exc_info=True)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = (addr[0], src_port)
            ch = TcpChannel(_PAIRED.get(req_type, req_type), node, peer, sock)
            ch.wire_version = version  # the hello's (accepted) generation
            ch._set_state(ChannelState.CONNECTED)
            node.register_passive_channel(ch)
            ch.start_reader()

    # -- connector (passed to Node.get_channel) -----------------------------
    def connect(self, src: Node, peer: Address,
                channel_type: ChannelType) -> Channel:
        timeout_s = src.conf.connect_timeout_ms / 1000.0
        counter("transport_connect_attempts_total", transport="tcp").inc()
        if FAULTS.enabled:
            FAULTS.check("connect")
        ver = WIRE_VERSION
        try:
            while True:
                sock = socket.create_connection(peer, timeout=timeout_s)
                sock.settimeout(timeout_s)
                if FAULTS.enabled and FAULTS.fires("hello"):
                    # a handshake fault dies between socket and ack —
                    # the half-open socket closes via the OSError path
                    sock.close()
                    raise OSError("injected fault at point 'hello'")
                sock.sendall(_HELLO.pack(
                    _MAGIC, _TYPE_BY_INDEX.index(channel_type),
                    src.address[1], ver,
                ))
                ack = _recv_exact(sock, 1)
                if ack == b"\x01":
                    break
                detail = ""
                if ack == b"\x00":
                    # structured version rejection carries both sides
                    try:
                        srv_ver, cli_ver = _HELLO_REJ.unpack(
                            _recv_exact(sock, _HELLO_REJ.size)
                        )
                    except TransportError:
                        srv_ver = None
                    else:
                        detail = (
                            f": peer requires wire version {srv_ver}, "
                            f"this hello spoke {cli_ver}"
                        )
                    if (srv_ver is not None
                            and MIN_WIRE_VERSION <= srv_ver < ver):
                        # negotiated fallback: the acceptor closed its
                        # end after the NAK, so re-dial speaking ITS
                        # generation; the channel remembers it so
                        # v2-only bytes (trace tails/fields) stay off
                        # this connection
                        try:
                            sock.close()
                        except OSError:
                            pass
                        ver = srv_ver
                        counter(
                            "wire_version_downgrades_total",
                            transport="tcp",
                        ).inc()
                        fr_event(
                            "transport", "version_downgrade",
                            peer=f"{peer[0]}:{peer[1]}", to=ver,
                        )
                        continue
                raise TransportError(
                    f"handshake rejected by {peer}{detail}"
                )
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except socket.timeout as e:
            counter(
                "transport_connect_timeouts_total", transport="tcp"
            ).inc()
            raise TransportError(f"connect to {peer} timed out: {e}") from e
        except OSError as e:
            counter(
                "transport_connect_failures_total", transport="tcp"
            ).inc()
            raise TransportError(f"connect to {peer} failed: {e}") from e
        if src.conf.transport_async_dispatcher:
            from sparkrdma_tpu.transport.dispatcher import AsyncTcpChannel

            ch = AsyncTcpChannel.attach(channel_type, src, peer, sock)
            ch.wire_version = ver
            return ch
        ch = TcpChannel(channel_type, src, peer, sock)
        ch.wire_version = ver
        ch._set_state(ChannelState.CONNECTED)
        ch.start_reader()
        return ch
