"""TCP transport: the real multi-process control/data plane backend.

Same connector interface as :class:`LoopbackNetwork`, over real sockets,
so driver and executors can live in separate processes or hosts.  On a
TPU pod the BULK shuffle plane rides ICI collectives (the TileExchange);
this backend carries what remains host-side — the control plane (the
five RPC message types) and the block-fetch path for executors outside
the mesh (spill-over, debugging, CPU-only deployments).

Mapping to the reference (RdmaNode.java / RdmaChannel.java):

- connect() plays the RDMA CM handshake: a 9-byte hello carrying the
  channel type and the caller's listening port, acked by the acceptor
  (CONNECT_REQUEST/ESTABLISHED, RdmaNode.java:114-214).
- OP_RPC frames are the two-sided SEND/RECV class; TCP supplies
  ordering and (via its window) flow control, so the software credit
  scheme of the loopback backend is not re-implemented here.
- OP_READ_REQ/RESP is the one-sided READ class: the acceptor serves
  registered-memory reads on the node's dedicated bulk pool — the
  application's receive listener is never involved, preserving the
  "remote CPU does not run app code to serve reads" split (the NIC's
  role in RdmaChannel.java:441-474; here dedicated service threads,
  kept off both the reader loop and the control-plane dispatcher).

Framing: every message is ``1B opcode + 4B LE length + payload``.
Read requests carry ``8B req_id + 4B count + count × (8B address,
4B length, 4B mkey)``; responses carry ``8B req_id + 1B status`` then
either ``count × (4B len + bytes)`` or an error string.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.metrics import counter, histogram
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.node import Address, Node
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)

_MAGIC = b"STPU"
_HDR = struct.Struct("<BI")          # opcode, payload length
_HELLO = struct.Struct("<4sBHH")     # magic, channel type, src port, pad
_REQ_HDR = struct.Struct("<QI")      # req_id, location count
_LOC = struct.Struct("<QII")         # address, length, mkey
_RESP_HDR = struct.Struct("<QB")     # req_id, status
_LEN = struct.Struct("<I")

OP_RPC = 1
OP_READ_REQ = 2
OP_READ_RESP = 3

_TYPE_BY_INDEX = list(ChannelType)

# what the acceptor's side of each connection is called
_PAIRED = {
    ChannelType.RPC_REQUESTOR: ChannelType.RPC_RESPONDER,
    ChannelType.RPC_WRAPPER: ChannelType.RPC_WRAPPER,
    ChannelType.READ_REQUESTOR: ChannelType.READ_RESPONDER,
}

_MAX_FRAME = 1 << 30


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed by peer")
        buf += chunk
    return bytes(buf)


class TcpChannel(Channel):
    """One TCP connection; either endpoint can carry RPC frames, the
    acceptor side additionally serves block reads."""

    def __init__(self, channel_type: ChannelType, node: Node,
                 peer: Address, sock: socket.socket):
        super().__init__(channel_type, node.conf.send_queue_depth)
        self.node = node
        self.peer = peer
        self._sock = sock
        self._send_lock = threading.Lock()
        self._next_req = 1
        # req_id -> (location count, listener, post monotonic time)
        self._reads: Dict[int, Tuple[int, CompletionListener, float]] = {}
        self._reads_lock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._m_bytes_sent = counter(
            "transport_bytes_sent_total", transport="tcp")
        self._m_bytes_recv = counter(
            "transport_bytes_received_total", transport="tcp")
        self._m_msgs_sent = counter(
            "transport_msgs_sent_total", transport="tcp")
        self._m_msgs_recv = counter(
            "transport_msgs_received_total", transport="tcp")
        self._m_read_rtt = histogram(
            "transport_read_rtt_ms", transport="tcp")
        self._m_fail_outstanding = counter(
            "transport_fail_outstanding_total", transport="tcp")

    # -- lifecycle ----------------------------------------------------------
    def start_reader(self) -> None:
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"tcp-{self.peer[0]}:{self.peer[1]}",
        )
        self._reader.start()

    def stop(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        err = TransportError("channel stopped")
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
        for _, listener, _t0 in reads:
            self._safe_fail(listener, err)
        super().stop()

    # -- sending ------------------------------------------------------------
    def _send_msg(self, opcode: int, payload: bytes) -> None:
        with self._send_lock:
            self._sock.sendall(_HDR.pack(opcode, len(payload)) + payload)
        self._m_msgs_sent.inc()
        self._m_bytes_sent.inc(_HDR.size + len(payload))

    def _post_rpc(self, frames: List[bytes], listener: CompletionListener) -> None:
        def run():
            try:
                for frame in frames:
                    self._send_msg(OP_RPC, frame)
            except BaseException as e:
                self._error(e)
                self._fail(listener, e)
            else:
                self._complete(listener, None)
            finally:
                self._release_budget()

        self.node.submit(run)

    def _post_read(self, locations: List[BlockLocation],
                   listener: CompletionListener) -> None:
        with self._reads_lock:
            req_id = self._next_req
            self._next_req += 1
            self._reads[req_id] = (len(locations), listener, time.monotonic())
        payload = bytearray(_REQ_HDR.pack(req_id, len(locations)))
        for loc in locations:
            payload += _LOC.pack(loc.address, loc.length, loc.mkey)

        def run():
            try:
                self._send_msg(OP_READ_REQ, bytes(payload))
            except BaseException as e:
                with self._reads_lock:
                    self._reads.pop(req_id, None)
                self._error(e)
                self._fail(listener, e)
                self._release_budget()
            # budget released when the response (or teardown) arrives

        self.node.submit(run)

    # -- receiving ----------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while True:
                opcode, length = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
                if length > _MAX_FRAME:
                    raise TransportError(f"oversized frame: {length}B")
                self._m_msgs_recv.inc()
                self._m_bytes_recv.inc(_HDR.size + length)
                if opcode == OP_READ_RESP:
                    # bulk data lands in a POOLED buffer; blocks are
                    # zero-copy slices whose collection returns it
                    # (BufferReleasingInputStream analog via alloc_gc)
                    self._finish_read(self._recv_payload(length))
                    continue
                payload = _recv_exact(self._sock, length) if length else b""
                if opcode == OP_RPC:
                    self.node.dispatch_frame(self, payload)
                elif opcode == OP_READ_REQ:
                    # serve OFF the reader thread: one large read must
                    # not head-of-line-block further frames on this
                    # channel (the reference's CQ model has no such
                    # serialization — the NIC serves reads).  Bulk pool,
                    # not the dispatcher: multi-MB serves must never
                    # starve heartbeat/RPC dispatch
                    self.node.submit_bulk(self._serve_read, payload)
                else:
                    raise TransportError(f"unknown opcode {opcode}")
        except BaseException as e:
            if self.state not in (ChannelState.STOPPED,):
                self._error(e)
                self._fail_outstanding(e)

    def _recv_payload(self, length: int):
        """Receive a bulk payload, preferring a pooled staging buffer
        (zero-copy slices for the consumer, pool reuse on release)."""
        pool = getattr(self.node, "staging_pool", None)
        if pool is not None and length > 0:
            try:
                arr = pool.alloc_gc(length)
            except MemoryError:
                arr = None
            if arr is not None:
                view = memoryview(arr)[:length]
                got = 0
                while got < length:
                    n = self._sock.recv_into(view[got:], length - got)
                    if n == 0:
                        raise TransportError("connection closed by peer")
                    got += n
                out = arr[:length]
                out.flags.writeable = False
                return out
        return _recv_exact(self._sock, length) if length else b""

    def _fail_outstanding(self, err: BaseException) -> None:
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
        self._m_fail_outstanding.inc()
        for _, listener, _t0 in reads:
            self._fail(listener, err)
            self._release_budget()

    def _serve_read(self, payload: bytes) -> None:
        """The one-sided READ service: runs on the node's bulk pool
        (posted by the reader loop) against the registered block
        stores — never via the application receive listener, and never
        on the reader thread itself (a large serve must not
        head-of-line-block the channel)."""
        req_id, count = _REQ_HDR.unpack_from(payload, 0)
        try:
            locs = []
            off = _REQ_HDR.size
            for _ in range(count):
                addr, length, mkey = _LOC.unpack_from(payload, off)
                off += _LOC.size
                locs.append(BlockLocation(addr, length, mkey))
            blocks = self.node.read_local_blocks(locs)
            body = bytearray(_RESP_HDR.pack(req_id, 0))
            for b in blocks:
                body += _LEN.pack(len(b))
                # blocks may be zero-copy ndarray views; memoryview
                # appends raw bytes (bytearray += ndarray would
                # dispatch to numpy broadcasting)
                body += memoryview(b)
        except BaseException as e:
            body = bytearray(_RESP_HDR.pack(req_id, 1))
            body += str(e).encode("utf-8", "replace")
        try:
            self._send_msg(OP_READ_RESP, bytes(body))
        except BaseException:
            logger.warning("read response to %s failed", self.peer)

    def _finish_read(self, payload: bytes) -> None:
        req_id, status = _RESP_HDR.unpack_from(payload, 0)
        with self._reads_lock:
            entry = self._reads.pop(req_id, None)
        if entry is None:
            return  # raced with teardown
        count, listener, t0 = entry
        self._m_read_rtt.observe((time.monotonic() - t0) * 1000.0)
        try:
            if status != 0:
                raise TransportError(
                    bytes(payload[_RESP_HDR.size:]).decode("utf-8", "replace")
                )
            blocks, off = [], _RESP_HDR.size
            for _ in range(count):
                (n,) = _LEN.unpack_from(payload, off)
                off += _LEN.size
                blocks.append(payload[off: off + n])
                off += n
        except BaseException as e:
            self._fail(listener, e)
        else:
            self._complete(listener, blocks)
        finally:
            self._release_budget()

    def reply_channel(self) -> Channel:
        """Replies ride the same socket."""
        return self


class TcpNetwork:
    """Listener + connector over real sockets (one instance per process)."""

    def __init__(self, listen_backlog: int = 128):
        self.listen_backlog = listen_backlog
        self._listeners: Dict[
            Address, Tuple[socket.socket, threading.Thread, Node]
        ] = {}
        self._lock = threading.Lock()

    # -- membership ---------------------------------------------------------
    def register(self, node: Node) -> None:
        host, port = node.address
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host, port))
        except OSError as e:
            srv.close()
            raise TransportError(f"bind failed at {host}:{port}: {e}") from e
        srv.listen(self.listen_backlog)
        t = threading.Thread(
            target=self._accept_loop, args=(srv, node), daemon=True,
            name=f"tcp-accept-{host}:{port}",
        )
        with self._lock:
            self._listeners[node.address] = (srv, t, node)
        t.start()

    def unregister(self, node: Node) -> None:
        with self._lock:
            entry = self._listeners.pop(node.address, None)
        if entry is not None:
            srv, _t, _n = entry
            try:
                srv.close()
            except OSError:
                pass

    # -- acceptor (the CM listener thread analog) ---------------------------
    def _accept_loop(self, srv: socket.socket, node: Node) -> None:
        while True:
            try:
                sock, addr = srv.accept()
            except OSError:
                return  # listener closed
            try:
                magic, type_idx, src_port, _ = _HELLO.unpack(
                    _recv_exact(sock, _HELLO.size)
                )
                if magic != _MAGIC or type_idx >= len(_TYPE_BY_INDEX):
                    raise TransportError(f"bad hello from {addr}")
                req_type = _TYPE_BY_INDEX[type_idx]
                sock.sendall(b"\x01")  # ack (ESTABLISHED)
            except BaseException:
                logger.warning("handshake with %s failed", addr, exc_info=True)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = (addr[0], src_port)
            ch = TcpChannel(_PAIRED.get(req_type, req_type), node, peer, sock)
            ch._set_state(ChannelState.CONNECTED)
            node.register_passive_channel(ch)
            ch.start_reader()

    # -- connector (passed to Node.get_channel) -----------------------------
    def connect(self, src: Node, peer: Address,
                channel_type: ChannelType) -> Channel:
        timeout_s = src.conf.connect_timeout_ms / 1000.0
        counter("transport_connect_attempts_total", transport="tcp").inc()
        try:
            sock = socket.create_connection(peer, timeout=timeout_s)
            sock.settimeout(timeout_s)
            sock.sendall(_HELLO.pack(
                _MAGIC, _TYPE_BY_INDEX.index(channel_type),
                src.address[1], 0,
            ))
            ack = _recv_exact(sock, 1)
            if ack != b"\x01":
                raise TransportError(f"handshake rejected by {peer}")
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except socket.timeout as e:
            counter(
                "transport_connect_timeouts_total", transport="tcp"
            ).inc()
            raise TransportError(f"connect to {peer} timed out: {e}") from e
        except OSError as e:
            counter(
                "transport_connect_failures_total", transport="tcp"
            ).inc()
            raise TransportError(f"connect to {peer} failed: {e}") from e
        ch = TcpChannel(channel_type, src, peer, sock)
        ch._set_state(ChannelState.CONNECTED)
        ch.start_reader()
        return ch
