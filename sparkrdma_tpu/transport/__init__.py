"""Transport layer: channels, nodes, flow control, loopback backend.

The reference's L4 (RdmaNode/RdmaChannel/RdmaThread over DiSNI verbs,
SURVEY.md §1).  Here a ``Channel`` carries the same two traffic classes —
small control RPCs and bulk one-sided block reads — over pluggable
backends: an in-process loopback for tests and single-host runs, and the
ICI collective exchange engine (sparkrdma_tpu.parallel) for the
device-to-device bulk path.
"""

from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    FnCompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.transport.loopback import LoopbackNetwork
from sparkrdma_tpu.transport.stripe import ReadGroup
from sparkrdma_tpu.transport.tcp import TcpNetwork

__all__ = [
    "TcpNetwork",
    "ReadGroup",
    "Channel",
    "ChannelState",
    "ChannelType",
    "CompletionListener",
    "FnCompletionListener",
    "TransportError",
    "Node",
    "LoopbackNetwork",
]
