"""Striped multi-channel block reads: per-peer groups over a shared
lane pool.

SparkRDMA's point-to-point perf trick was channel specialization: each
peer pair keeps RPC channels separate from dedicated RDMA_READ
requestor/responder channels so bulk reads never head-of-line-block
control traffic (RdmaChannel.java:41; our ``ChannelType`` mirrors the
split but every peer previously shared ONE serialized socket per
type).  This module extends the split with fabric-lib-style striping:

- a :class:`ReadGroup` per peer owns one SMALL-read lane (slot 0) and
  BORROWS data lanes (slots 1..k) per read from the node's fixed
  :class:`~sparkrdma_tpu.transport.node._LanePool`
  (``transportLanePoolSize``), so concurrent stripe fan-out across all
  peers is bounded node-wide instead of every peer owning
  ``transportNumStripes`` dedicated sockets — idle peers cost zero
  data-lane connections (their cached channels age out of the node's
  LRU channel cache);
- block reads larger than ``transportStripeThreshold`` are chunked and
  issued round-robin across the borrowed lanes as ordinary sub-range
  one-sided reads (a stripe is just a ``BlockLocation`` at
  ``address + offset`` — the responder needs no special handling), each
  landing via ``recv_into`` DIRECTLY in its slice of one pooled
  destination row (``StagingPool.alloc_gc``) — reassembly happens in
  the kernel copy, with no intermediate buffers or joins;
- small reads ride slot 0 whole, so metadata-sized fetches never queue
  behind multi-MB stripes; when the lane pool is dry, bulk reads fall
  back to slot 0 unstriped (narrower, never wrong).

Lane channels come from the node's slot-keyed LRU channel cache, so an
evicted lane transparently reconnects on the next read; a post that
loses the eviction race (channel stopped between cache lookup and the
post) re-resolves through the cache exactly once — see ``_post``.

Failure contract: the first failing sub-read fails the WHOLE group
read exactly once (each lane's ``_fail_outstanding`` covers its
stripes; the combiner fans the first error out to the caller), so a
dead data channel surfaces as a prompt fetch failure, never a hang.
Borrowed lanes are returned exactly once, on the group's completion or
first failure.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.qos import BULK, INTERACTIVE
from sparkrdma_tpu.transport.channel import (
    ChannelType,
    CompletionListener,
    FnCompletionListener,
    TransportError,
)
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.ledger import ledger_acquire
from sparkrdma_tpu.utils.statemachine import StateMachine
from sparkrdma_tpu.utils.types import BlockLocation


def _alloc_row(pool, nbytes: int) -> np.ndarray:
    """Pooled destination row for one striped block (zero-copy slices,
    GC-tied release); plain numpy when no pool is wired or the budget
    is exhausted."""
    from sparkrdma_tpu.memory.staging import alloc_row_gc

    return alloc_row_gc(
        pool, nbytes, "transport_stripe_row_pool_fallbacks_total"
    )


class _GroupRead(StateMachine):
    """Completion combiner for one group read: N sub-reads, one
    caller-facing listener.  First failure wins and suppresses further
    progress reports; success fires once when every sub-read landed.
    ``on_finish`` (borrowed-lane return) runs exactly once, on the
    finished transition, before the caller's listener."""

    __slots__ = ("listener", "out", "rows", "on_progress", "pending",
                 "lock", "_state", "on_finish")

    MACHINE = "stripe.group_read"
    STATES = ("pending", "done", "failed")
    INITIAL = "pending"
    TERMINAL = ("done", "failed")
    TRANSITIONS = {
        "pending": ("done", "failed"),
    }

    def __init__(self, listener: CompletionListener, out: list,
                 rows: List[int], on_progress, pending: int,
                 on_finish=None):
        self.listener = listener
        self.out = out
        self.rows = rows  # indices whose out[] entry is a dest row
        self.on_progress = on_progress
        self.pending = pending  # guarded-by: lock
        self.lock = dbg_lock("stripe.group", 54)
        # read UNLOCKED by progress() as a suppress hint (racy by
        # design — a late progress report is harmless); writes stay
        # under the lock
        self._state = "pending"  # state: stripe.group_read guarded-by: lock
        self.on_finish = on_finish

    def _finish(self) -> None:
        # only the thread that made the finished transition gets here
        cb, self.on_finish = self.on_finish, None
        if cb is not None:
            try:
                cb()
            except BaseException:
                pass

    def progress(self, n: int) -> None:
        cb = self.on_progress
        # racy suppress hint — a late progress report is harmless
        if cb is not None and self._state == "pending":  # noqa: SC03 hint
            cb(n)

    def part_done(self) -> None:
        with self.lock:
            if self._state != "pending":
                return
            self.pending -= 1
            if self.pending:
                return
            self._transition("done", frm="pending")
        self._finish()
        for i in self.rows:
            row = self.out[i]
            if isinstance(row, np.ndarray):
                row.flags.writeable = False
        self.listener.on_success(self.out)

    def fail(self, err: BaseException) -> None:
        with self.lock:
            if self._state != "pending":
                return
            self._transition("failed", frm="pending")
        self._finish()
        self.listener.on_failure(err)


class ReadGroup:
    """One peer's channel group: stripes bulk reads over borrowed
    lanes, keeps small reads on their own lane.  Obtained via
    ``Node.get_read_group``; channels come from the node's slot-keyed
    LRU cache, so lane death/eviction/reconnect rides the existing
    racy-create machinery."""

    def __init__(self, node, peer, connect):
        self.node = node
        self.peer = peer
        self._connect = connect
        conf = node.conf
        self.num_stripes = conf.transport_num_stripes
        self.threshold = max(conf.transport_stripe_threshold, 1)
        self._rr = 0  # guarded-by: _rr_lock
        self._rr_lock = dbg_lock("stripe.rr", 52)
        self._m_stripes = counter("transport_stripes_total")
        self._m_stripe_bytes = counter("transport_stripe_bytes_total")
        self._m_striped_reads = counter("transport_striped_reads_total")
        self._m_evict_races = counter("transport_channel_evict_races_total")

    def channel(self, slot: int = 0):
        return self.node.get_channel(
            self.peer, ChannelType.READ_REQUESTOR, self._connect, slot=slot
        )

    def data_channels(self) -> List:
        """The full-width data lanes (slots 1..num_stripes) — chaos
        tests reach in here to kill one mid-read."""
        return [self.channel(s) for s in range(1, self.num_stripes + 1)]

    def _post(self, slot: int, locs, listener, dest=None,
              on_progress=None, ctx=None) -> None:
        """Post one lane's sub-read, re-resolving the channel exactly
        once if the cached channel was evicted between the cache lookup
        and the post (``read_blocks`` raises synchronously BEFORE
        touching the listener, so a retry can never double-deliver)."""
        if FAULTS.enabled and slot > 0:
            FAULTS.check("stripe")
        for attempt in (0, 1):
            ch = self.channel(slot)
            try:
                if dest is None and on_progress is None and ctx is None:
                    ch.read_blocks(locs, listener)
                else:
                    ch.read_blocks(
                        locs, listener, dest=dest, on_progress=on_progress,
                        ctx=ctx,
                    )
            except TransportError:
                if attempt:
                    raise
                self._m_evict_races.inc()
                continue
            if (FAULTS.enabled and slot > 0
                    and FAULTS.fires("lane_kill")):
                # mid-read lane death: the sub-read was posted, now the
                # lane dies under it — _fail_outstanding surfaces the
                # structured failure exactly like a real cut socket
                ch.stop()
            return

    def read_blocks(
        self,
        locations: Sequence[BlockLocation],
        listener: CompletionListener,
        on_progress=None,
        tenant=None,
        ctx=None,
    ) -> None:
        """Same contract as ``Channel.read_blocks``: completion delivers
        one bytes-like payload per location, in order — striped blocks
        arrive as the full reassembled destination row (read-only
        ndarray), small ones exactly as a plain channel read returns
        them.  ``tenant`` (qos/) shapes the lane borrow: interactive
        tenants draw on the pool's reserved slice, and a DEGRADED
        tenant (over its admission quota) narrows to one data lane —
        correct, just no longer fanned out."""
        locations = list(locations)
        ch0 = self.channel(0)
        scatter = getattr(ch0, "supports_scatter", False)
        striped = (
            [i for i, loc in enumerate(locations)
             if loc.length > self.threshold]
            if scatter and self.num_stripes > 1 else []
        )
        if striped and self.node.peer_health(self.peer).stripes.demoted():
            # repeated lane failures against this peer: demote to the
            # unstriped small-read lane for the health window (the
            # dry-pool fallback below, driven by a health signal)
            counter("transport_stripe_demotions_total").inc()
            striped = []
        lanes_borrowed = 0
        if striped:
            # borrow this read's stripe width from the node-wide pool;
            # a dry pool demotes the read to the small lane, unstriped
            want, cls = self.num_stripes, BULK
            if tenant is not None:
                if tenant.degraded:
                    want = 1  # admission degrade: narrower stripes
                    counter("qos_degraded_reads_total",
                            tenant=tenant.name).inc()
                if tenant.interactive:
                    cls = INTERACTIVE
            lanes_borrowed = self.node.lane_pool.try_borrow(
                want, cls=cls
            )  # acquires: node.lane_tokens
            # owns: node.lane_tokens -> release_lanes
            if lanes_borrowed == 0:
                striped = []
        if not striped:
            if scatter and (on_progress is not None or ctx is not None):
                self._post(
                    0, locations, listener, on_progress=on_progress,
                    ctx=ctx,
                )
            else:
                self._post(0, locations, listener)
            return

        # ONE-SHOT release shared by every owner: the group state's
        # finish transition AND the pre-state exception path below.  A
        # plain release in both places would double-credit the pool
        # when a caller's on_failure raises out of state.fail AFTER
        # the finish transition already returned the tokens.
        owed = [lanes_borrowed]
        tkt = ledger_acquire("node.lane_tokens", lanes_borrowed)

        def release_lanes() -> None:
            n, owed[0] = owed[0], 0
            self.node.lane_pool.release(n)  # releases: node.lane_tokens  # one-shot
            tkt.release(n)

        try:
            self._read_striped(
                locations, striped, lanes_borrowed, listener, on_progress,
                release_lanes, ctx,
            )
        except BaseException:
            release_lanes()
            raise

    def _read_striped(self, locations, striped, width, listener,
                      on_progress, release_lanes, ctx=None) -> None:
        striped_set = set(striped)
        small = [i for i in range(len(locations)) if i not in striped_set]
        out: list = [None] * len(locations)
        # lane -> ([sub-locations], [dest views]); slots 1..width so
        # back-to-back reads reuse the same cached lane channels
        lanes = {s: ([], []) for s in range(1, width + 1)}
        pool = getattr(self.node, "staging_pool", None)
        with self._rr_lock:
            rr = self._rr
            self._rr += sum(
                self._num_chunks(locations[i].length, width)
                for i in striped
            )
        for i in striped:
            loc = locations[i]
            row = _alloc_row(pool, loc.length)
            out[i] = row
            k = self._num_chunks(loc.length, width)
            base, extra = divmod(loc.length, k)
            off = 0
            for j in range(k):
                n = base + (1 if j < extra else 0)
                slot = 1 + (rr % width)
                rr += 1
                locs, dests = lanes[slot]
                locs.append(BlockLocation(loc.address + off, n, loc.mkey))
                dests.append(row[off:off + n])
                off += n
            self._m_stripes.inc(k)
            self._m_stripe_bytes.inc(loc.length)
            self._m_striped_reads.inc()

        live_lanes = [s for s, (locs, _d) in lanes.items() if locs]
        state = _GroupRead(
            listener, out, striped, on_progress,
            pending=len(live_lanes) + (1 if small else 0),
            on_finish=release_lanes,
        )
        health = self.node.peer_health(self.peer).stripes

        def lane_done(_blocks) -> None:
            health.note_success()
            state.part_done()

        def lane_fail(err: BaseException) -> None:
            # striped-lane failure feeds the peer's demotion signal
            # BEFORE the group fails, so the retry attempt already
            # sees the updated health
            health.note_lane_failure()
            state.fail(err)

        def lane_listener():
            return FnCompletionListener(lane_done, lane_fail)

        def small_done(blocks):
            for idx, b in zip(small, blocks):
                out[idx] = b
            state.part_done()

        try:
            if small:
                self._post(
                    0, [locations[i] for i in small],
                    FnCompletionListener(small_done, state.fail),
                    on_progress=state.progress, ctx=ctx,
                )
            for s in live_lanes:
                locs, dests = lanes[s]
                self._post(
                    s, locs, lane_listener(), dest=dests,
                    on_progress=state.progress,
                    ctx=ctx.child() if ctx is not None else None,
                )
        except BaseException as e:
            state.fail(e)

    def _num_chunks(self, length: int, width: int) -> int:
        """Stripes for one block across ``width`` borrowed lanes: every
        chunk stays above half the threshold so tiny tail chunks never
        pay a full round trip."""
        min_chunk = max(self.threshold // 2, 1)
        return max(1, min(width, length // min_chunk))


__all__ = ["ReadGroup"]
