"""Async completion-driven transport core: one selector loop per node.

The thread-per-lane TCP plane (transport/tcp.py) costs one blocking
reader thread per channel — O(peers × stripes) threads per node at
production fan-out, plus the accept thread.  This module replaces that
with the submission-queue / completion-queue idiom of fabric-lib and
RAMC (PAPERS.md): post work as descriptors, reap batched completions
from a single progress engine.

- :class:`Dispatcher` — ONE event-loop thread per node owning every
  transport socket in non-blocking mode via ``selectors``.  Work
  arrives on a **submission queue** (descriptors posted from any
  thread; a wakeup pipe interrupts ``select``), progress happens as
  partial ``sendmsg``/``recv_into`` continuations, and results leave
  through a **completion queue**: per-iteration batches of completion
  events handed to the node's completion executor — the CQ-poller →
  RdmaThread split of the reference, with the loop playing the NIC/CQ
  and the executor playing the completion-listener threads.
- :class:`AsyncTcpChannel` — ``TcpChannel``'s send/recv state machines
  ported onto the loop: frames go out as iovec descriptors with
  per-channel write backpressure (a channel whose response backlog
  exceeds ``transportSendBacklogBytes`` stops being READ until it
  drains), and read responses scatter into their registered
  destination buffers exactly like the threaded path — striped
  reassembly (``on_progress``) and the decode-pool submissions feed
  straight off completion events.
- :class:`Acceptor` / :class:`_Handshake` — the listening socket rides
  the same loop (no accept thread); the 9-byte hello is parsed as a
  non-blocking continuation.

Wire format is byte-identical to transport/tcp.py — an async client
interoperates with a threaded server and vice versa; the threaded path
stays available behind ``transportAsyncDispatcher=off`` for A/B and
bit-exactness.

Two mechanisms adapt the engine to load.  LANE STREAMING
(``transportStreamOffloadBytes``, see ``_rx_maybe_offload``): a bulk
channel with enough response bytes outstanding hands its whole recv
machine to a completion-pool worker doing blocking ``recv`` with
inline completion delivery — the threaded reader's exact
syscall-and-delivery shape, paid only while the lane is busy (one
handoff per burst; a bounded number of lanes at a time).  The
SPIN-POLL (``transportPollSpinUs``): after an iteration that did real
work the loop can busy-poll the selector before re-arming the
blocking ``select``, reaping back-to-back completions at syscall cost
(the CQ busy-poll of the reference designs — a multi-core luxury,
default off on single-core hosts where the spin steals the core the
peer needs).  One-sided READ serving keeps the bounded serve pool
(node.py): block resolution may fault on mapped files, which must
never stall the loop.
A serve worker resolves the blocks, posts the response descriptor, and
returns — its byte credits are released by the send-completion event,
not by a worker blocking in ``sendall`` (``_ServePool`` deferred
release), so credits still bound resident serve memory while workers
stay free.

Discipline: methods marked ``# on-loop`` run on the event-loop thread
and must never block — tools/concheck.py CK05 enforces it (the CK02
blocking-call analysis re-aimed at the loop's callback plane).
"""

from __future__ import annotations

import errno
import logging
import os
import select
import selectors
import socket
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter, gauge, histogram
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    TransportError,
    decode_remote_error,
)
from sparkrdma_tpu.transport import tcp as wire
from sparkrdma_tpu.utils import wiredbg
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.statemachine import (
    GLOBAL_STATE_DEBUG,
    StateMachine,
    check_named,
)
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)

#: iovec batch per sendmsg call (mirrors transport/tcp.py)
_IOV_MAX = wire._IOV_MAX

_SCRATCH = 1 << 16  # discard-path receive chunk

# fairness budget: max bytes one channel may move per readiness
# callback before yielding the loop back to the selector — bounds the
# worst-case iteration so no handler monopolizes the loop (the
# selector is level-triggered, so the remainder re-reports
# immediately).
_FAIR_BUDGET = 2 << 20

# priority-poll cadence: bulk-class channels re-poll the selector for
# LATENCY-class events (RPC channels, accepts, handshakes) after this
# many bytes of recv/send work, servicing them inline — the loop's
# analog of the dedicated small-read lane: a multi-MiB stream in
# flight adds at most ~this many bytes of latency to an RPC pong,
# instead of a whole transfer.  256 KiB ≈ 50 µs of memcpy per poll,
# one epoll_wait(0) ≈ 3 µs — the A/B sweet spot between per-chunk
# Python overhead (which dominated at 128 KiB) and pong queueing.
_POLL_BYTES = 256 << 10

# lane streaming (conf transportStreamOffloadBytes): a bulk channel
# with at least that many response bytes outstanding streams its recv
# machine on a completion-pool worker (blocking recv + inline
# delivery) until idle — at most _OFFLOAD_WORKERS lanes at a time so
# completion delivery can never starve; further busy lanes land
# on-loop.  Per-BODY offloading was A/B'd first and lost ~8% striped
# throughput to its per-body round trips (unregister, pool handoff,
# resume post); per-BURST streaming amortizes the handoff to noise.
# 3 of the completion pool's 4 threads may hold streams at once — one
# slot always stays free for loop-side completion batches (a typical
# client needs 3: two data lanes + one hot RPC channel)
_OFFLOAD_WORKERS = 3
_OFFLOAD_TIMEOUT = 0.2  # blocking-recv tick; worker rechecks _closed
# idle-exit grace: a streaming worker with nothing outstanding and
# nothing sending waits this long for a follow-on frame before handing
# the fd back to the loop — request bursts have sub-ms gaps, and a
# handoff round trip costs more than the wait
_STREAM_GRACE = 0.002
# hot-channel trigger: two frames closer together than this = a
# conversation in progress (an RPC ping stream, a request burst) —
# stream the channel so every later frame lands on a blocked reader
# at kernel-wake cost instead of epoll + loop machinery
_HOT_FRAME_S = 0.001

#: channel roles whose traffic is latency-class (control plane)
_LATENCY_TYPES = frozenset((
    ChannelType.RPC_REQUESTOR,
    ChannelType.RPC_RESPONDER,
    ChannelType.RPC_WRAPPER,
))


_RMEM_MAX_FALLBACK = 6 << 20  # Linux tcp_rmem[2] default ballpark


def _rmem_max() -> int:
    """Autotune growth ceiling of the TCP receive buffer — the bound
    for RCVLOWAT watermarks on autotuned sockets."""
    try:
        with open("/proc/sys/net/ipv4/tcp_rmem") as f:
            return int(f.read().split()[2])
    except (OSError, ValueError, IndexError):
        return _RMEM_MAX_FALLBACK


def _safe(fn, *args) -> None:
    """Run one completion callback, never letting it kill the batch."""
    try:
        fn(*args)
    except BaseException:
        logger.exception("completion callback raised")


def _run_batch(batch: List[Tuple]) -> None:
    """Drain one completion batch in order on the completion executor."""
    for fn, args in batch:
        _safe(fn, *args)


class _SendOp(StateMachine):
    """One outbound frame descriptor: iovec views + a cursor advanced
    across partial sends, completed (on the completion queue) when the
    whole frame has been handed to the kernel.  Lifecycle: ``new`` until
    it enters a channel's tx queue, then ``sent`` (fully written) or
    ``failed`` (queue swept by teardown / rejected by a closed
    channel)."""

    __slots__ = ("views", "i", "total", "frames", "on_done", "tkt",
                 "_state")

    MACHINE = "dispatcher.sendop"
    STATES = ("new", "queued", "sent", "failed")
    INITIAL = "new"
    TERMINAL = ("sent", "failed")
    TRANSITIONS = {
        "new": ("queued", "failed"),
        "queued": ("sent", "failed"),
    }

    def __init__(self, views: List[memoryview], total: int, frames: int,
                 on_done=None):
        self.views = views
        self.i = 0
        self.total = total          # wire bytes incl. headers
        self.frames = frames        # logical frames in this descriptor
        self.on_done = on_done      # callable(err-or-None) | None
        self.tkt = NOOP_TICKET      # ledger ticket, set when queued
        self._state = "new"  # state: dispatcher.sendop guarded-by: AsyncTcpChannel._tx_lock  # noqa: PY02

    def advance(self, n: int) -> None:
        while n and self.i < len(self.views):
            v = self.views[self.i]
            if n >= v.nbytes:
                n -= v.nbytes
                self.i += 1
            else:
                self.views[self.i] = v[n:]
                n = 0

    @property
    def done(self) -> bool:
        return self.i >= len(self.views)


class Dispatcher:
    """One event-loop thread per node: selector + submission queue +
    completion queue (the progress engine)."""

    def __init__(self, name: str, conf, exec_submit, pin_fn=None):
        self.name = name
        self.conf = conf
        self._exec_submit = exec_submit  # node.submit — completion executor
        self._pin_fn = pin_fn
        self._sel = selectors.DefaultSelector()
        r, w = os.pipe()
        os.set_blocking(r, False)
        os.set_blocking(w, False)
        self._wake_r, self._wake_w = r, w
        self._sel.register(r, selectors.EVENT_READ, None)
        self._subs: Deque[Tuple] = deque()  # guarded-by: _subs_lock
        self._stopping = False  # guarded-by: _subs_lock
        # True from just before the submission drain until select
        # returns: a post() in that window MUST write the wakeup pipe
        # (the loop may be blocked in select); outside it the loop is
        # busy and will drain at the top of its next iteration — the
        # pipe syscalls are skipped (hot-path posts get cheap)
        self._armed = False  # guarded-by: _subs_lock
        self._subs_lock = dbg_lock("disp.subs", 72)
        self._comp_batch: List[Tuple] = []  # loop-thread only
        self._polling = False  # loop-thread only (nested-poll guard)
        # registered latency-class handlers (RPC channels, acceptors,
        # handshakes).  While zero, bulk channels skip the poll cadence
        # and run full-size GIL-free recv calls — chunking only costs
        # when there is actually control traffic to protect
        self._latency_handlers = 0  # loop-thread only
        # bounds concurrent big-body landing offloads onto the node's
        # completion pool (semaphore: no rank — never held across a
        # blocking call; try-acquire on the loop, released by workers)
        self.offload_sem = threading.Semaphore(_OFFLOAD_WORKERS)
        # adaptive busy-poll (the poll-mode progress engine): after an
        # iteration that did real work the loop re-polls the selector
        # non-blocking for this long before re-arming the blocking
        # select — back-to-back events (an RPC pong chased by the next
        # ping, successive bulk chunks draining a stripe) are serviced
        # at syscall cost with no sleep/wake transition on either side
        self._spin_s = conf.transport_poll_spin_us / 1e6
        self._m_loop_us = histogram("transport_dispatcher_loop_us")
        self._m_polls = counter("transport_dispatcher_latency_polls_total")
        self._m_sub_depth = gauge("transport_dispatcher_submission_depth")
        self._m_comp_depth = gauge("transport_dispatcher_completion_depth")
        self._m_submissions = counter(
            "transport_dispatcher_submissions_total")
        self._m_completions = counter(
            "transport_dispatcher_completions_total")
        self._m_batches = counter(
            "transport_dispatcher_completion_batches_total")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"disp-{name}",
        )
        self._thread.start()

    # -- submission side (any thread) ---------------------------------------
    def post(self, fn, *args) -> None:
        """Post one descriptor/action to the loop.  Never blocks; raises
        TransportError once the dispatcher is stopping."""
        with self._subs_lock:
            if self._stopping:
                raise TransportError(f"dispatcher {self.name} stopped")
            self._subs.append((fn, args))
            depth = len(self._subs)
            need_wake = self._armed
        self._m_submissions.inc()
        self._m_sub_depth.set(depth)
        if need_wake:
            self._wake()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\x00")
        except (BlockingIOError, InterruptedError):
            pass  # pipe full — a wakeup is already pending
        except OSError:
            pass  # torn down under us

    def stop(self) -> None:
        """Stop the loop: every registered handler is closed and every
        queued descriptor fails.  Idempotent; joins the loop thread."""
        with self._subs_lock:
            self._stopping = True
        self._wake()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)

    # -- completion side (loop thread) --------------------------------------
    def complete(self, fn, *args) -> None:  # on-loop
        """Queue one completion event; the batch is dispatched to the
        completion executor at the end of the loop iteration."""
        self._comp_batch.append((fn, args))

    def _flush_completions(self) -> None:  # on-loop
        batch, self._comp_batch = self._comp_batch, []
        if not batch:
            return
        self._m_completions.inc(len(batch))
        self._m_batches.inc()
        self._m_comp_depth.set(len(batch))
        try:
            self._exec_submit(_run_batch, batch)
        except BaseException:
            # completion executor gone (node teardown): deliver inline
            # so failure listeners still fire
            _run_batch(batch)

    def poll_latency(self) -> None:  # on-loop
        """Priority poll, called by BULK-class handlers between I/O
        chunks: drain pending submissions and service LATENCY-class
        socket events (RPC frames, accepts, handshakes) inline, then
        flush their completions — so control traffic preempts a
        multi-MiB transfer mid-stream instead of queueing behind it
        (the channel-specialization split, enforced inside the loop)."""
        if self._polling:
            return  # no recursive nesting
        self._polling = True
        self._m_polls.inc()
        try:
            self._drain_submissions()
            for key, mask in self._sel.select(0):
                handler = key.data
                if handler is None or not getattr(
                        handler, "latency_class", False):
                    continue
                try:
                    if mask & selectors.EVENT_READ:
                        handler.on_readable()
                    if mask & selectors.EVENT_WRITE:
                        handler.on_writable()
                except BaseException:
                    logger.exception("transport handler raised")
                    _safe(handler.loop_close,
                          TransportError("handler raised"))
            self._flush_completions()
        finally:
            self._polling = False

    def latency_active(self) -> bool:  # on-loop
        return self._latency_handlers > 0

    # -- selector plumbing (loop thread) ------------------------------------
    @staticmethod
    def _is_latency(handler) -> bool:
        # only RPC CHANNELS force the bulk planes into chunk+poll mode
        # — acceptors/handshakes are still SERVICED by polls, but a
        # mere listener must not tax bulk throughput on an idle node
        return bool(getattr(handler, "latency_counts", False))

    def sel_register(self, sock, events: int, handler) -> None:  # on-loop
        self._sel.register(sock, events, handler)
        if self._is_latency(handler):
            self._latency_handlers += 1

    def sel_modify(self, sock, events: int, handler) -> None:  # on-loop
        try:
            old = self._sel.get_key(sock).data
        except (KeyError, ValueError):
            old = None
        self._sel.modify(sock, events, handler)
        if old is not handler:
            if self._is_latency(old):
                self._latency_handlers -= 1
            if self._is_latency(handler):
                self._latency_handlers += 1

    def sel_unregister(self, sock) -> None:  # on-loop
        try:
            key = self._sel.get_key(sock)
        except (KeyError, ValueError, OSError):
            return
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            return
        if self._is_latency(key.data):
            self._latency_handlers -= 1

    # -- the loop ------------------------------------------------------------
    def _drain_wake(self) -> None:  # on-loop
        try:
            while os.read(self._wake_r, 4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _drain_submissions(self) -> bool:  # on-loop
        # lock-free empty peek: a post racing past it is drained at the
        # top of the next iteration (same guarantee as the armed-pipe
        # contract), and the hot no-work path pays no lock
        if not self._subs and not self._stopping:  # noqa: CK03 - racy peek
            return False
        with self._subs_lock:
            subs, stop = None, self._stopping
            if self._subs:
                subs = list(self._subs)
                self._subs.clear()
        if subs:
            self._m_sub_depth.set(0)
            for fn, args in subs:
                try:
                    fn(*args)
                except BaseException:
                    logger.exception("submission raised on dispatcher loop")
        return stop

    def _run(self) -> None:
        if self._pin_fn is not None:
            self._pin_fn()
        g = gauge("transport_threads", role="dispatcher_loop")
        g.inc()
        spin_deadline = 0.0
        try:
            while True:
                with self._subs_lock:
                    pending = bool(self._subs) or self._stopping
                    # posts landed while we were busy (no wakeup
                    # written) poll instead of blocking so they drain
                    # immediately; inside the spin window we also poll
                    # (busy-wait for the next completion, no sleep) —
                    # in both cases posters may skip the wakeup pipe
                    poll = pending or (
                        self._spin_s > 0.0
                        and time.monotonic() < spin_deadline
                    )
                    self._armed = not poll
                events = self._sel.select(0 if poll else None)
                # disarm + drain in ONE lock round trip (the wake path
                # is latency-critical: every saved acquisition is RTT)
                with self._subs_lock:
                    self._armed = False
                    stop = self._stopping
                    subs = None
                    if self._subs:
                        subs = list(self._subs)
                        self._subs.clear()
                if poll and not pending and not events and not subs \
                        and not stop:
                    continue  # empty spin poll: burn-and-retry
                t0 = time.monotonic()
                if subs:
                    self._m_sub_depth.set(0)
                    for fn, args in subs:
                        try:
                            fn(*args)
                        except BaseException:
                            logger.exception(
                                "submission raised on dispatcher loop")
                self._flush_completions()
                if len(events) > 1:
                    # interactive-before-bulk within one event batch
                    # (the qos/ scheduling-edge contract): RPC lanes,
                    # accepts and handshakes service ahead of bulk
                    # channels — stable sort, so per-class arrival
                    # order (and per-channel frame order) is untouched
                    events.sort(
                        key=lambda km: not getattr(
                            km[0].data, "latency_class", False
                        )
                    )
                for key, mask in events:
                    handler = key.data
                    if handler is None:
                        self._drain_wake()
                        continue
                    try:
                        if mask & selectors.EVENT_READ:
                            handler.on_readable()
                        if mask & selectors.EVENT_WRITE:
                            handler.on_writable()
                    except BaseException:
                        logger.exception("transport handler raised")
                        _safe(handler.loop_close,
                              TransportError("handler raised"))
                    # flush per handler, not per iteration: a completed
                    # read's callbacks reach the completion executor
                    # before the next handler's I/O, not after
                    self._flush_completions()
                now = time.monotonic()
                self._m_loop_us.observe((now - t0) * 1e6)
                spin_deadline = now + self._spin_s
                if stop:
                    break
        finally:
            self._teardown()
            g.dec()

    def _teardown(self) -> None:  # on-loop
        err = TransportError(f"dispatcher {self.name} stopped")
        for key in list(self._sel.get_map().values()):
            if key.data is not None:
                _safe(key.data.loop_close, err)
        self._flush_completions()
        try:
            self._sel.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass


# accept() errnos that mean the LISTENING socket itself is gone —
# anything else (ECONNABORTED, EMFILE, ENFILE, ENOBUFS, ...) is a
# per-connection or transient-pressure failure the listener survives
_FATAL_ACCEPT_ERRNOS = frozenset(
    (errno.EBADF, errno.EINVAL, errno.ENOTSOCK)
)


class Acceptor:
    """The listening socket on the loop — the CM listener with no
    thread.  Fresh connections enter a :class:`_Handshake` continuation;
    completed handshakes become :class:`AsyncTcpChannel`s on the same
    selector."""

    latency_class = True   # serviced by priority polls
    latency_counts = False  # but does not force bulk chunking

    def __init__(self, dispatcher: Dispatcher, node, sock: socket.socket):
        self._disp = dispatcher
        self._node = node
        self._sock = sock
        self._closed = False  # loop-thread only after registration

    def loop_register(self) -> None:  # on-loop
        self._disp.sel_register(self._sock, selectors.EVENT_READ, self)

    def on_readable(self) -> None:  # on-loop
        while True:
            try:
                sock, addr = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                if self._closed or e.errno in _FATAL_ACCEPT_ERRNOS:
                    self.loop_close(None)
                    return
                # transient: ECONNABORTED (the peer reset before we
                # accepted — routine when a connect attempt dies
                # mid-handshake) or fd/buffer pressure.  The LISTENER
                # is still healthy; closing it here would refuse every
                # future peer on this node forever.  Level-triggered
                # readiness re-fires for anything still queued.
                counter("transport_accept_transient_errors_total").inc()
                return
            try:
                sock.setblocking(False)
            except OSError:
                sock.close()
                continue
            hs = _Handshake(self._disp, self._node, sock, addr)
            self._disp.sel_register(sock, selectors.EVENT_READ, hs)

    def on_writable(self) -> None:  # on-loop
        pass

    def loop_close(self, _err) -> None:  # on-loop
        if self._closed:
            return
        self._closed = True
        self._disp.sel_unregister(self._sock)
        try:
            self._sock.close()
        except OSError:
            pass

    def request_close(self) -> None:
        """Close from any thread (network unregister): route through
        the loop; fall back to a direct close when it is already gone."""
        try:
            self._disp.post(self.loop_close, None)
        except TransportError:
            try:
                self._sock.close()
            except OSError:
                pass


class _Handshake:
    """Non-blocking hello continuation for one accepted socket
    (the CONNECT_REQUEST/ESTABLISHED exchange, RdmaNode.java:114-214)."""

    latency_class = True   # 9 bytes; never worth queueing behind bulk
    latency_counts = False

    def __init__(self, dispatcher: Dispatcher, node, sock, addr):
        self._disp = dispatcher
        self._node = node
        self._sock = sock
        self._addr = addr
        self._buf = bytearray(wire._HELLO.size)
        self._got = 0
        # once the socket is handed to its channel (or closed), a
        # STALE readiness event from the outer loop — this handshake
        # may have completed inside a nested priority poll — must not
        # touch the socket again (it would eat the first frame's bytes)
        self._done = False

    def on_readable(self) -> None:  # on-loop
        if self._done:
            return
        try:
            n = self._sock.recv_into(
                memoryview(self._buf)[self._got:],
                wire._HELLO.size - self._got,
            )
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self.loop_close(None)
            return
        if n == 0:
            self.loop_close(None)
            return
        self._got += n
        if self._got < wire._HELLO.size:
            return
        try:
            magic, type_idx, src_port, version = wire._HELLO.unpack(
                bytes(self._buf)
            )
            if magic != wire._MAGIC \
                    or type_idx >= len(wire._TYPE_BY_INDEX):
                raise TransportError(f"bad hello from {self._addr}")
            if not (wire.MIN_WIRE_VERSION <= version
                    <= wire.WIRE_VERSION):
                # structured rejection (NAK + both versions) — the 5
                # bytes always fit a fresh socket's send buffer; the
                # connector's error names both sides
                self._sock.send(
                    b"\x00"
                    + wire._HELLO_REJ.pack(wire.WIRE_VERSION, version)
                )
                counter("wire_version_rejects_total").inc()
                raise TransportError(
                    f"protocol version mismatch from {self._addr}: "
                    f"hello spoke wire version {version}, this node "
                    f"accepts {wire.MIN_WIRE_VERSION}.."
                    f"{wire.WIRE_VERSION}"
                )
            # the 1-byte ack always fits a fresh socket's send buffer
            self._sock.send(b"\x01")
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        except (BlockingIOError, InterruptedError, OSError,
                TransportError):
            logger.warning("handshake with %s failed", self._addr)
            self.loop_close(None)
            return
        req_type = wire._TYPE_BY_INDEX[type_idx]
        peer = (self._addr[0], src_port)
        ch = AsyncTcpChannel(
            wire._PAIRED.get(req_type, req_type), self._node, peer,
            self._sock, self._disp,
        )
        ch.wire_version = version  # the hello's (accepted) generation
        ch._set_state(ChannelState.CONNECTED)
        # swap this socket's handler from the handshake to the channel
        self._done = True
        self._disp.sel_modify(self._sock, selectors.EVENT_READ, ch)
        ch._mark_registered()
        self._node.register_passive_channel(ch)

    def on_writable(self) -> None:  # on-loop
        pass

    def loop_close(self, _err) -> None:  # on-loop
        if self._done:
            return
        self._done = True
        self._disp.sel_unregister(self._sock)
        try:
            self._sock.close()
        except OSError:
            pass


class AsyncTcpChannel(Channel):
    """One TCP connection driven entirely by the node's dispatcher
    loop: sends are posted as descriptors and written as partial
    ``sendmsg`` continuations; receives run the same framed state
    machine as ``TcpChannel`` but re-entrantly, landing striped read
    responses straight into their registered dest buffers.  Wire format
    identical to ``TcpChannel`` — the two interoperate."""

    supports_scatter = True

    #: recv-machine states
    _HDR, _RPC, _REQ, _RESP_HDR, _RESP_WHOLE, _RESP_LEN, _RESP_BLOCK, \
        _RESP_ERR, _DISCARD = (
            "hdr", "rpc", "req", "resp_hdr", "resp_whole", "resp_len",
            "resp_block", "resp_err", "discard",
        )

    #: the recv machine rides NEXT TO the inherited channel.lifecycle
    #: machine, so its table lives under the RX_ prefix (``table: RX``)
    RX_STATES = _HDR, _RPC, _REQ, _RESP_HDR, _RESP_WHOLE, _RESP_LEN, \
        _RESP_BLOCK, _RESP_ERR, _DISCARD
    RX_INITIAL = "hdr"
    RX_TERMINAL = ()
    RX_TRANSITIONS = {
        "hdr": ("rpc", "req", "resp_hdr"),
        "rpc": ("hdr",),
        "req": ("hdr",),
        # resp_hdr fans out: empty/error bodies settle straight back to
        # hdr, torn-down reads drain via discard, scatter reads walk
        # the len/block loop, whole-frame landings take resp_whole
        "resp_hdr": ("hdr", "discard", "resp_err", "resp_whole",
                     "resp_len"),
        "resp_whole": ("hdr",),
        "resp_len": ("resp_block", "hdr"),
        "resp_block": ("resp_len", "hdr"),
        "resp_err": ("hdr",),
        "discard": ("hdr",),
    }

    def __init__(self, channel_type: ChannelType, node, peer, sock,
                 dispatcher: Dispatcher):
        super().__init__(channel_type, node.conf.send_queue_depth)
        self.node = node
        self.peer = peer
        self._sock = sock
        self._disp = dispatcher
        self._sg = (
            node.conf.transport_scatter_gather
            and hasattr(sock, "sendmsg")
        )
        # latency-class channels (RPC) are serviced by bulk channels'
        # priority polls; bulk channels chunk their I/O at _POLL_BYTES
        # and poll between chunks
        self.latency_class = channel_type in _LATENCY_TYPES
        self.latency_counts = self.latency_class
        self._bulk = not self.latency_class
        self._backlog_hi = node.conf.transport_send_backlog_bytes
        # pinned socket buffers (the QP ring-size analog): a whole
        # stripe parks in the kernel between loop visits instead of
        # trickling through autotune growth; kernel doubles + caps at
        # net.core.{w,r}mem_max
        bufs = node.conf.transport_socket_buffer_bytes
        if bufs:
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, bufs)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, bufs)
            except OSError:
                pass
        # receive-wakeup coalescing (interrupt moderation): mid-body,
        # SO_RCVLOWAT batches epoll wakeups to ~_coalesce bytes; EOF
        # and socket errors always wake regardless, so dead peers are
        # still detected promptly.  Bulk lanes only — RPC must wake on
        # the first byte
        self._coalesce = (
            node.conf.transport_recv_coalesce_bytes if self._bulk else 0
        )
        if self._coalesce:
            # clamp the watermark under what the receive buffer can
            # actually hold: select/epoll honor RCVLOWAT, so a
            # watermark the buffer can never reach would simply never
            # report readable — a silent permanent stall on the
            # on-loop landing path.  Pinned buffers bound at the
            # pinned size (the kernel doubles the request); autotuned
            # ones at half the tcp_rmem growth ceiling.
            cap = bufs if bufs else _rmem_max() // 2
            self._coalesce = max(1, min(self._coalesce, cap))
        # big-body landing offload threshold; 0 (the default) keeps
        # every landing on-loop — see the _OFFLOAD_WORKERS note
        self._offload_min = node.conf.transport_stream_offload_bytes
        self._lowat = 1  # loop-thread only
        self._next_req = 1  # guarded-by: _reads_lock
        # req_id -> (count, listener, post time, dest, on_progress,
        #            total bytes)
        self._reads = {}  # guarded-by: _reads_lock
        # response bytes posted but not yet settled — the lane-stream
        # trigger (read racily off-loop: a stale value only delays or
        # double-checks a stream handoff, never corrupts state)
        self._rx_outstanding = 0  # guarded-by: _reads_lock
        self._reads_lock = dbg_lock("adisp.reads", 68)
        # ---- send side: shared between posting threads and the loop.
        # INLINE SENDS (the fabric-lib small-message idiom): a posting
        # thread whose channel has no queued tx writes the descriptor
        # straight to the non-blocking socket under _tx_lock — serve
        # workers push response bytes in big GIL-free sendmsg calls
        # (exactly the threaded path's send behavior) and RPC pings
        # reach the wire without a loop hop; only the EAGAIN remainder
        # is left for the loop to drain on EVENT_WRITE.  The lock also
        # serializes the fd's close against in-flight writes.
        # resource: dispatcher.send_ops (queued outbound descriptors)
        self._tx: Deque[_SendOp] = deque()  # guarded-by: _tx_lock
        self._tx_bytes = 0  # guarded-by: _tx_lock
        # True while a serve worker synchronously drains the tx queue
        # (_drain_tx_blocking) — at most one drainer per channel; the
        # loop and other posters leave the queue to it
        self._tx_draining = False  # guarded-by: _tx_lock
        self._closed = False  # written under _tx_lock (read racily)
        # single-owner fd close: with the recv machine streamable onto
        # workers and a teardown fallback on the stop() path, more than
        # one thread can reach "I should close this fd" — the flag
        # makes exactly ONE of them win, so a recycled fd number can
        # never be closed out from under an unrelated socket
        self._fd_closed = False  # guarded-by: _tx_lock
        self._tx_lock = dbg_lock("adisp.tx", 71)
        # owns: tcp.fds -> _close_fd_locked
        self._fd_tkt = ledger_acquire("tcp.fds")  # acquires: tcp.fds
        # ---- loop-thread-only state (never touched off-loop) ----
        self._events = 0
        self._registered = False
        self._read_paused = False
        self._rx_state = self._HDR  # state: channel.recv table: RX
        self._rx_view: Optional[memoryview] = None  # current fill target
        self._rx_got = 0
        self._rx_store = None       # backing object of _rx_view
        self._rx_frame_len = 0
        self._rx_entry = None       # (count, listener, t0, dest, on_progress)
        self._rx_idx = 0
        self._rx_blocks: List = []
        self._rx_block = None       # object delivered for current block
        self._rx_discard = 0
        self._rx_scratch = bytearray(_SCRATCH)
        # True while a completion worker owns the socket's recv side
        # (lane streaming); loop-thread written, the worker reads
        # _closed under _tx_lock for the fd-close handoff.  _on_worker
        # is the delivery-context flag: while the WORKER runs the recv
        # machine, completions deliver inline on it (the threaded
        # reader's shape) instead of hopping through the loop's
        # completion batches
        self._rx_offloaded = False
        self._on_worker = False  # touched only by the machine's owner
        # last completed-frame instant — the hot-conversation trigger
        # (machine-owner only, like the rest of the rx state)
        self._last_frame_t = 0.0
        self._arm_fixed(self._HDR, wire._HDR.size)
        # same metric series as the threaded path — it IS tcp wire
        self._m_bytes_sent = counter(
            "transport_bytes_sent_total", transport="tcp")
        self._m_bytes_recv = counter(
            "transport_bytes_received_total", transport="tcp")
        self._m_msgs_sent = counter(
            "transport_msgs_sent_total", transport="tcp")
        self._m_msgs_recv = counter(
            "transport_msgs_received_total", transport="tcp")
        self._m_read_rtt = histogram(
            "transport_read_rtt_ms", transport="tcp")
        self._m_fail_outstanding = counter(
            "transport_fail_outstanding_total", transport="tcp")
        self._m_sendmsg_bytes = counter(
            "transport_sendmsg_bytes_total", transport="tcp")
        self._m_backlog = gauge("transport_send_backlog_bytes")
        self._m_offloads = counter(
            "transport_dispatcher_lane_streams_total", transport="tcp")

    # -- attach (connector side) --------------------------------------------
    @classmethod
    def attach(cls, channel_type: ChannelType, node, peer,
               sock: socket.socket) -> "AsyncTcpChannel":
        """Wrap a freshly handshaken socket and hand it to the node's
        dispatcher (the connector-side entry; the acceptor side attaches
        on the loop itself)."""
        disp = node.get_dispatcher()
        sock.setblocking(False)
        ch = cls(channel_type, node, peer, sock, disp)
        ch._set_state(ChannelState.CONNECTED)
        try:
            disp.post(ch._loop_register)
        except TransportError:
            # settle through the single-owner close so the channel's
            # fd accounting closes with the socket
            ch._close_fd()
            raise
        return ch

    def _mark_registered(self) -> None:  # on-loop
        self._registered = True
        self._events = selectors.EVENT_READ

    def _loop_register(self) -> None:  # on-loop
        if self._closed:
            return
        self._events = selectors.EVENT_READ
        self._disp.sel_register(self._sock, self._events, self)
        self._registered = True

    # -- posting (any thread) ------------------------------------------------
    def _frame_op(self, opcode: int, parts, frames: int,
                  on_done=None) -> _SendOp:
        views = [v for v in map(wire._as_view, parts) if v.nbytes]
        length = sum(v.nbytes for v in views)
        hdr = wire._HDR.pack(opcode, length)
        if not self._sg:
            # legacy concat wire path (A/B parity with
            # transportScatterGather=off): one copy, one buffer
            buf = bytearray(hdr)
            for v in views:
                buf += v
            views = [memoryview(buf)]
        else:
            views = [memoryview(hdr)] + views
        return _SendOp(views, wire._HDR.size + length, frames, on_done)

    def _post_op(self, op: _SendOp, drain: bool = False) -> None:
        """Submit one send descriptor; a dead dispatcher fails it on
        the caller thread (the threaded path's synchronous-post-failure
        analog).

        Inline-send fast path: when the channel's tx queue is empty,
        THIS thread writes the descriptor to the non-blocking socket
        immediately under ``_tx_lock`` — big serve responses leave on
        the serve worker in GIL-free ``sendmsg`` calls and small RPC
        frames hit the wire with no loop hop; whatever the kernel
        refuses (EAGAIN) stays queued and the loop is kicked to drain
        it on EVENT_WRITE.

        ``drain=True`` (serve workers, which may block) goes one step
        further: instead of handing the EAGAIN remainder to the loop,
        THIS thread finishes the drain itself in ``_drain_tx_blocking``
        — writability waits + GIL-free sendmsg, the threaded serve
        path's blocking-``sendall`` shape without loop round trips.
        One drainer per channel; concurrent posters just append."""
        done_ops: List[_SendOp] = []
        err = None
        queued = False
        drained_here = False
        rejected = None  # op refused by an already-closed channel
        with self._tx_lock:
            if self._closed:
                err = TransportError("channel stopped")
                rejected = op
            else:
                # a queued descriptor leaves the tx queue exactly once:
                # fully written (_write_locked pops it) or swept by a
                # teardown path that fails the queue
                # owns: dispatcher.send_ops -> _write_locked
                # owns: dispatcher.send_ops -> _fail_tx
                # owns: dispatcher.send_ops -> _loop_fail
                op.tkt = ledger_acquire(
                    "dispatcher.send_ops"
                )  # acquires: dispatcher.send_ops
                op._transition("queued", frm="new")
                self._tx.append(op)
                self._tx_bytes += op.total
                self._m_backlog.inc(op.total)
                if len(self._tx) == 1 or self._tx_draining:
                    # inline send: socket is NON-blocking (see
                    # _write_locked's contract) — not a blocking call
                    # under _tx_lock.  With a drainer active, skip —
                    # it owns the queue.
                    if not self._tx_draining:
                        err = self._write_locked(done_ops)  # noqa: CK02
                queued = bool(self._tx) and err is None
                if queued and drain and not self._tx_draining:
                    self._tx_draining = True
                    drained_here = True
                # decided under the lock: a drainer active HERE is
                # guaranteed to see our op (it re-checks _tx under
                # _tx_lock before retiring)
                covered = drained_here or self._tx_draining
        if err is None:
            for d in done_ops:
                if d.on_done is not None:
                    _safe(d.on_done, None)
            if drained_here:
                self._drain_tx_blocking()
            elif queued and not covered:
                try:
                    self._disp.post(self._loop_kick)
                except TransportError as e:
                    self._fail_tx(e)
            return
        # write failed (or channel already stopped): completed ops
        # still succeeded; everything queued — including op — fails,
        # and (on a write failure) the loop is asked to tear the
        # socket down
        for d in done_ops:
            if d.on_done is not None:
                _safe(d.on_done, None)
        if rejected is not None:
            # closed before the post: the op was never queued and the
            # teardown already ran — fail JUST this descriptor
            rejected._transition("failed", frm="new")
            if rejected.on_done is not None:
                _safe(rejected.on_done, err)
            return
        self._error(err)
        self._fail_tx(err)
        try:
            self._disp.post(self._loop_close)
        except TransportError:
            pass

    def _write_locked(self, done_ops: List[_SendOp]):
        """Drain the tx queue onto the socket until EAGAIN or empty —
        caller holds ``_tx_lock``.  Completed ops are appended to
        ``done_ops`` (their callbacks run after the lock drops); a
        socket error is RETURNED, and the tx queue is failed by the
        caller.  The socket is non-blocking, so the ``sendmsg`` here
        returns immediately (the GIL is dropped only for the kernel
        copy) — not a blocking send under a lock."""
        while self._tx:  # noqa: CK03 - caller holds _tx_lock
            op = self._tx[0]  # noqa: CK03 - caller holds _tx_lock
            try:
                if self._sg:
                    n = self._sock.sendmsg(  # noqa: CK02
                        op.views[op.i:op.i + _IOV_MAX])
                else:
                    n = self._sock.send(op.views[op.i])
            except (BlockingIOError, InterruptedError):
                return None
            except OSError as e:
                return TransportError(f"send failed: {e}")
            if n <= 0:
                return None
            self._m_sendmsg_bytes.inc(n)
            op.advance(n)
            if op.done:
                self._tx.popleft()  # noqa: CK03 - caller holds _tx_lock
                self._tx_bytes -= op.total  # noqa: CK03 - caller holds _tx_lock
                self._m_backlog.dec(op.total)
                self._m_msgs_sent.inc(op.frames)
                self._m_bytes_sent.inc(op.total)
                op.tkt.release()  # releases: dispatcher.send_ops
                op._transition("sent", frm="queued")
                done_ops.append(op)
        return None

    def _drain_tx_blocking(self) -> None:
        """Finish the tx queue on THIS (serve-worker) thread: repeated
        non-blocking ``_write_locked`` bursts with short writability
        waits in between — the threaded serve path's blocking
        ``sendall`` shape, minus any loop involvement.  Caller set
        ``_tx_draining`` under ``_tx_lock``.  The wait runs WITHOUT the
        lock and with a bounded tick: if the channel closes (and the fd
        number is even reused) under us, the next burst re-checks
        ``_closed`` under the lock and retires; a stale-fd ``select``
        can at worst idle one tick."""
        while True:
            done_ops: List[_SendOp] = []
            fd = -1
            with self._tx_lock:
                if self._closed or not self._tx:
                    self._tx_draining = False
                    err, pending = None, False
                else:
                    # non-blocking socket (see _write_locked) — not CK02
                    err = self._write_locked(done_ops)  # noqa: CK02
                    pending = bool(self._tx) and err is None
                    if not pending:
                        self._tx_draining = False
                if pending:
                    try:
                        fd = self._sock.fileno()
                    except OSError:
                        fd = -1
            for d in done_ops:
                if d.on_done is not None:
                    _safe(d.on_done, None)
            if err is not None:
                with self._tx_lock:
                    self._tx_draining = False
                self._error(err)
                self._fail_tx(err)
                try:
                    self._disp.post(self._loop_close)
                except TransportError:
                    pass
                return
            if not pending:
                return
            if fd >= 0:
                try:
                    select.select([], [fd], [fd], _OFFLOAD_TIMEOUT)
                except (OSError, ValueError):
                    pass  # fd torn down under us; loop re-checks _closed

    def _close_fd_locked(self) -> None:
        """Close the fd exactly once — caller holds ``_tx_lock``."""
        if not self._fd_closed:  # noqa: CK03 - caller holds _tx_lock
            self._fd_closed = True  # noqa: CK03 - caller holds _tx_lock
            tkt, self._fd_tkt = self._fd_tkt, NOOP_TICKET
            tkt.release()  # releases: tcp.fds  # one-shot
            try:
                self._sock.close()
            except OSError:
                pass

    def _close_fd(self) -> None:
        with self._tx_lock:
            self._close_fd_locked()

    def _stream_drain_tx(self):
        """Drain queued tx from the STREAMING worker: while a lane is
        streamed its socket is off the selector, so EVENT_WRITE can
        never re-arm — a frame the inline send EAGAIN'd would strand
        until the next post.  The worker's select watches writability
        whenever tx is pending and drains here.  Returns a
        TransportError on socket failure (the worker turns it into the
        stream error), None otherwise."""
        done_ops: List[_SendOp] = []
        with self._tx_lock:
            if self._closed:
                return None
            if self._tx_draining:
                return None  # a serve-worker drainer owns the queue
            # non-blocking socket (see _write_locked) — not CK02
            err = self._write_locked(done_ops)  # noqa: CK02
        for d in done_ops:
            if d.on_done is not None:
                _safe(d.on_done, None)
        return err

    def _fail_tx(self, err: BaseException) -> None:
        """Fail every queued descriptor (any thread)."""
        with self._tx_lock:
            tx, self._tx = list(self._tx), deque()
            if self._tx_bytes:
                self._m_backlog.dec(self._tx_bytes)
            self._tx_bytes = 0
        for op in tx:
            op.tkt.release()  # releases: dispatcher.send_ops
            op._transition("failed")
            if op.on_done is not None:
                _safe(op.on_done, err)

    def _send_msg(self, opcode: int, parts) -> None:
        """Post one raw frame (fire-and-forget) — the threaded path's
        ``_send_msg`` sibling, used by chaos/fault tests to inject
        hand-crafted frames.  Delivery is asynchronous."""
        self._post_op(self._frame_op(opcode, parts, 1))

    def _post_rpc(self, frames, listener: CompletionListener) -> None:
        parts: List = []
        for f in frames:
            v = wire._as_view(f)
            parts.append(wire._HDR.pack(wire.OP_RPC, v.nbytes))
            parts.append(v)
        views = [memoryview(wire._as_view(p)) for p in parts if len(p)]
        total = sum(v.nbytes for v in views)
        if not self._sg:
            buf = bytearray()
            for v in views:
                buf += v
            views = [memoryview(bytes(buf))]

        def done(err):
            if err is not None:
                self._error(err)
                self._fail(listener, err)
            else:
                self._complete(listener, None)
            self._release_budget()

        if FAULTS.enabled:
            try:
                FAULTS.check("send")
            except TransportError as e:
                done(e)
                return
        self._post_op(_SendOp(views, total, len(frames), done))

    def _post_read(self, locations: List[BlockLocation],
                   listener: CompletionListener,
                   dest=None, on_progress=None, ctx=None) -> None:
        total = sum(loc.length for loc in locations)
        with self._reads_lock:
            req_id = self._next_req
            self._next_req += 1
            self._reads[req_id] = (
                len(locations), listener, time.monotonic(), dest,
                on_progress, total,
            )
            self._rx_outstanding += total
        payload = bytearray(wire._REQ_HDR.pack(req_id, len(locations)))
        for loc in locations:
            payload += wire._LOC.pack(loc.address, loc.length, loc.mkey)
        if ctx is not None and self.wire_version != 1:
            # optional v2 tail; suppressed on channels negotiated down
            payload += wire._TRACE_CTX.pack(ctx[0], ctx[1])
            if RECORDER.enabled:
                fr_event(
                    "transport", "wire_send",
                    trace_id=ctx[0], span_id=ctx[1],
                    locs=len(locations),
                )

        def done(err):
            if err is not None:
                with self._reads_lock:
                    entry = self._reads.pop(req_id, None)
                    if entry is not None:
                        self._rx_outstanding -= entry[5]
                self._error(err)
                self._fail(listener, err)
                self._release_budget()
            # success: budget released when the response arrives

        if FAULTS.enabled:
            try:
                FAULTS.check("send")
            except TransportError as e:
                done(e)
                return
        self._post_op(self._frame_op(wire.OP_READ_REQ, (payload,), 1, done))

    # -- send machine (loop side) -------------------------------------------
    def _loop_kick(self) -> None:  # on-loop
        """Arm/drain the tx remainder an inline send left behind."""
        if not self._closed:
            self._flush_tx()

    def on_writable(self) -> None:  # on-loop
        self._flush_tx()

    def _flush_tx(self) -> None:  # on-loop
        done_ops: List[_SendOp] = []
        with self._tx_lock:
            # non-blocking socket (see _write_locked) — not CK02
            err = None if self._closed \
                else self._write_locked(done_ops)  # noqa: CK02
        for d in done_ops:
            if d.on_done is not None:
                self._disp.complete(d.on_done, None)
        if err is not None:
            self._loop_fail(err)
            return
        self._update_interest()

    def _update_interest(self) -> None:  # on-loop
        if self._closed or not self._registered:
            return
        with self._tx_lock:
            pending = bool(self._tx)
            backlog = self._tx_bytes
        # per-channel write backpressure: a peer that stops draining
        # its responses gets its READ interest parked until the backlog
        # halves — new requests stay in the kernel / its TCP window
        if self._read_paused:
            if backlog <= self._backlog_hi // 2:
                self._read_paused = False
        elif backlog > self._backlog_hi:
            self._read_paused = True
        want = 0 if self._read_paused else selectors.EVENT_READ
        if pending:
            want |= selectors.EVENT_WRITE
        if not want:
            want = selectors.EVENT_WRITE  # paused + drained: impossible,
            # but the selector needs a non-empty interest set
        if want != self._events:
            self._events = want
            self._disp.sel_modify(self._sock, want, self)

    # -- recv machine (loop thread) -----------------------------------------
    def _transition_rx(self, state: str) -> None:  # on-loop
        if GLOBAL_STATE_DEBUG.enabled:
            check_named(self, state, name="channel.recv", field="_rx_state",
                        transitions=self.RX_TRANSITIONS)
        self._rx_state = state

    def _arm_fixed(self, state: str, n: int) -> None:  # on-loop
        self._transition_rx(state)
        self._rx_store = bytearray(n)
        self._rx_view = memoryview(self._rx_store)
        self._rx_got = 0

    def _arm_into(self, state: str, store, view: memoryview) -> None:  # on-loop
        self._transition_rx(state)
        self._rx_store = store
        self._rx_view = view
        self._rx_got = 0

    def _recv_buffer(self, length: int):
        """Pooled receive buffer (zero-copy slices for the consumer)
        with a plain bytearray fallback — the threaded ``_recv_payload``
        allocation policy."""
        if length == 0:
            return b""
        pool = getattr(self.node, "staging_pool", None)
        if pool is not None:
            try:
                arr = pool.alloc_gc(length)
            except MemoryError:
                arr = None
            if arr is not None:
                return arr
        return bytearray(length)

    def on_readable(self) -> None:  # on-loop
        self._rx_pump()
        if not self._closed and not self._rx_offloaded and self._coalesce:
            self._tune_lowat()

    def _tune_lowat(self) -> None:  # on-loop
        """Set the receive low-watermark for the CURRENT arm target:
        ``_coalesce`` while ≥ that many body bytes are still expected
        (one wakeup per ~watermark of queued bytes), 1 for headers and
        body tails — a tail below the watermark would otherwise never
        wake the loop (rcvbuf autotuning stalls when the app stops
        reading)."""
        if self._rx_state == self._DISCARD:
            rem = self._rx_discard
        elif self._rx_view is not None:
            rem = self._rx_view.nbytes - self._rx_got
        else:
            rem = 0
        want = self._coalesce if rem >= self._coalesce else 1
        if want != self._lowat:
            try:
                self._sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVLOWAT, want
                )
                self._lowat = want
            except OSError:
                self._coalesce = 0  # platform without RCVLOWAT

    def _rx_pump(self) -> None:  # on-loop
        budget = _FAIR_BUDGET
        polled = 0
        frames = 0
        # chunk + priority-poll only while the loop actually hosts
        # latency-class traffic; otherwise bulk runs full-size
        # GIL-free recv calls (the threaded reader's syscall shape)
        chunked = self._bulk and self._disp.latency_active()
        while not self._closed:
            if budget <= 0:
                # fairness: yield the loop; the level-triggered
                # selector re-reports the remainder immediately
                return
            if self._rx_state == self._DISCARD:
                got = self._rx_run_discard()
                if not got:
                    return
                budget -= got
                continue
            # ≥2 full frames in ONE readiness callback = an inbound
            # burst (a windowed requester fires its whole window
            # back-to-back) — stream the responder side too, not just
            # lanes with outstanding READS of our own
            if self._rx_maybe_offload(force=frames >= 2):
                return
            want = self._rx_view.nbytes - self._rx_got
            if chunked and want > _POLL_BYTES:
                # chunk bulk receives at the poll cadence so RPC
                # events preempt mid-stream
                want = _POLL_BYTES
            try:
                n = self._sock.recv_into(
                    self._rx_view[self._rx_got:], want,
                )
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self._loop_fail(TransportError(f"recv failed: {e}"))
                return
            if n == 0:
                self._loop_fail(
                    TransportError("connection closed by peer")
                )
                return
            budget -= n
            self._rx_got += n
            if chunked:
                polled += n
                if polled >= _POLL_BYTES:
                    polled = 0
                    self._disp.poll_latency()
                    if self._closed:
                        return
            if self._rx_got < self._rx_view.nbytes:
                if n < want:
                    return  # kernel buffer drained; wait for the event
                continue
            try:
                self._rx_dispatch()
            except TransportError as e:
                self._loop_fail(e)
                return
            except BaseException as e:
                logger.exception("recv state machine failed")
                self._loop_fail(TransportError(f"recv failed: {e}"))
                return
            if self._rx_state == self._HDR:
                # a LOGICAL frame completed (not a mid-response state
                # hop, which arms something else)
                frames += 1
                t = time.monotonic()
                hot = t - self._last_frame_t < _HOT_FRAME_S
                self._last_frame_t = t
                if hot and self._rx_maybe_offload(force=True):
                    return

    def _rx_run_discard(self) -> int:  # on-loop
        """Consume discard-path bytes; returns how many were read
        (0 = would-block or channel failed)."""
        want = min(self._rx_discard, _SCRATCH)
        try:
            n = self._sock.recv_into(
                memoryview(self._rx_scratch)[:want], want
            )
        except (BlockingIOError, InterruptedError):
            return 0
        except OSError as e:
            self._loop_fail(TransportError(f"recv failed: {e}"))
            return 0
        if n == 0:
            self._loop_fail(TransportError("connection closed by peer"))
            return 0
        self._rx_discard -= n
        if self._rx_discard == 0:
            self._arm_fixed(self._HDR, wire._HDR.size)
        return n

    def _rx_dispatch(self) -> None:  # on-loop
        """One completed fixed-size read: advance the frame state
        machine (the re-entrant ``_read_loop``)."""
        state = self._rx_state
        if state == self._HDR:
            opcode, length = wire._HDR.unpack(bytes(self._rx_store))
            if FAULTS.enabled:
                # raising here rides the _rx_pump failure path: the
                # channel dies and outstanding reads fail structured
                FAULTS.check("recv")
            if length > wire._MAX_FRAME:
                raise TransportError(f"oversized frame: {length}B")
            if wiredbg.wire_debug_enabled():
                herr = wiredbg.header_error("dispatcher", opcode, length)
                if herr is not None:
                    raise TransportError(f"wireDebug: {herr}")
            self._m_msgs_recv.inc()
            self._m_bytes_recv.inc(wire._HDR.size + length)
            if opcode == wire.OP_RPC:
                if length == 0:
                    self._rx_rpc_frame(b"")
                    self._arm_fixed(self._HDR, wire._HDR.size)
                else:
                    self._arm_fixed(self._RPC, length)
            elif opcode == wire.OP_READ_REQ:
                if length == 0:
                    self._arm_fixed(self._HDR, wire._HDR.size)
                    self.node.submit_serve(
                        self._serve_read_async, (b"", time.monotonic()),
                        0, deferred=True,
                    )
                else:
                    self._arm_fixed(self._REQ, length)
            elif opcode == wire.OP_READ_RESP:
                if FAULTS.enabled:
                    FAULTS.check("read_resp")
                if length < wire._RESP_HDR.size:
                    raise TransportError(f"short read response: {length}B")
                self._rx_frame_len = length
                self._arm_fixed(self._RESP_HDR, wire._RESP_HDR.size)
            else:
                # desynced byte stream: the channel must die, but
                # counted and scoped (outstanding reads fail with a
                # structured error; the node stays up)
                counter(
                    "wire_unknown_frames_total",
                    engine="dispatcher", kind="opcode",
                ).inc()
                raise TransportError(f"unknown opcode {opcode}")
        elif state == self._RPC:
            frame = bytes(self._rx_store)
            self._arm_fixed(self._HDR, wire._HDR.size)
            self._rx_rpc_frame(frame)
        elif state == self._REQ:
            payload = bytes(self._rx_store)
            self._arm_fixed(self._HDR, wire._HDR.size)
            # resolution runs on the bounded serve pool (mapped-file
            # reads may fault — never on the loop); its byte credits
            # are released by the response's send-completion event
            self.node.submit_serve(
                self._serve_read_async, (payload, time.monotonic()),
                wire._req_cost(payload), deferred=True,
                mkey=wire._req_mkey(payload),
            )
        elif state == self._RESP_HDR:
            self._rx_resp_hdr()
        elif state == self._RESP_WHOLE:
            self._rx_resp_whole()
        elif state == self._RESP_LEN:
            self._rx_resp_len()
        elif state == self._RESP_BLOCK:
            self._rx_block_done(self._rx_block, self._rx_view.nbytes)
        elif state == self._RESP_ERR:
            reason = bytes(self._rx_store).decode("utf-8", "replace")
            self._rx_settle(None, decode_remote_error(reason))
        else:  # pragma: no cover - state machine exhaustive
            raise TransportError(f"bad recv state {state}")

    def _rx_rpc_frame(self, frame: bytes) -> None:  # on-loop
        """Hand one RPC frame to the application dispatch plane —
        schema-validated first under wireDebug (a rejected frame is
        counted, hexdump-logged, and dropped: one-frame blast
        radius)."""
        if (wiredbg.wire_debug_enabled()
                and not wiredbg.rpc_frame_ok("dispatcher", frame)):
            return
        self.node.dispatch_frame(self, frame)

    def _rx_resp_hdr(self) -> None:  # on-loop
        req_id, status = wire._RESP_HDR.unpack(bytes(self._rx_store))
        body = self._rx_frame_len - wire._RESP_HDR.size
        # bytes of this frame's body not yet consumed — the hard bound
        # every block-length prefix is validated against (a lying
        # prefix must never read into the next frame or size an
        # allocation)
        self._rx_resp_left = body
        with self._reads_lock:
            entry = self._reads.pop(req_id, None)
        if entry is None:
            # raced with teardown: drop the body without materializing
            if body == 0:
                self._arm_fixed(self._HDR, wire._HDR.size)
            else:
                self._rx_discard = body
                self._transition_rx(self._DISCARD)
            return
        self._rx_entry = entry
        self._rx_idx = 0
        self._rx_blocks = []
        count, _listener, _t0, dest, _prog, _total = entry
        if status != 0:
            if body == 0:
                self._rx_settle(None, TransportError("read failed"))
            else:
                self._arm_fixed(self._RESP_ERR, body)
        elif dest is None:
            # whole-frame landing in ONE pooled buffer, blocks served
            # as zero-copy slices (threaded _recv_payload parity)
            store = self._recv_buffer(body)
            if body == 0:
                self._rx_store = store
                self._rx_resp_whole()
            else:
                self._arm_into(
                    self._RESP_WHOLE, store,
                    wire._as_view(store)[:body],
                )
        elif count == 0:
            self._rx_settle([], None)
        else:
            self._rx_next_block()

    def _rx_resp_whole(self) -> None:  # on-loop
        count, _listener, _t0, _dest, on_progress, _total = self._rx_entry
        store = self._rx_store
        if isinstance(store, np.ndarray):
            store.flags.writeable = False
        payload = store if isinstance(store, np.ndarray) else bytes(store)
        blocks, off = [], 0
        for _ in range(count):
            (n,) = wire._LEN.unpack_from(payload, off)
            off += wire._LEN.size
            if n > len(payload) - off:
                # lying length prefix: fail the read, then tear the
                # (desynced) channel down — never silently truncate
                self._rx_settle(None, TransportError(
                    f"block length {n}B exceeds response remainder "
                    f"{len(payload) - off}B"
                ))
                raise TransportError("block length exceeds frame")
            blocks.append(payload[off: off + n])
            off += n
            if on_progress is not None:
                if self._on_worker:
                    _safe(on_progress, n)
                else:
                    self._disp.complete(_safe, on_progress, n)
        self._rx_settle(blocks, None)

    def _rx_next_block(self) -> None:  # on-loop
        if self._rx_resp_left < wire._LEN.size:
            self._rx_settle(None, TransportError(
                f"short read response: {self._rx_resp_left}B left "
                f"before next block prefix"
            ))
            raise TransportError("short read response body")
        self._arm_fixed(self._RESP_LEN, wire._LEN.size)

    def _rx_resp_len(self) -> None:  # on-loop
        (n,) = wire._LEN.unpack(bytes(self._rx_store))
        self._rx_resp_left -= wire._LEN.size
        count, listener, _t0, dest, _prog, _total = self._rx_entry
        if n > self._rx_resp_left:
            # without this bound a lying prefix would read INTO the
            # next frame's bytes (or hang waiting for bytes that never
            # come) and size an attacker-controlled allocation
            self._rx_settle(None, TransportError(
                f"block length {n}B exceeds response remainder "
                f"{self._rx_resp_left}B"
            ))
            raise TransportError("block length exceeds frame")
        self._rx_resp_left -= n
        d = dest[self._rx_idx] if self._rx_idx < len(dest) else None
        if d is None:
            store = self._recv_buffer(n)
            block = store
            view = wire._as_view(store)[:n]
        else:
            view = wire._as_view(d)
            if view.nbytes != n:
                # protocol desync: fail this read, then tear the
                # channel down (the threaded path raises out of the
                # reader loop here)
                self._rx_settle(None, TransportError(
                    f"stripe length mismatch: {n}B payload for "
                    f"{view.nbytes}B dest buffer"
                ))
                raise TransportError("stripe length mismatch")
            store, block = d, d
        if n == 0:
            self._rx_block_done(block, 0)
        else:
            self._rx_block = block
            self._arm_into(self._RESP_BLOCK, store, view)

    def _rx_block_done(self, block, n: int) -> None:  # on-loop
        count, _listener, _t0, dest, on_progress, _total = self._rx_entry
        if (dest is None or self._rx_idx >= len(dest)
                or dest[self._rx_idx] is None):
            if isinstance(block, np.ndarray):
                block.flags.writeable = False
        self._rx_blocks.append(block)
        if on_progress is not None:
            if self._on_worker:
                _safe(on_progress, n)
            else:
                self._disp.complete(_safe, on_progress, n)
        self._rx_idx += 1
        if self._rx_idx >= count:
            self._rx_settle(self._rx_blocks, None)
        else:
            self._rx_next_block()

    def _rx_settle(self, blocks, err) -> None:  # on-loop
        """One read response fully received (or failed): queue the
        completion event and re-arm for the next frame header.  On a
        streaming worker the completion delivers INLINE — the worker
        IS completion-executor context (the threaded reader's delivery
        shape), so the loop round trip is skipped."""
        entry, self._rx_entry = self._rx_entry, None
        self._rx_blocks = []
        self._rx_block = None
        _count, listener, t0, _dest, _prog, total = entry
        with self._reads_lock:
            self._rx_outstanding -= total
        if self._on_worker:
            self._deliver(listener, blocks, err, t0)
        else:
            self._disp.complete(self._deliver, listener, blocks, err, t0)
        self._arm_fixed(self._HDR, wire._HDR.size)

    def _deliver(self, listener, blocks, err, t0) -> None:
        """Completion-executor side of one read: RTT covers the whole
        transfer through completion-queue dispatch (comparable with the
        threaded/loopback series)."""
        self._m_read_rtt.observe((time.monotonic() - t0) * 1000.0)
        if err is not None:
            self._fail(listener, err)
        else:
            self._complete(listener, blocks)
        self._release_budget()

    # -- lane streaming (completion-worker recv) ----------------------------
    def _rx_maybe_offload(self, force: bool = False) -> bool:  # on-loop
        """Hand a BUSY lane's whole recv machine to a completion worker
        doing BLOCKING recv (the completion-worker half of the CQ
        split): when at least ``transportStreamOffloadBytes`` of
        response bytes are outstanding on this channel, the socket
        leaves the selector and the worker runs the frame state machine
        with kernel-coalesced blocking reads and INLINE completion
        delivery — the threaded reader's exact syscall-and-delivery
        shape, paid only while the lane is actually busy — until the
        lane goes idle, then ``_offload_done`` hands the fd back to the
        loop.  One handoff per burst, not per body.  Bulk channels
        only, at most ``_OFFLOAD_WORKERS`` lanes at a time — when the
        semaphore is exhausted further lanes land on-loop as usual.

        ``force`` streams regardless of OUR outstanding reads (and
        also covers HOT latency channels) — the burst/conversation
        triggers detected by the pump."""
        if not self._offload_min:
            return False
        # racy read of _outstanding: a stale value only delays the
        # handoff one pump or streams a lane that just went idle (the
        # worker exits after its grace tick) — never corrupts state
        if not force and (
                not self._bulk
                or self._rx_outstanding < self._offload_min):  # noqa: CK03
            return False
        if not self._disp.offload_sem.acquire(blocking=False):
            return False
        self._rx_offloaded = True
        self._on_worker = True
        if self._registered:
            self._disp.sel_unregister(self._sock)
            self._registered = False
        self._m_offloads.inc()
        try:
            self.node.submit(self._stream_recv)
        except BaseException:
            # completion pool gone (teardown): land on-loop after all
            self._disp.offload_sem.release()
            self._rx_offloaded = False
            self._on_worker = False
            self._loop_register()
            return False
        return True

    def _stream_recv(self) -> None:
        """Dedicated recv loop of one streamed lane — runs on a
        completion-pool worker, NOT on the loop (a sleeping per-fd
        reader gets RCVLOWAT-coalesced wakeups where the shared epoll
        pays loop machinery per event, and inline delivery skips the
        loop completion round trip).  The fd stays NON-blocking — the
        worker waits in its own ``select`` — because the send side of
        the same socket keeps running concurrently (see the comment at
        the recv call).  The loop does not touch this channel's recv
        state or the socket until ``_offload_done`` is posted back;
        ``stop``/``_loop_fail`` shutdown() the socket to wake this
        worker, which then owns the final close (fd-reuse safety)."""
        err = None
        # readahead carve buffer: one recv per wakeup pulls everything
        # queued (up to _SCRATCH); headers / prefixes / small frames
        # are carved out of ra[lo:hi] with no further syscalls, and
        # armed targets with ≥ _SCRATCH still to fill recv DIRECTLY
        # into their view (zero copy for the body bulk)
        ra = memoryview(self._rx_scratch)
        lo = hi = 0
        try:
            while err is None and not self._closed:
                state = self._rx_state
                if state == self._DISCARD:
                    if hi > lo:
                        take = min(hi - lo, self._rx_discard)
                        lo += take
                        self._rx_discard -= take
                    else:
                        # ra is free when the spill is empty — reuse it
                        want = min(self._rx_discard, _SCRATCH)
                        try:
                            n = self._sock.recv_into(ra[:want], want)
                        except (BlockingIOError, InterruptedError):
                            try:
                                fd = self._sock.fileno()
                                wl = (
                                    [fd] if self._tx_bytes  # noqa: CK03
                                    and not self._tx_draining  # noqa: CK03
                                    else [])
                                _r, w, _x = select.select(
                                    [fd], wl, [fd], _OFFLOAD_TIMEOUT)
                            except (OSError, ValueError):
                                err = TransportError("socket gone")
                                break
                            if w:
                                err = self._stream_drain_tx()
                                if err is not None:
                                    break
                            continue
                        except OSError as e:
                            err = TransportError(f"recv failed: {e}")
                            break
                        if n == 0:
                            err = TransportError(
                                "connection closed by peer")
                            break
                        self._rx_discard -= n
                    if self._rx_discard == 0:
                        self._arm_fixed(self._HDR, wire._HDR.size)
                    continue
                view = self._rx_view
                want = view.nbytes - self._rx_got
                if hi > lo:
                    take = hi - lo if hi - lo < want else want
                    view[self._rx_got:self._rx_got + take] = \
                        ra[lo:lo + take]
                    lo += take
                    self._rx_got += take
                else:
                    grace = False
                    if state == self._HDR and self._rx_got == 0:
                        # between frames with nothing buffered, nothing
                        # outstanding and nothing being sent: the burst
                        # is probably over — wait one short grace tick
                        # for a follow-on frame (request bursts have
                        # sub-ms gaps), then hand the fd back.  While
                        # the conversation is HOT the lock checks are
                        # skipped entirely — the previous frame just
                        # landed, another is coming
                        if (time.monotonic() - self._last_frame_t
                                >= _HOT_FRAME_S):
                            with self._reads_lock:
                                idle = self._rx_outstanding == 0
                            if idle:
                                with self._tx_lock:
                                    idle = not self._tx_bytes
                            grace = idle
                        # select FIRST at a frame boundary: the lane is
                        # usually between frames here, and probing with
                        # a guaranteed-EAGAIN recv pays a syscall plus
                        # an exception per frame; when bytes are
                        # already queued the select returns immediately.
                        # The watermark MUST drop to the header size
                        # first — select honors RCVLOWAT, and a stale
                        # mid-body watermark would never report a lone
                        # header readable
                        if self._coalesce and self._lowat != want:
                            try:
                                self._sock.setsockopt(
                                    socket.SOL_SOCKET, socket.SO_RCVLOWAT,
                                    want,
                                )
                                self._lowat = want
                            except OSError:
                                self._coalesce = 0
                        try:
                            fd = self._sock.fileno()
                            wl = (
                                [fd] if self._tx_bytes  # noqa: CK03
                                and not self._tx_draining  # noqa: CK03
                                else [])
                            r, w, x = select.select(
                                [fd], wl, [fd],
                                _STREAM_GRACE if grace
                                else _OFFLOAD_TIMEOUT,
                            )
                        except (OSError, ValueError):
                            err = TransportError("socket gone")
                            break
                        if w:
                            err = self._stream_drain_tx()
                            if err is not None:
                                break
                        if not r and not x:
                            if grace and not w:
                                break  # idle through grace: hand back
                            continue  # periodic _closed recheck
                    direct = want >= _SCRATCH
                    if self._coalesce:
                        # wake per ~coalesce bytes mid-body, exact-fill
                        # for headers/tails (RCVLOWAT gates select
                        # readability, so it must never exceed the
                        # bytes the machine still needs)
                        lw = (want if want < self._coalesce
                              else self._coalesce)
                        if lw != self._lowat:
                            try:
                                self._sock.setsockopt(
                                    socket.SOL_SOCKET, socket.SO_RCVLOWAT,
                                    lw,
                                )
                                self._lowat = lw
                            except OSError:
                                self._coalesce = 0
                    # The socket MUST stay non-blocking: settimeout()
                    # would flip the whole fd into Python's timeout
                    # mode and make concurrent inline sendmsg on the
                    # SAME socket (_write_locked under _tx_lock)
                    # wait-then-raise socket.timeout — dropping a
                    # half-sent frame and desyncing the wire.  So the
                    # worker waits in select() and recvs non-blocking:
                    # the RCVLOWAT watermark still coalesces select
                    # wakeups exactly like a blocking reader's.
                    try:
                        if direct:
                            n = self._sock.recv_into(
                                view[self._rx_got:], want)
                        else:
                            n = self._sock.recv_into(ra, _SCRATCH)
                    except (BlockingIOError, InterruptedError):
                        try:
                            fd = self._sock.fileno()
                            wl = (
                                [fd] if self._tx_bytes  # noqa: CK03
                                and not self._tx_draining  # noqa: CK03
                                else [])
                            r, w, x = select.select(
                                [fd], wl, [fd],
                                _STREAM_GRACE if grace
                                else _OFFLOAD_TIMEOUT,
                            )
                        except (OSError, ValueError):
                            err = TransportError("socket gone")
                            break
                        if w:
                            err = self._stream_drain_tx()
                            if err is not None:
                                break
                        if grace and not r and not x and not w:
                            break  # idle through the grace: hand back
                        continue  # data/EOF ready, or periodic recheck
                    except OSError as e:
                        err = TransportError(f"recv failed: {e}")
                        break
                    if n == 0:
                        err = TransportError("connection closed by peer")
                        break
                    if direct:
                        self._rx_got += n
                    else:
                        lo, hi = 0, n
                        continue  # carve on the next iteration
                if self._rx_got < view.nbytes:
                    continue
                try:
                    self._rx_dispatch()
                except TransportError as e:
                    err = e
                    break
                except BaseException as e:
                    logger.exception("recv state machine failed")
                    err = TransportError(f"recv failed: {e}")
                    break
                if self._rx_state == self._HDR:
                    # logical frame completed on the worker: feed the
                    # hot-conversation clock (grace skip above)
                    self._last_frame_t = time.monotonic()
        finally:
            self._disp.offload_sem.release()
        with self._tx_lock:
            closed = self._closed
            if closed:
                # the channel died while we owned the fd — the closer
                # skipped the close (fd-reuse safety); finish it here
                self._close_fd_locked()
        if closed:
            self._stream_fail_entry(err)
            return
        try:
            self._disp.post(self._offload_done, err)
        except TransportError:
            # dispatcher stopped while we owned the fd: nobody will
            # take the machine back — close it here (single-owner flag
            # arbitrates against the stop() fallback)
            self._close_fd()
            self._stream_fail_entry(err)

    def _stream_fail_entry(self, err) -> None:
        """Worker-side cleanup of a read mid-body when the channel died
        under a streamed lane: _loop_fail deferred the entry to us (we
        own the recv machine), and _fail_outstanding no longer covers
        it (it left _reads at RESP_HDR) — fail it exactly once here."""
        entry, self._rx_entry = self._rx_entry, None
        if entry is not None:
            with self._reads_lock:
                self._rx_outstanding -= entry[5]
            self._deliver(
                entry[1], None,
                err if err is not None
                else TransportError("channel stopped"),
                entry[2],
            )

    def _offload_done(self, err) -> None:  # on-loop
        """Streaming worker finished (lane idle) or failed: take the
        recv machine back, re-register the socket and drain whatever
        already queued."""
        self._rx_offloaded = False
        self._on_worker = False
        if self._closed:
            # closed between the worker's post and this running:
            # _loop_fail deferred the mid-body entry while the worker
            # owned the machine — it is ours to fail now
            self._close_fd()
            self._stream_fail_entry(err)
            return
        if err is not None:
            self._loop_fail(err)
            return
        self._loop_register()
        self._rx_pump()  # drain whatever else is already queued
        if self._closed or self._rx_offloaded:
            return
        if self._coalesce:
            self._tune_lowat()
        self._update_interest()

    # -- serving (serve-pool worker thread) ---------------------------------
    def _serve_read_async(self, payload: bytes, t_enq, release) -> None:
        """One-sided READ service, completion-driven: resolve the
        blocks here on the serve worker, post the response descriptor,
        return.  The serve's byte credits are released by the
        send-completion event — not by a worker blocked in sendall —
        so the credit budget still bounds resident serve memory while
        the worker moves on."""
        ctx = None
        if RECORDER.enabled:
            # t_enq → now spans the serve queue AND credit wait
            ctx = wire._req_trace(payload)
            fr_event(
                "transport", "serve_admit",
                trace_id=ctx[0] if ctx else 0,
                span_id=ctx[1] if ctx else 0,
                wait_us=0 if t_enq is None
                else int((time.monotonic() - t_enq) * 1e6),
                bytes=wire._req_cost(payload),
            )
        parts = wire.build_read_response_parts(
            self.node, payload, self.peer
        )
        if parts is None:
            release()
            return
        t0 = time.monotonic()

        def sent(err):
            release()
            if err is not None:
                logger.warning("read response to %s failed", self.peer)
            elif ctx is not None and RECORDER.enabled:
                fr_event(
                    "transport", "serve_send",
                    trace_id=ctx[0], span_id=ctx[1],
                    us=int((time.monotonic() - t0) * 1e6),
                )

        # drain=True: this serve worker finishes the send itself
        # (blocking-sendall shape, no loop round trips) and the credits
        # release right when the last byte reaches the kernel
        self._post_op(
            self._frame_op(wire.OP_READ_RESP, parts, 1, sent), drain=True,
        )

    # -- teardown ------------------------------------------------------------
    def _fail_outstanding(self, err: BaseException) -> None:
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
            self._rx_outstanding = 0
        if reads:
            self._m_fail_outstanding.inc()
        for entry in reads:
            self._fail(entry[1], err)
            self._release_budget()

    def _on_loop_dead(self, err: BaseException) -> None:
        if self.state not in (ChannelState.STOPPED,):
            self._error(err)
        self._fail_outstanding(err)
        # cache/passive/read-group cleanup, exactly like the threaded
        # reader loop's exit path — a dead peer must not pin its cache
        # slots until node teardown
        self.node.on_channel_dead(self)

    def _loop_fail(self, err: BaseException) -> None:  # on-loop
        if self._closed:
            return
        with self._tx_lock:
            if self._closed:
                return
            self._closed = True
            tx, self._tx = list(self._tx), deque()
            if self._tx_bytes:
                self._m_backlog.dec(self._tx_bytes)
            self._tx_bytes = 0
            if self._registered:
                self._disp.sel_unregister(self._sock)
                self._registered = False
            # shutdown wakes a completion worker blocked in an
            # offloaded recv; close INSIDE the lock: an inline sender
            # mid-sendmsg holds it, so the fd can never be reused
            # under a write in flight.  While a worker owns the recv
            # side the close is DEFERRED to it (same fd-reuse safety).
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            if not self._rx_offloaded:
                self._close_fd_locked()
        # a read mid-body when the channel died: its entry already left
        # _reads, so _fail_outstanding no longer covers it — fail it
        # here.  NOT while a streaming worker owns the recv machine
        # (_rx_entry/_rx_outstanding are the machine owner's state):
        # the shutdown above wakes the worker, which either fails the
        # entry itself (channel seen closed) or posts _offload_done,
        # whose _loop_fail re-runs this block with ownership back
        if not self._rx_offloaded:
            entry, self._rx_entry = self._rx_entry, None
            if entry is not None:
                with self._reads_lock:
                    self._rx_outstanding -= entry[5]
                self._disp.complete(self._deliver, entry[1], None, err,
                                    entry[2])
        for op in tx:
            op.tkt.release()  # releases: dispatcher.send_ops
            op._transition("failed")
            if op.on_done is not None:
                self._disp.complete(op.on_done, err)
        self._disp.complete(self._on_loop_dead, err)

    def _loop_close(self) -> None:  # on-loop
        self._loop_fail(TransportError("channel stopped"))

    def loop_close(self, err) -> None:  # on-loop
        """Dispatcher-teardown/handler-failure entry (the generic
        handler close contract shared with Acceptor/_Handshake)."""
        self._loop_fail(err if err is not None
                        else TransportError("channel stopped"))

    def stop(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        err = TransportError("channel stopped")
        with self._reads_lock:
            reads = list(self._reads.values())
            self._reads.clear()
        for entry in reads:
            self._safe_fail(entry[1], err)
        super().stop()
        try:
            self._disp.post(self._loop_close)
        except TransportError:
            # loop already gone: it cannot close the fd for us
            self._fail_tx(err)
            with self._tx_lock:
                self._closed = True
                # a streaming worker may still own the fd: shutdown()
                # above wakes it and IT closes via _close_fd_locked —
                # never close out from under it here
                if not self._rx_offloaded:
                    self._close_fd_locked()

    def reply_channel(self) -> Channel:
        """Replies ride the same socket."""
        return self


__all__ = ["Dispatcher", "Acceptor", "AsyncTcpChannel"]
