"""In-process loopback transport: the test/fallback backend.

The reference has no fake transport (its only backend is real verbs,
SURVEY.md §4) — this backend is the test harness the rebuild adds: a
process-local "network" of Nodes where

- ``send_rpc`` delivers frames to the peer node's receive dispatcher on
  the peer's dispatcher pool (async, like SEND/RECV + CQ thread), and
- ``read_blocks`` pulls bytes straight out of the peer node's registered
  block stores with *no peer-side handler involved* — faithfully modeling
  the one-sided RDMA READ data plane (the "remote CPU never serves
  reads" property, SURVEY.md §2 backend notes).

Failure injection: ``partition(addr)`` refuses new connects and kills
in-flight ops to that address; ``Channel.inject_error()`` flips a single
channel to sticky ERROR, failing its outstanding ops — exercising the
same failure semantics the reference gets from CQ error completions.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter, histogram
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.node import Address, Node
from sparkrdma_tpu.utils import wiredbg
from sparkrdma_tpu.utils.dbglock import dbg_lock

_PAIRED = {
    ChannelType.RPC_REQUESTOR: ChannelType.RPC_RESPONDER,
    ChannelType.RPC_WRAPPER: ChannelType.RPC_WRAPPER,
    ChannelType.READ_REQUESTOR: ChannelType.READ_RESPONDER,
}


def _land(block, d):
    """Copy one served block into its registered dest buffer (the
    recv_into analog — bit-exact with TcpChannel's scatter path)."""
    arr = d if isinstance(d, np.ndarray) else np.frombuffer(d, np.uint8)
    src = (
        block if isinstance(block, np.ndarray)
        else np.frombuffer(memoryview(block), np.uint8)
    )
    if src.shape[0] != arr.shape[0]:
        raise TransportError(
            f"stripe length mismatch: {src.shape[0]}B payload for "
            f"{arr.shape[0]}B dest buffer"
        )
    arr[:] = src
    return d


class LoopbackChannel(Channel):
    """One direction of an in-process channel pair.

    RPC channels implement receiver-credit software flow control when
    the node's conf enables it (reference: sender consumes one credit
    per SEND, receiver piggybacks credit reports once half the recv
    queue is consumed, RdmaChannel.java:56-59,508-520,690-703)."""

    supports_scatter = True

    def __init__(
        self,
        channel_type: ChannelType,
        local: Node,
        remote: Node,
        network: "LoopbackNetwork",
        send_queue_depth: int,
    ):
        super().__init__(channel_type, send_queue_depth)
        self.local = local
        self.remote = remote
        self.network = network
        self.peer_channel: Optional["LoopbackChannel"] = None
        conf = local.conf
        self._fc_enabled = conf.sw_flow_control and channel_type in (
            ChannelType.RPC_REQUESTOR, ChannelType.RPC_RESPONDER,
            ChannelType.RPC_WRAPPER,
        )
        self._credits = conf.recv_queue_depth  # guarded-by: _credit_lock
        self._credit_lock = dbg_lock("loopback.credits", 66)
        # (frames, listener) blocked on credits
        self._credit_waiting: List = []  # guarded-by: _credit_lock
        self._consumed_since_report = 0  # guarded-by: _credit_lock
        self._report_threshold = max(1, conf.recv_queue_depth // 2)
        self._m_bytes_sent = counter(
            "transport_bytes_sent_total", transport="loopback")
        self._m_bytes_recv = counter(
            "transport_bytes_received_total", transport="loopback")
        self._m_msgs_sent = counter(
            "transport_msgs_sent_total", transport="loopback")
        self._m_read_rtt = histogram(
            "transport_read_rtt_ms", transport="loopback")

    # -- credit machinery (transport-internal, like WRITE_WITH_IMM) ---------
    def _on_credit_report(self, n: int) -> None:
        """Credits became available (peer report or failed-delivery
        restore); drain blocked sends.  Pop-and-take happens atomically
        under _credit_lock so a report racing a failed take can never
        strand a queued send; failed deliveries restore their credits and
        loop so later queued sends still fail promptly."""
        while True:
            drained = []
            with self._credit_lock:
                self._credits += n
                n = 0
                while (
                    self._credit_waiting
                    and self._credits >= len(self._credit_waiting[0][0])
                ):
                    frames, listener = self._credit_waiting.pop(0)
                    self._credits -= len(frames)
                    drained.append((frames, listener))
            if not drained:
                return
            restore = 0
            for frames, listener in drained:
                if not self._deliver_frames(frames, listener):
                    restore += len(frames)
            if restore == 0:
                return
            n = restore

    def _frame_consumed(self) -> None:
        """Receiver side: one recv slot freed after dispatch; report
        credits back in batches."""
        with self._credit_lock:
            self._consumed_since_report += 1
            if self._consumed_since_report < self._report_threshold:
                return
            n, self._consumed_since_report = self._consumed_since_report, 0
        peer = self.peer_channel
        if peer is not None:
            peer._on_credit_report(n)

    # -- posting ------------------------------------------------------------
    def _post_rpc(self, frames: List[bytes], listener: CompletionListener) -> None:
        def deliver():
            # fail fast BEFORE consuming credits: a dead channel must not
            # burn credits it can never get reported back
            err = self._check_deliverable()
            if err is None and FAULTS.enabled:
                try:
                    FAULTS.check("send")
                except TransportError as e:
                    err = e
            if err is not None:
                self._error(err)
                self._fail(listener, err)
                self._release_budget()
                return
            if self._fc_enabled:
                with self._credit_lock:
                    if self._credits >= len(frames):
                        self._credits -= len(frames)
                    else:
                        self._credit_waiting.append((frames, listener))
                        return  # budget held until credits arrive
            if not self._deliver_frames(frames, listener) and self._fc_enabled:
                self._on_credit_report(len(frames))  # restore + re-drain

        self.local.submit(deliver)

    def _check_deliverable(self) -> Optional[TransportError]:
        if self.network.is_partitioned(self.local.address, self.remote.address):
            return TransportError(f"network partition to {self.remote.address}")
        if self.state != ChannelState.CONNECTED:
            return TransportError("channel not connected")
        return None

    def _deliver_frames(
        self, frames: List[bytes], listener: CompletionListener
    ) -> bool:
        """Returns True when the frames were handed to the peer; on False
        the listener has been failed and (for flow-controlled channels)
        the caller must restore the consumed credits."""
        try:
            err = self._check_deliverable()
            if err is not None:
                raise err
            target = self.peer_channel if self.peer_channel is not None else self
            for frame in frames:
                data = bytes(frame)
                if (wiredbg.wire_debug_enabled()
                        and not wiredbg.rpc_frame_ok("loopback", data)):
                    # loopback has no byte framing, so this is the
                    # engine's whole validator: the rejected frame is
                    # dropped (counted + logged) but still frees its
                    # recv slot — the credit must flow back or the
                    # sender leaks it
                    target._frame_consumed()
                    continue
                self.remote.dispatch_frame(
                    target, data, on_consumed=target._frame_consumed
                )
        except BaseException as e:
            self._error(e)
            self._fail(listener, e)
            self._release_budget()
            return False
        else:
            self._m_msgs_sent.inc(len(frames))
            self._m_bytes_sent.inc(sum(len(f) for f in frames))
            self._complete(listener, None)
            self._release_budget()
            return True

    def _post_read(self, locations, listener: CompletionListener,
                   dest=None, on_progress=None, ctx=None) -> None:
        # clock starts at POST time (like TcpChannel stamping t0 in
        # _post_read): the serve-queue wait is part of the RTT, so the
        # tcp/loopback series stay comparable under load
        t0 = time.monotonic()

        def fail(e: BaseException) -> None:
            self._error(e)
            self._fail(listener, e)
            self._release_budget()

        def land(data) -> None:
            # receiver-side completion: the landing copy, progress and
            # completion callbacks run INSIDE the serve (still under
            # its byte credits), so a slow receiver back-pressures the
            # responder exactly like TcpChannel's credit-held sendmsg
            try:
                if dest is not None:
                    # striped-reassembly parity with TcpChannel: each
                    # payload lands in its registered dest buffer and
                    # the dest object IS the completed block
                    data = [
                        _land(data[i], dest[i])
                        if i < len(dest) and dest[i] is not None
                        else data[i]
                        for i in range(len(data))
                    ]
                if on_progress is not None:
                    for b in data:
                        try:
                            on_progress(len(b))
                        except BaseException:
                            pass
            except BaseException as e:
                fail(e)
            else:
                self._m_read_rtt.observe((time.monotonic() - t0) * 1000.0)
                self._m_bytes_recv.inc(sum(len(b) for b in data))
                self._complete(listener, data)
                self._release_budget()

        def serve() -> None:
            # responder side: resolve the blocks from registered memory
            # on the REMOTE node's bounded serve pool — off this node's
            # dispatcher (a multi-MB loopback read must not head-of-
            # line-block control frames), under the same byte-credit
            # flow control the TCP read service carries (PR 3 parity;
            # the serve holds its block views only while it owns
            # credits)
            try:
                if self.network.is_partitioned(
                    self.local.address, self.remote.address
                ):
                    raise TransportError(
                        f"network partition to {self.remote.address}"
                    )
                if self.state != ChannelState.CONNECTED:
                    raise TransportError("channel not connected")
                if FAULTS.enabled:
                    FAULTS.check("serve_delay")
                    FAULTS.check("serve")
                    # loopback has no response frame to cut, so the
                    # read_resp point fires here on the reply boundary
                    FAULTS.check("read_resp")
                ts = time.monotonic()
                data = self.remote.read_local_blocks(locations)
                if ctx is not None and RECORDER.enabled:
                    # in-process serve: the trace context needs no wire
                    # tail — the closure carries it to the serve side
                    fr_event(
                        "transport", "serve_read",
                        trace_id=ctx[0], span_id=ctx[1],
                        blocks=len(locations),
                        us=int((time.monotonic() - ts) * 1e6),
                    )
            except BaseException as e:
                fail(e)
                return
            land(data)

        try:
            self.remote.submit_serve(
                serve, (), cost=sum(loc.length for loc in locations),
                mkey=locations[0].mkey if locations else None,
            )
        except BaseException as e:
            # remote node stopped (serve pool refused): fail fast like
            # a read against a dead peer, asynchronously so post-read
            # keeps its completion-callback contract
            try:
                self.local.submit(fail, e)
            except BaseException:
                fail(e)

    def _error(self, err: BaseException) -> None:
        # ERROR is sticky (the channel is dead for good): run the same
        # cache/passive/read-group cleanup the TCP engines run on their
        # teardown paths, so a partitioned/stopped loopback peer does
        # not pin cache slots until node teardown (idempotent; no-op
        # while the owning node is itself stopping)
        super()._error(err)
        self.local.on_channel_dead(self)

    def stop(self) -> None:
        # credit-waiting listeners are tracked in _outstanding, which
        # super().stop() fails exactly once — just drop the queue
        with self._credit_lock:
            self._credit_waiting.clear()
        super().stop()

    # -- failure injection --------------------------------------------------
    def inject_error(self) -> None:
        self._error(TransportError("injected channel error"))
        err = TransportError("injected channel error")
        with self._outstanding_lock:
            outstanding = list(self._outstanding)
            self._outstanding.clear()
        for l in outstanding:
            self._safe_fail(l, err)

    def reply_channel(self) -> Channel:
        """Channel on which the receiver of a frame answers.  Frames are
        dispatched tagged with the receiver-owned reverse channel, so the
        reply path is this very channel."""
        return self


class LoopbackNetwork:
    """Registry of in-process nodes + connector, with failure injection."""

    def __init__(self):
        self._nodes: Dict[Address, Node] = {}  # guarded-by: _lock
        self._lock = dbg_lock("loopback.network", 56)
        # frozenset({a, b}) pairs or single addr
        self._partitioned: set = set()  # guarded-by: _lock

    # -- membership ---------------------------------------------------------
    def register(self, node: Node) -> None:
        with self._lock:
            if node.address in self._nodes:
                raise TransportError(f"address already bound: {node.address}")
            self._nodes[node.address] = node

    def unregister(self, node: Node) -> None:
        with self._lock:
            self._nodes.pop(node.address, None)

    def lookup(self, address: Address) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(address)

    # -- failure injection --------------------------------------------------
    def partition(self, address: Address) -> None:
        """Cut an endpoint off (executor loss)."""
        with self._lock:
            self._partitioned.add(address)

    def heal(self, address: Address) -> None:
        with self._lock:
            self._partitioned.discard(address)

    def is_partitioned(self, a: Address, b: Address) -> bool:
        with self._lock:
            return a in self._partitioned or b in self._partitioned

    # -- connector (passed to Node.get_channel) -----------------------------
    def connect(
        self, src: Node, peer: Address, channel_type: ChannelType
    ) -> Channel:
        """CM-handshake analog: create the channel pair, register the
        passive side with the acceptor (RdmaNode CM listener accepting
        CONNECT_REQUEST, RdmaNode.java:114-214)."""
        counter(
            "transport_connect_attempts_total", transport="loopback"
        ).inc()
        if FAULTS.enabled:
            FAULTS.check("connect")
        dst = self.lookup(peer)
        if dst is None:
            counter(
                "transport_connect_failures_total", transport="loopback"
            ).inc()
            raise TransportError(f"connection refused: no node at {peer}")
        if self.is_partitioned(src.address, peer):
            counter(
                "transport_connect_failures_total", transport="loopback"
            ).inc()
            raise TransportError(f"network partition to {peer}")
        depth = src.conf.send_queue_depth
        fwd = LoopbackChannel(channel_type, src, dst, self, depth)
        back_type = _PAIRED.get(channel_type, channel_type)
        bwd = LoopbackChannel(back_type, dst, src, self, depth)
        fwd.peer_channel = bwd
        bwd.peer_channel = fwd
        fwd._set_state(ChannelState.CONNECTED)
        bwd._set_state(ChannelState.CONNECTED)
        dst.register_passive_channel(bwd)
        return fwd
