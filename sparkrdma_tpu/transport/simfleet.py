"""Dry-run peer fleet: N wire-protocol peers on ONE selector thread.

Scale tests and the fabric-scale bench need hundreds of fetchable peers
without paying hundreds of real :class:`~sparkrdma_tpu.transport.node.Node`
instances (each with its own dispatcher loop and pools — the very cost
the bounded fabric exists to avoid paying per peer).  A
:class:`SimPeerFleet` listens on ``n_peers`` consecutive ports and
speaks just enough of the TCP wire protocol (transport/tcp.py framing)
to serve the fetch path:

- the 9-byte connect hello is acked (any channel type),
- ``OP_READ_REQ`` frames are answered with ``OP_READ_RESP`` served
  from one shared pattern buffer (``BlockLocation.address`` indexes
  into it; ``mkey`` is ignored), so striped sub-range reads reassemble
  bit-exactly,
- ``OP_RPC`` frames are swallowed.

Everything — all listeners and every accepted connection — runs on a
single daemon thread with non-blocking sockets, so a 256-peer fleet
costs one thread plus its sockets.  The node under test connects to
``fleet.addresses[i]`` through the REAL engines (threaded or async);
only the far side is simulated.
"""

from __future__ import annotations

import logging
import selectors
import socket
import threading
import time
from typing import List, Tuple

from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport import tcp as wire
from sparkrdma_tpu.utils.statemachine import StateMachine

logger = logging.getLogger(__name__)

_MAX_RX = 1 << 20


class _Conn:
    __slots__ = ("sock", "rx", "tx", "hello_done")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rx = bytearray()
        self.tx = bytearray()
        self.hello_done = False


class SimPeerFleet:
    """``n_peers`` fake wire-protocol peers on one selector thread."""

    def __init__(self, n_peers: int, base_port: int, pattern,
                 host: str = "127.0.0.1"):
        self._pattern = memoryview(pattern).cast("B")
        self.addresses: List[Tuple[str, int]] = []
        self._sel = selectors.DefaultSelector()
        self._listeners: List[socket.socket] = []
        self._conns: dict = {}
        self._stop = threading.Event()
        # wake pipe so stop() interrupts a parked select
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        for i in range(n_peers):
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                srv.bind((host, base_port + i))
                srv.listen(64)
            except OSError:
                srv.close()
                self.close()
                raise
            srv.setblocking(False)
            self._sel.register(srv, selectors.EVENT_READ, "accept")
            self._listeners.append(srv)
            self.addresses.append((host, base_port + i))
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="simfleet",
        )
        self._thread.start()

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        for srv in self._listeners:
            try:
                srv.close()
            except OSError:
                pass
        for conn in list(self._conns.values()):
            try:
                conn.sock.close()
            except OSError:
                pass
        self._conns.clear()
        try:
            self._sel.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()

    # -- event loop ----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            for key, events in self._sel.select(timeout=1.0):
                if self._stop.is_set():
                    return
                if key.data == "wake":
                    return
                if key.data == "accept":
                    self._accept(key.fileobj)
                    continue
                conn = key.data
                try:
                    if events & selectors.EVENT_READ:
                        self._readable(conn)
                    if (conn.sock in self._conns
                            and events & selectors.EVENT_WRITE):
                        self._flush(conn)
                except Exception:
                    logger.exception("simfleet connection failed")
                    self._drop(conn)

    def _accept(self, srv) -> None:
        try:
            sock, _addr = srv.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drop(self, conn: _Conn) -> None:
        if conn.sock not in self._conns:
            return
        del self._conns[conn.sock]
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not chunk:
            self._drop(conn)
            return
        conn.rx += chunk
        self._process(conn)

    def _process(self, conn: _Conn) -> None:
        rx = conn.rx
        if not conn.hello_done:
            if len(rx) < wire._HELLO.size:
                return
            magic, _ct, _port, version = wire._HELLO.unpack_from(rx, 0)
            del rx[:wire._HELLO.size]
            if magic != wire._MAGIC:
                self._drop(conn)
                return
            if not (wire.MIN_WIRE_VERSION <= version <= wire.WIRE_VERSION):
                # same structured rejection real acceptors send: the
                # dialing engine surfaces both versions in its error
                self._send(conn, b"\x00" + wire._HELLO_REJ.pack(
                    wire.WIRE_VERSION, version))
                self._drop(conn)
                return
            conn.hello_done = True
            self._send(conn, b"\x01")
        while len(rx) >= wire._HDR.size:
            opcode, length = wire._HDR.unpack_from(rx, 0)
            if len(rx) < wire._HDR.size + length:
                if len(rx) > _MAX_RX + wire._HDR.size + length:
                    self._drop(conn)
                return
            payload = bytes(rx[wire._HDR.size:wire._HDR.size + length])
            del rx[:wire._HDR.size + length]
            if opcode == wire.OP_READ_REQ:
                self._serve_read(conn, payload)
            # OP_RPC frames are swallowed: the fleet has no control plane

    def _serve_read(self, conn: _Conn, payload: bytes) -> None:
        req_id, count = wire._REQ_HDR.unpack_from(payload, 0)
        if RECORDER.enabled:
            # the requester's trace context rides the request's v2
            # tail — the fleet's serve events join its trace exactly
            # like a real peer's would
            ctx = wire._req_trace(payload)
            t0 = time.monotonic()
        parts = [wire._RESP_HDR.pack(req_id, 0)]
        off = wire._REQ_HDR.size
        try:
            for _ in range(count):
                addr, length, _mkey = wire._LOC.unpack_from(payload, off)
                off += wire._LOC.size
                if addr < 0 or addr + length > self._pattern.nbytes:
                    raise ValueError(
                        f"read [{addr},{addr + length}) outside the "
                        f"{self._pattern.nbytes}B pattern"
                    )
                parts.append(wire._LEN.pack(length))
                parts.append(self._pattern[addr:addr + length])
        except Exception as e:
            parts = [
                wire._RESP_HDR.pack(req_id, 1),
                str(e).encode("utf-8", "replace"),
            ]
        body = b"".join(bytes(p) for p in parts)
        if RECORDER.enabled:
            fr_event(
                "transport", "serve_read",
                trace_id=ctx[0] if ctx else 0,
                span_id=ctx[1] if ctx else 0,
                blocks=count,
                us=int((time.monotonic() - t0) * 1e6),
            )
        self._send(
            conn, wire._HDR.pack(wire.OP_READ_RESP, len(body)) + body
        )

    def _send(self, conn: _Conn, data: bytes) -> None:
        conn.tx += data
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        while conn.tx:
            try:
                n = conn.sock.send(conn.tx)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop(conn)
                return
            del conn.tx[:n]
        events = selectors.EVENT_READ
        if conn.tx:
            events |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass


def _fleet_proc_main(n_peers, base_port, pattern, dump_path, host,
                     ready, stop) -> None:
    """Entry point of the spawned fleet process: serve until ``stop``,
    then leave a flight-recorder dump at ``dump_path`` so the parent
    can merge this process's serve spans with its own trace
    (obs/collect.py)."""
    RECORDER.retain()
    try:
        fleet = SimPeerFleet(n_peers, base_port, pattern, host=host)
    except OSError as e:
        ready.put(("err", str(e)))
        return
    ready.put(("ok", fleet.addresses))
    stop.wait()
    fleet.close()
    if dump_path:
        RECORDER.dump("fleet_stop", path=dump_path)
    RECORDER.release()


class SimPeerFleetProc:
    """A :class:`SimPeerFleet` in its OWN process (multiprocessing
    spawn — the module chain stays jax-free, so spawn is cheap).

    The point is cross-process observability: the child retains the
    flight recorder, its ``serve_read`` events carry the requester's
    trace context off the wire, and ``close()`` leaves a dump at
    ``dump_path`` for the parent to merge — a 2-process run then
    yields ONE trace spanning requester and server spans."""

    def __init__(self, n_peers: int, base_port: int, pattern,
                 dump_path: str = "", host: str = "127.0.0.1",
                 start_timeout: float = 30.0):
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        self._stop = ctx.Event()
        ready = ctx.Queue()
        self.dump_path = dump_path
        self._proc = ctx.Process(
            target=_fleet_proc_main,
            args=(n_peers, base_port, bytes(pattern), dump_path, host,
                  ready, self._stop),
            daemon=True,
        )
        self._proc.start()
        try:
            status, detail = ready.get(timeout=start_timeout)
        except Exception:
            self._proc.terminate()
            raise RuntimeError("simfleet subprocess did not come up")
        if status != "ok":
            self._proc.join(timeout=5)
            raise OSError(f"simfleet subprocess bind failed: {detail}")
        self.addresses: List[Tuple[str, int]] = detail

    def close(self) -> None:
        self._stop.set()
        self._proc.join(timeout=15)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)


# ---------------------------------------------------------------------------
# ProcessCluster: driver + N executor TpuShuffleManager PROCESSES
# ---------------------------------------------------------------------------
#
# Where SimPeerFleet fakes the far side of the wire, ProcessCluster is
# the real thing: every executor is a full TpuShuffleManager in its own
# spawned interpreter with its own TcpNetwork, decode pool, and serve
# threads — processes sidestep the GIL, so the overlap planes finally
# run concurrently on multi-core hosts.  The parent holds the driver
# manager; each child gets the driver's BOUND port written into its
# conf (bound-port broadcast), says hello over real sockets, and then
# serves a small picklable command protocol over a duplex pipe:
#
#   register   declarative shuffle spec (partitioner/aggregator KINDS,
#              not objects — Aggregator holds lambdas and can't pickle)
#   write      explicit records, or a named deterministic generator so
#              benchmark data is made in-child and never rides the pipe
#   read       records back, or an order-independent digest (count /
#              sum / xor of per-record CRCs via the native crc kernel)
#   metrics    registry snapshot + process census (cpu, fds, threads)
#   stop       manager.stop() — writes metrics JSON + flight-recorder
#              dump (conf paths), then the child exits
#
# Lifecycle: start → ready barrier (pipe acks AND driver.executors
# census) → commands → stop/kill → collect() merges per-process
# flight-recorder dumps through obs/collect.merge_dumps.

_PORT_SPACING = 40  # > portMaxRetries so per-child bind hunts don't collide


def _process_census() -> dict:
    """CPU/fd/thread census of THIS process (parent and children both
    report through it, so bench_cluster can sum a fleet)."""
    import os

    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = -1
    t = os.times()
    return {
        "pid": os.getpid(),
        "cpu_user_s": t.user,
        "cpu_sys_s": t.system,
        "fds": fds,
        "threads": threading.active_count(),
    }


def _build_partitioner(spec):
    """('hash', n) | ('range', n, sample) → a Partitioner, in-child."""
    from sparkrdma_tpu.shuffle.partitioner import (
        HashPartitioner,
        RangePartitioner,
    )

    kind = spec[0]
    if kind == "hash":
        return HashPartitioner(int(spec[1]))
    if kind == "range":
        return RangePartitioner(int(spec[1]), list(spec[2]))
    raise ValueError(f"unknown partitioner spec {spec!r}")


def _build_aggregator(kind):
    """None | 'group' | 'sum' | 'min' | 'max' → an Aggregator, in-child
    (lambdas live here; only the KIND crosses the pipe)."""
    if not kind:
        return None
    from sparkrdma_tpu.shuffle.manager import ColumnarAggregator

    if kind == "group":
        return ColumnarAggregator.group()
    return ColumnarAggregator.reduce(kind)


def _gen_records(gen: dict, map_id: int):
    """Named deterministic record generators — data is born in the
    executor process so benchmark payloads never cross the pipe."""
    import random

    kind = gen["kind"]
    n = int(gen.get("records", 1000))
    seed = int(gen.get("seed", 0x5eed)) + map_id * 7919
    rng = random.Random(seed)
    if kind == "terasort":
        vlen = int(gen.get("value_len", 90))
        return [
            (rng.getrandbits(80).to_bytes(10, "big"),
             bytes([(seed + i) & 0xFF]) * vlen)
            for i in range(n)
        ]
    if kind == "wordcount":
        vocab = [f"word{j:04d}" for j in range(int(gen.get("vocab", 97)))]
        return [(vocab[rng.randrange(len(vocab))], 1) for _ in range(n)]
    raise ValueError(f"unknown generator {kind!r}")


def records_digest(records) -> dict:
    """Order-independent digest of a record set: per-record pickle
    CRCs combined by count/sum/xor, so two readers agree no matter the
    arrival order.  The CRC batch rides the native ``crc32_spans``
    kernel when built, with the zlib loop as the pure-Python path."""
    import pickle
    import zlib

    import numpy as np

    from sparkrdma_tpu.memory.staging import native_crc32_spans

    parts = [pickle.dumps(r, 4) for r in records]
    crcs = None
    if parts:
        # span table built as an int64 array (not tuple pairs): the
        # native call then starts without a list→ndarray conversion
        lens = np.fromiter((len(p) for p in parts), np.int64, len(parts))
        spans = np.empty((len(parts), 2), np.int64)
        np.cumsum(lens, out=spans[:, 1])
        np.subtract(spans[:, 1], lens, out=spans[:, 0])
        crcs = native_crc32_spans(bytearray().join(parts), spans)
    if crcs is None:
        crcs = [zlib.crc32(p) for p in parts]
    acc_sum = 0
    acc_xor = 0
    for c in crcs:
        acc_sum = (acc_sum + int(c)) & 0xFFFFFFFFFFFFFFFF
        acc_xor ^= int(c)
    return {"count": len(parts), "sum": acc_sum, "xor": acc_xor}


def _cmd_register(mgr, handles, *, shuffle_id, num_maps, partitioner,
                  aggregator=None, map_side_combine=False,
                  key_ordering=False):
    handles[shuffle_id] = mgr.register_shuffle(
        int(shuffle_id), int(num_maps), _build_partitioner(partitioner),
        _build_aggregator(aggregator), map_side_combine=map_side_combine,
        key_ordering=key_ordering,
    )
    return {"shuffle_id": shuffle_id}


def _cmd_write(mgr, handles, *, shuffle_id, map_id, records=None,
               gen=None):
    if records is None:
        records = _gen_records(gen, int(map_id))
    writer = mgr.get_writer(handles[shuffle_id], int(map_id))
    writer.write(iter(records))
    writer.stop(True)
    return {"map_id": map_id, "records": len(records)}


def _cmd_read(mgr, handles, *, shuffle_id, start, end, maps_by_host,
              digest=False):
    reader = mgr.get_reader(
        handles[shuffle_id], int(start), int(end), maps_by_host,
    )
    records = list(reader.read())
    out = {"records": len(records)}
    if digest:
        out["digest"] = records_digest(records)
    else:
        out["data"] = records
    return out


def _cmd_metrics(mgr, handles):
    from sparkrdma_tpu.metrics import get_registry

    reg = get_registry()
    return {
        "executor_id": mgr.executor_id,
        "census": _process_census(),
        "metrics": reg.snapshot() if reg.enabled else {},
    }


_EXEC_COMMANDS = {
    "register": _cmd_register,
    "write": _cmd_write,
    "read": _cmd_read,
    "metrics": _cmd_metrics,
}


def _executor_proc_main(idx, conf_map, host, port_base, log_path,
                        conn) -> None:
    """Spawned executor entry: build a full TpuShuffleManager (its
    __init__ says hello to the driver over the real socket), ack
    readiness on the pipe, then serve commands until stop/EOF."""
    if log_path:
        logging.basicConfig(
            filename=log_path, level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.transport.tcp import TcpNetwork

    try:
        mgr = TpuShuffleManager(
            TpuShuffleConf(conf_map), is_driver=False,
            network=TcpNetwork(), host=host, port=port_base,
            executor_id=str(idx), stage_to_device=False,
        )
    except Exception as e:  # bind/hello failure → structured nack
        try:
            conn.send(("err", type(e).__name__, str(e), ""))
        except OSError:
            pass
        return
    import os

    conn.send(("ready", {
        "pid": os.getpid(),
        "smid": mgr.local_smid,
        "address": mgr.node.address,
    }))
    handles: dict = {}
    try:
        while True:
            try:
                cmd, kwargs = conn.recv()
            except (EOFError, OSError):
                break  # parent died — fall through to manager teardown
            if cmd == "stop":
                break
            fn = _EXEC_COMMANDS.get(cmd)
            try:
                if fn is None:
                    raise ValueError(f"unknown cluster command {cmd!r}")
                result = fn(mgr, handles, **kwargs)
                conn.send(("ok", result))
            except Exception as e:
                import traceback

                try:
                    conn.send(("err", type(e).__name__, str(e),
                               traceback.format_exc()))
                except OSError:
                    break
    finally:
        # stop() writes the metrics JSON and flight-recorder dump the
        # parent's collect() merges (conf metricsJsonPath /
        # flightRecorderDumpPath, both suffixed/tagged per process)
        try:
            mgr.stop()
        except Exception:
            logger.exception("executor %s stop failed", idx)
        try:
            conn.send(("ok", {"stopped": True}))
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass


class ExecutorDiedError(RuntimeError):
    """The executor process went away mid-command (crash/kill)."""


class ExecutorCommandError(RuntimeError):
    """A command raised in the executor; carries the remote type name."""

    def __init__(self, kind: str, message: str, tb: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.remote_traceback = tb


class ExecutorProcess:
    """One spawned executor: process + command pipe.  ``send``/``recv``
    are split so callers can overlap commands across the fleet (and so
    the crash test can park a read while killing a sibling)."""

    def __init__(self, idx: int, conf_map: dict, host: str,
                 port_base: int, log_path: str = ""):
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        self.idx = idx
        self.log_path = log_path
        self.info: dict = {}
        self._conn, child_conn = ctx.Pipe()
        self._proc = ctx.Process(
            target=_executor_proc_main,
            args=(idx, conf_map, host, port_base, log_path, child_conn),
            daemon=True, name=f"cluster-exec-{idx}",
        )
        self._proc.start()
        child_conn.close()

    @property
    def alive(self) -> bool:
        return self._proc.is_alive()

    @property
    def pid(self):
        return self._proc.pid

    def wait_ready(self, timeout: float) -> dict:
        if not self._conn.poll(timeout):
            raise ExecutorDiedError(
                f"executor {self.idx}: not ready within {timeout:.0f}s"
            )
        try:
            msg = self._conn.recv()
        except (EOFError, OSError) as e:
            raise ExecutorDiedError(
                f"executor {self.idx}: died during startup ({e})"
            ) from e
        if msg[0] != "ready":
            raise ExecutorDiedError(
                f"executor {self.idx} failed to start: {msg[1:]}"
            )
        self.info = msg[1]
        return self.info

    def send(self, cmd: str, **kwargs) -> None:
        try:
            self._conn.send((cmd, kwargs))
        except (OSError, BrokenPipeError) as e:
            raise ExecutorDiedError(
                f"executor {self.idx}: pipe closed ({e})"
            ) from e

    def recv(self, timeout: float = 120.0):
        try:
            if not self._conn.poll(timeout):
                raise TimeoutError(
                    f"executor {self.idx}: no reply within {timeout:.0f}s"
                )
            msg = self._conn.recv()
        except (EOFError, OSError) as e:
            raise ExecutorDiedError(
                f"executor {self.idx}: died mid-command ({e})"
            ) from e
        if msg[0] == "ok":
            return msg[1]
        raise ExecutorCommandError(msg[1], msg[2],
                                   msg[3] if len(msg) > 3 else "")

    def call(self, cmd: str, timeout: float = 120.0, **kwargs):
        self.send(cmd, **kwargs)
        return self.recv(timeout)

    def kill(self) -> None:
        """SIGKILL — the crash-mid-stage path.  No goodbye, no dump."""
        if self._proc.is_alive():
            self._proc.kill()
        self._proc.join(timeout=10)

    def stop(self, timeout: float = 30.0) -> bool:
        """Graceful stop; True when the child acked its teardown."""
        acked = False
        try:
            self.send("stop")
            acked = bool(self.recv(timeout))
        except (ExecutorDiedError, ExecutorCommandError, TimeoutError):
            pass
        self._proc.join(timeout=timeout)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)
        try:
            self._conn.close()
        except OSError:
            pass
        return acked


class ProcessCluster(StateMachine):
    """Driver in THIS process + ``n_executors`` full shuffle-manager
    processes over real TCP sockets.

    Keep ``base_port`` below the kernel ephemeral range (use 2xxxx
    bases); the driver binds at ``base_port`` (with the manager's own
    retry hunt), each executor at ``base_port + 100 + idx * 40``.
    ``workdir`` receives per-process logs, metrics JSONs, and
    flight-recorder dumps; ``collect()`` folds the dumps into one
    merged trace document via obs/collect.merge_dumps."""

    MACHINE = "cluster.proc"
    STATES = ("running", "stopping", "stopped")
    INITIAL = "running"
    TERMINAL = ("stopped",)
    TRANSITIONS = {
        "running": ("stopping",),
        "stopping": ("stopped",),
    }

    def __init__(self, n_executors: int, base_port: int,
                 conf: dict = None, host: str = "127.0.0.1",
                 workdir: str = "", start_timeout: float = 180.0):
        import os
        import tempfile

        from sparkrdma_tpu.conf import TpuShuffleConf
        from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
        from sparkrdma_tpu.transport.tcp import TcpNetwork

        self.n_executors = n_executors
        self.host = host
        self._own_workdir = not workdir
        self.workdir = workdir or tempfile.mkdtemp(prefix="tpucluster-")
        os.makedirs(self.workdir, exist_ok=True)
        base = dict(conf or {})
        pfx = TpuShuffleConf.PREFIX
        base.setdefault(pfx + "metricsJsonPath",
                        os.path.join(self.workdir, "metrics.json"))
        base.setdefault(pfx + "flightRecorderDumpPath", self.workdir)
        self.driver = TpuShuffleManager(
            TpuShuffleConf(dict(base)), is_driver=True,
            network=TcpNetwork(), host=host, port=base_port,
            stage_to_device=False,
        )
        self.executors: List[ExecutorProcess] = []
        self._state = "running"  # state: cluster.proc
        try:
            # bound-port broadcast: children dial the port the driver
            # ACTUALLY bound, not the one we asked for
            child_base = dict(base)
            child_base[pfx + "driverHost"] = host
            child_base[pfx + "driverPort"] = self.driver.node.address[1]
            for i in range(n_executors):
                self.executors.append(ExecutorProcess(
                    i, dict(child_base), host,
                    base_port + 100 + i * _PORT_SPACING,
                    log_path=os.path.join(self.workdir, f"executor-{i}.log"),
                ))
            deadline = time.monotonic() + start_timeout
            for ex in self.executors:
                ex.wait_ready(max(1.0, deadline - time.monotonic()))
            # second half of the barrier: the driver's own census —
            # every hello landed, so maps_by_host routing is live
            while len(self.driver.executors) < n_executors:
                if time.monotonic() > deadline:
                    raise ExecutorDiedError(
                        f"driver saw {len(self.driver.executors)}/"
                        f"{n_executors} hellos within {start_timeout:.0f}s"
                    )
                time.sleep(0.02)
        except Exception:
            self.stop(graceful=False)
            raise

    # -- command fan-out -----------------------------------------------------
    def call(self, idx: int, cmd: str, timeout: float = 120.0, **kwargs):
        return self.executors[idx].call(cmd, timeout=timeout, **kwargs)

    def broadcast(self, cmd: str, timeout: float = 120.0, **kwargs):
        """Send to every executor, THEN collect — commands overlap
        across the fleet instead of serializing through one pipe."""
        for ex in self.executors:
            ex.send(cmd, **kwargs)
        return [ex.recv(timeout) for ex in self.executors]

    def register(self, shuffle_id: int, num_maps: int, partitioner,
                 aggregator=None, **kwargs):
        return self.broadcast(
            "register", shuffle_id=shuffle_id, num_maps=num_maps,
            partitioner=partitioner, aggregator=aggregator, **kwargs,
        )

    def maps_by_host(self, shuffle_id: int):
        return self.driver.maps_by_host(shuffle_id)

    def wait_published(self, shuffle_id: int, num_maps: int,
                       timeout: float = 60.0):
        """Block until the driver has seen ``num_maps`` map outputs."""
        deadline = time.monotonic() + timeout
        while True:
            mbh = self.driver.maps_by_host(shuffle_id)
            if sum(len(v) for v in mbh.values()) >= num_maps:
                return mbh
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shuffle {shuffle_id}: {mbh} after {timeout:.0f}s"
                )
            time.sleep(0.02)

    def read(self, idx: int, shuffle_id: int, start: int, end: int,
             digest: bool = False, timeout: float = 120.0):
        return self.call(
            idx, "read", timeout=timeout, shuffle_id=shuffle_id,
            start=start, end=end,
            maps_by_host=self.driver.maps_by_host(shuffle_id),
            digest=digest,
        )

    def census(self) -> dict:
        """Fleet-wide process census: driver + every live executor."""
        out = {"driver": _process_census(), "executors": {}}
        for ex in self.executors:
            if not ex.alive:
                continue
            try:
                out["executors"][ex.idx] = ex.call("metrics", timeout=30.0)
            except (ExecutorDiedError, TimeoutError):
                pass
        return out

    # -- lifecycle -----------------------------------------------------------
    def kill(self, idx: int) -> None:
        self.executors[idx].kill()

    def stop(self, graceful: bool = True) -> None:
        if self._state != "running":
            return
        self._transition("stopping", frm="running")
        # deliberate shutdown must not race the heartbeat monitor into
        # declaring executor deaths (manager.quiesce contract)
        try:
            self.driver.quiesce()
        except Exception:
            pass
        for ex in self.executors:
            if graceful and ex.alive:
                ex.stop()
            else:
                ex.kill()
        try:
            self.driver.stop()
        except Exception:
            logger.exception("cluster driver stop failed")
        self._transition("stopped", frm="stopping")

    def collect(self) -> dict:
        """Merge every per-process flight-recorder dump in ``workdir``
        into one trace document (obs/collect merge path); also lists
        the metrics JSONs and logs the run left behind."""
        import glob
        import os

        from sparkrdma_tpu.obs.collect import merge_dumps

        dumps = sorted(
            glob.glob(os.path.join(self.workdir, "flightrec-*.json")))
        merged = merge_dumps(dumps) if dumps else {"merged": True,
                                                  "processes": []}
        merged["dump_paths"] = dumps
        merged["metrics_paths"] = sorted(
            glob.glob(os.path.join(self.workdir, "metrics.json*")))
        merged["log_paths"] = sorted(
            glob.glob(os.path.join(self.workdir, "executor-*.log")))
        return merged

    def close(self) -> None:
        """stop() + scrub the workdir when the cluster owns it."""
        self.stop()
        if self._own_workdir:
            import shutil

            shutil.rmtree(self.workdir, ignore_errors=True)

    def __enter__(self) -> "ProcessCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


__all__ = [
    "ExecutorCommandError",
    "ExecutorDiedError",
    "ExecutorProcess",
    "ProcessCluster",
    "SimPeerFleet",
    "SimPeerFleetProc",
    "records_digest",
]
