"""Dry-run peer fleet: N wire-protocol peers on ONE selector thread.

Scale tests and the fabric-scale bench need hundreds of fetchable peers
without paying hundreds of real :class:`~sparkrdma_tpu.transport.node.Node`
instances (each with its own dispatcher loop and pools — the very cost
the bounded fabric exists to avoid paying per peer).  A
:class:`SimPeerFleet` listens on ``n_peers`` consecutive ports and
speaks just enough of the TCP wire protocol (transport/tcp.py framing)
to serve the fetch path:

- the 9-byte connect hello is acked (any channel type),
- ``OP_READ_REQ`` frames are answered with ``OP_READ_RESP`` served
  from one shared pattern buffer (``BlockLocation.address`` indexes
  into it; ``mkey`` is ignored), so striped sub-range reads reassemble
  bit-exactly,
- ``OP_RPC`` frames are swallowed.

Everything — all listeners and every accepted connection — runs on a
single daemon thread with non-blocking sockets, so a 256-peer fleet
costs one thread plus its sockets.  The node under test connects to
``fleet.addresses[i]`` through the REAL engines (threaded or async);
only the far side is simulated.
"""

from __future__ import annotations

import logging
import selectors
import socket
import threading
import time
from typing import List, Tuple

from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport import tcp as wire

logger = logging.getLogger(__name__)

_MAX_RX = 1 << 20


class _Conn:
    __slots__ = ("sock", "rx", "tx", "hello_done")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rx = bytearray()
        self.tx = bytearray()
        self.hello_done = False


class SimPeerFleet:
    """``n_peers`` fake wire-protocol peers on one selector thread."""

    def __init__(self, n_peers: int, base_port: int, pattern,
                 host: str = "127.0.0.1"):
        self._pattern = memoryview(pattern).cast("B")
        self.addresses: List[Tuple[str, int]] = []
        self._sel = selectors.DefaultSelector()
        self._listeners: List[socket.socket] = []
        self._conns: dict = {}
        self._stop = threading.Event()
        # wake pipe so stop() interrupts a parked select
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        for i in range(n_peers):
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                srv.bind((host, base_port + i))
                srv.listen(64)
            except OSError:
                srv.close()
                self.close()
                raise
            srv.setblocking(False)
            self._sel.register(srv, selectors.EVENT_READ, "accept")
            self._listeners.append(srv)
            self.addresses.append((host, base_port + i))
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="simfleet",
        )
        self._thread.start()

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        for srv in self._listeners:
            try:
                srv.close()
            except OSError:
                pass
        for conn in list(self._conns.values()):
            try:
                conn.sock.close()
            except OSError:
                pass
        self._conns.clear()
        try:
            self._sel.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()

    # -- event loop ----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            for key, events in self._sel.select(timeout=1.0):
                if self._stop.is_set():
                    return
                if key.data == "wake":
                    return
                if key.data == "accept":
                    self._accept(key.fileobj)
                    continue
                conn = key.data
                try:
                    if events & selectors.EVENT_READ:
                        self._readable(conn)
                    if (conn.sock in self._conns
                            and events & selectors.EVENT_WRITE):
                        self._flush(conn)
                except Exception:
                    logger.exception("simfleet connection failed")
                    self._drop(conn)

    def _accept(self, srv) -> None:
        try:
            sock, _addr = srv.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drop(self, conn: _Conn) -> None:
        if conn.sock not in self._conns:
            return
        del self._conns[conn.sock]
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not chunk:
            self._drop(conn)
            return
        conn.rx += chunk
        self._process(conn)

    def _process(self, conn: _Conn) -> None:
        rx = conn.rx
        if not conn.hello_done:
            if len(rx) < wire._HELLO.size:
                return
            magic, _ct, _port, version = wire._HELLO.unpack_from(rx, 0)
            del rx[:wire._HELLO.size]
            if magic != wire._MAGIC:
                self._drop(conn)
                return
            if not (wire.MIN_WIRE_VERSION <= version <= wire.WIRE_VERSION):
                # same structured rejection real acceptors send: the
                # dialing engine surfaces both versions in its error
                self._send(conn, b"\x00" + wire._HELLO_REJ.pack(
                    wire.WIRE_VERSION, version))
                self._drop(conn)
                return
            conn.hello_done = True
            self._send(conn, b"\x01")
        while len(rx) >= wire._HDR.size:
            opcode, length = wire._HDR.unpack_from(rx, 0)
            if len(rx) < wire._HDR.size + length:
                if len(rx) > _MAX_RX + wire._HDR.size + length:
                    self._drop(conn)
                return
            payload = bytes(rx[wire._HDR.size:wire._HDR.size + length])
            del rx[:wire._HDR.size + length]
            if opcode == wire.OP_READ_REQ:
                self._serve_read(conn, payload)
            # OP_RPC frames are swallowed: the fleet has no control plane

    def _serve_read(self, conn: _Conn, payload: bytes) -> None:
        req_id, count = wire._REQ_HDR.unpack_from(payload, 0)
        if RECORDER.enabled:
            # the requester's trace context rides the request's v2
            # tail — the fleet's serve events join its trace exactly
            # like a real peer's would
            ctx = wire._req_trace(payload)
            t0 = time.monotonic()
        parts = [wire._RESP_HDR.pack(req_id, 0)]
        off = wire._REQ_HDR.size
        try:
            for _ in range(count):
                addr, length, _mkey = wire._LOC.unpack_from(payload, off)
                off += wire._LOC.size
                if addr < 0 or addr + length > self._pattern.nbytes:
                    raise ValueError(
                        f"read [{addr},{addr + length}) outside the "
                        f"{self._pattern.nbytes}B pattern"
                    )
                parts.append(wire._LEN.pack(length))
                parts.append(self._pattern[addr:addr + length])
        except Exception as e:
            parts = [
                wire._RESP_HDR.pack(req_id, 1),
                str(e).encode("utf-8", "replace"),
            ]
        body = b"".join(bytes(p) for p in parts)
        if RECORDER.enabled:
            fr_event(
                "transport", "serve_read",
                trace_id=ctx[0] if ctx else 0,
                span_id=ctx[1] if ctx else 0,
                blocks=count,
                us=int((time.monotonic() - t0) * 1e6),
            )
        self._send(
            conn, wire._HDR.pack(wire.OP_READ_RESP, len(body)) + body
        )

    def _send(self, conn: _Conn, data: bytes) -> None:
        conn.tx += data
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        while conn.tx:
            try:
                n = conn.sock.send(conn.tx)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop(conn)
                return
            del conn.tx[:n]
        events = selectors.EVENT_READ
        if conn.tx:
            events |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass


def _fleet_proc_main(n_peers, base_port, pattern, dump_path, host,
                     ready, stop) -> None:
    """Entry point of the spawned fleet process: serve until ``stop``,
    then leave a flight-recorder dump at ``dump_path`` so the parent
    can merge this process's serve spans with its own trace
    (obs/collect.py)."""
    RECORDER.retain()
    try:
        fleet = SimPeerFleet(n_peers, base_port, pattern, host=host)
    except OSError as e:
        ready.put(("err", str(e)))
        return
    ready.put(("ok", fleet.addresses))
    stop.wait()
    fleet.close()
    if dump_path:
        RECORDER.dump("fleet_stop", path=dump_path)
    RECORDER.release()


class SimPeerFleetProc:
    """A :class:`SimPeerFleet` in its OWN process (multiprocessing
    spawn — the module chain stays jax-free, so spawn is cheap).

    The point is cross-process observability: the child retains the
    flight recorder, its ``serve_read`` events carry the requester's
    trace context off the wire, and ``close()`` leaves a dump at
    ``dump_path`` for the parent to merge — a 2-process run then
    yields ONE trace spanning requester and server spans."""

    def __init__(self, n_peers: int, base_port: int, pattern,
                 dump_path: str = "", host: str = "127.0.0.1",
                 start_timeout: float = 30.0):
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        self._stop = ctx.Event()
        ready = ctx.Queue()
        self.dump_path = dump_path
        self._proc = ctx.Process(
            target=_fleet_proc_main,
            args=(n_peers, base_port, bytes(pattern), dump_path, host,
                  ready, self._stop),
            daemon=True,
        )
        self._proc.start()
        try:
            status, detail = ready.get(timeout=start_timeout)
        except Exception:
            self._proc.terminate()
            raise RuntimeError("simfleet subprocess did not come up")
        if status != "ok":
            self._proc.join(timeout=5)
            raise OSError(f"simfleet subprocess bind failed: {detail}")
        self.addresses: List[Tuple[str, int]] = detail

    def close(self) -> None:
        self._stop.set()
        self._proc.join(timeout=15)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)


__all__ = ["SimPeerFleet", "SimPeerFleetProc"]
