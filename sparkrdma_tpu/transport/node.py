"""Per-process transport endpoint: channel cache, dispatch, teardown.

TPU-native re-design of the reference's RdmaNode (RdmaNode.java:36-397):
one ``Node`` per process (driver and each executor) owning

- the process's listening address,
- the receive dispatcher for incoming control-plane frames (the
  reference's receiveListener wiring),
- the block-store registry serving one-sided reads (the PD + registered
  MRs in the reference; HBM arenas / host stores here),
- an active-channel cache with racy-create resolution and bounded
  connect retries (RdmaNode.java:277-351),
- parallel teardown of all channels on stop (RdmaNode.java:353-394).

The CM event channel / listening thread has no analog: backends
(loopback now, ICI exchange for bulk) register passive channels directly
via ``register_passive_channel``.
"""

from __future__ import annotations

import logging
import os
import queue
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.faults.breaker import PeerHealthRegistry
from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.qos import (
    BULK,
    INTERACTIVE,
    ClassedTaskQueue,
    WeightedCreditBroker,
    get_qos,
)
from sparkrdma_tpu.utils.dbglock import dbg_condition, dbg_lock
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.transport.channel import (
    BlockStore,
    Channel,
    ChannelType,
    FatalTransportError,
    TransportError,
)
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)

Address = Tuple[str, int]

# Frames arriving on a channel are handed to: (source_channel, frame_bytes)
ReceiveListener = Callable[[Channel, bytes], None]

#: thread-name prefixes of every transport/shuffle plane thread this
#: library spawns — the census (and the scale tests) count by these
TRANSPORT_THREAD_PREFIXES = (
    "disp-",        # async dispatcher event loops
    "tcp-",         # threaded-mode channel readers + accept loops
    "serve-",       # bounded read-serve pool workers
    "node-",        # completion/dispatch pool + teardown workers
    "decode-",      # reduce-side decode pool workers
)


def transport_census() -> Dict[str, object]:
    """Thread/fd census of the transport planes: live library threads
    grouped by role prefix, total Python threads, and this process's
    open fd count (Linux; -1 elsewhere).  Refreshes the
    ``transport_threads`` gauge so scrapes see the census too.  The
    async dispatcher's acceptance criterion — O(1) transport threads
    per node regardless of peer × stripe fan-out — is asserted against
    this (tests/test_dryrun_scale.py)."""
    by_role: Dict[str, int] = {}
    for t in threading.enumerate():
        for prefix in TRANSPORT_THREAD_PREFIXES:
            if t.name.startswith(prefix):
                by_role[prefix.rstrip("-")] = (
                    by_role.get(prefix.rstrip("-"), 0) + 1
                )
                break
    n = sum(by_role.values())
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = -1
    gauge("transport_threads").set(n)
    return {
        "transport_threads": n,
        "by_role": by_role,
        "python_threads": threading.active_count(),
        "open_fds": fds,
    }


class _ServePool:
    """Bounded read-serve pool: fixed worker threads drain serve tasks
    under a byte-credit budget — the responder-side flow control of
    the one-sided READ service.  A serve's cost is the requested byte
    total; workers block until enough credits are free, so a slow
    reducer draining many multi-MB responses can never pin unbounded
    server memory (the serve holds its resolved block views only while
    it owns credits).  A single serve larger than the whole budget
    clamps to it and runs alone rather than deadlocking.

    Credits flow through a :class:`WeightedCreditBroker` (qos/): with
    QoS off that is plain FIFO handoff over one budget (and the
    explicit FIFO is itself the fairness fix — grants go to credit
    waiters in arrival order, so a clamped oversized serve can no
    longer be bypassed indefinitely by a stream of small serves that
    happen to fit the remaining credits); with QoS on, tenants take
    weighted max-min shares, interactive-class serves (small reads,
    interactive tenants) dequeue AND acquire ahead of bulk, and aging
    keeps bulk from starving."""

    def __init__(self, name: str, workers: int, credit_bytes: int,
                 init_fn=None, conf: Optional[TpuShuffleConf] = None):
        qos = (
            get_qos() if conf is not None and conf.qos_enabled else None
        )
        self._qos = qos
        self._interactive_bytes = (
            conf.qos_interactive_bytes if conf is not None else 512 << 10
        )
        aging_ms = conf.qos_aging_ms if conf is not None else 100
        # both conditions are created HERE (and handed to the qos/
        # machinery) so their ranks land in this file's hierarchy
        self._queue_cv = dbg_condition("node.serve_queue", 49)
        self._queue = ClassedTaskQueue(
            self._queue_cv,
            classed=qos is not None, aging_ms=aging_ms,
        )
        self._stopped = False
        self._m_depth = gauge("transport_serve_queue_depth")
        self._m_tasks = counter("transport_serve_tasks_total")
        self._m_credit_waits = counter("transport_serve_credit_waits_total")
        self._cv = dbg_condition("node.serve_credits", 50)
        # resource: serve.credit_bytes
        self._broker = WeightedCreditBroker(
            "serve", max(int(credit_bytes), 1), self._cv,
            qos=qos, classed=qos is not None, aging_ms=aging_ms,
            wait_counter=self._m_credit_waits,
        )
        self._workers = [
            threading.Thread(
                target=self._run, daemon=True, name=f"serve-{name}-{i}",
                args=(init_fn,),
            )
            for i in range(max(1, workers))
        ]
        for t in self._workers:
            t.start()

    def _classify(self, cost: int, tenant, cls: Optional[str]) -> str:
        if cls is not None:
            return cls
        if self._qos is None:
            return BULK
        if cost <= self._interactive_bytes:
            return INTERACTIVE  # the small-read-lane lineage
        if tenant is not None and tenant.interactive:
            return INTERACTIVE
        return BULK

    def submit(self, fn, args: tuple, cost: int,
               deferred: bool = False, tenant=None,
               cls: Optional[str] = None) -> None:
        """Never blocks the caller (channel reader loops and the async
        dispatcher post here).  ``deferred=True`` is the
        completion-driven contract: the worker calls
        ``fn(*args, release)`` and the CALLEE owns returning the
        credits via the idempotent ``release()`` — typically from the
        response's send-completion event — so credits keep bounding
        resident serve memory without a worker blocked in the send."""
        if self._stopped:
            raise TransportError("serve pool stopped")
        cost = max(int(cost), 0)
        cls = self._classify(cost, tenant, cls)
        self._m_depth.inc()
        self._queue.put((fn, args, cost, deferred, tenant, cls), cls=cls)

    def _make_release(self, cost: int, tenant, tkt=NOOP_TICKET):
        """Idempotent credit return, safe from any thread (list.pop is
        atomic under the GIL — exactly one caller wins the token)."""
        token = [None]

        def release() -> None:
            try:
                token.pop()
            except IndexError:
                return
            self._broker.release(cost, tenant)  # releases: serve.credit_bytes
            tkt.release()

        return release

    def _run(self, init_fn) -> None:
        if init_fn is not None:
            init_fn()
        g = gauge("transport_threads", role="serve")
        g.inc()
        try:
            self._drain(init_fn)
        finally:
            g.dec()

    def _drain(self, _init_fn) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            self._m_depth.dec()
            fn, args, cost, deferred, tenant, cls = item
            cost = self._broker.clamp(cost)
            # owns: serve.credit_bytes -> release  (every exit of the
            # try below — including the deferred contract, where the
            # callee's completion event settles it — funnels through
            # the idempotent closure)
            if not self._broker.acquire(  # acquires: serve.credit_bytes
                    cost, tenant, cls):
                return  # pool stopped while credit-waiting
            self._m_tasks.inc()
            tkt = ledger_acquire("serve.credit_bytes", cost)
            release = self._make_release(cost, tenant, tkt)
            try:
                if deferred:
                    fn(*args, release)
                else:
                    fn(*args)
            except BaseException:
                logger.exception("read serve failed")
                release()
            finally:
                if not deferred:
                    release()

    def stop(self) -> None:
        self._stopped = True
        self._broker.stop()
        # abandon queued serves (their channels are tearing down) and
        # keep the queue-depth gauge honest for the next node in this
        # process
        for _item in self._queue.drain_nowait():
            self._m_depth.dec()
        for _ in self._workers:
            self._queue.put_sentinel()
        for t in self._workers:
            t.join(timeout=2.0)


class _LanePool:
    """Fixed per-node budget of borrowable data lanes (the RDMAvisor /
    fabric-lib bounded-channel idiom): a striped read borrows up to
    ``transportNumStripes`` tokens for its duration and returns them on
    completion, so concurrent stripe fan-out across ALL peers is capped
    at ``transportLanePoolSize`` instead of every peer owning
    ``transportNumStripes`` dedicated sockets.  Borrowing never blocks:
    an empty pool means the read falls back to the peer's dedicated
    small-read lane, unstriped (narrower, never wrong).  Size 0 is the
    unbounded pre-fabric sentinel.

    With QoS on, ``reserve`` lane tokens are withheld from BULK-class
    borrows (qos/ priority grants): an interactive tenant's striped
    read always finds width even while bulk fan-out saturates the
    pool — the lane-scheduler half of the small-read-lane
    generalization."""

    def __init__(self, size: int, reserve: int = 0):
        self.size = max(int(size), 0)
        # a reserve covering the whole pool would demote EVERY bulk
        # read to the small lane — cap it below the pool size
        self.reserve = (
            min(max(int(reserve), 0), max(self.size - 1, 0))
            if self.size else 0
        )
        self._free = self.size  # resource: node.lane_tokens  # guarded-by: _lock
        self._lock = dbg_lock("node.lane_pool", 45)
        self._m_in_use = gauge("transport_lane_pool_in_use")
        self._m_borrows = counter("transport_lane_borrows_total")
        self._m_exhausted = counter("transport_lane_pool_exhausted_total")

    def try_borrow(self, want: int, cls: str = BULK) -> int:
        """Take up to ``want`` lane tokens without blocking; returns
        how many were granted (0 when the pool is dry).  BULK-class
        borrows leave the interactive reserve untouched."""
        if want <= 0:
            return 0
        if self.size == 0:
            return want
        floor = self.reserve if cls != INTERACTIVE else 0
        with self._lock:
            got = min(want, max(self._free - floor, 0))
            self._free -= got
        if got:
            self._m_in_use.inc(got)
            self._m_borrows.inc(got)
        else:
            self._m_exhausted.inc()
        return got

    def release(self, n: int) -> None:
        if n <= 0 or self.size == 0:
            return
        with self._lock:
            self._free = min(self.size, self._free + n)
        self._m_in_use.dec(n)


class Node:
    """One transport endpoint per process."""

    def __init__(
        self,
        address: Address,
        conf: Optional[TpuShuffleConf] = None,
        is_executor: bool = False,
    ):
        self.address = address
        self.conf = conf or TpuShuffleConf()
        self.is_executor = is_executor
        # optional pooled-buffer source for bulk receives (set by the
        # owning manager; TCP read responses land in pooled buffers)
        self.staging_pool = None
        # optional tiered block store (memory/tier.py, set by the
        # owning manager): prefetch hints warm its cold blocks through
        # the serve pool before the read RPCs arrive (warm_blocks)
        self.tier_store = None
        self._receive_listener: Optional[ReceiveListener] = None
        self._block_stores: Dict[int, BlockStore] = {}  # guarded-by: _block_store_lock
        self._block_store_lock = dbg_lock("node.block_stores", 48)
        # active (locally initiated) channels keyed by (peer, type, slot)
        # — slots > 0 are the striped data lanes of a peer's channel
        # group (transport/stripe.py)
        self._active: Dict[
            Tuple[Address, ChannelType, int], Channel
        ] = {}  # guarded-by: _active_lock
        self._active_lock = dbg_lock("node.active", 42)
        # LRU bookkeeping for the bounded channel cache: last-use
        # sequence per key, keys evicted at least once (so a
        # reconnect is countable), and the conf cap (0 = unbounded)
        self._last_use: Dict[
            Tuple[Address, ChannelType, int], int
        ] = {}  # guarded-by: _active_lock
        self._use_seq = 0  # guarded-by: _active_lock
        self._evicted_keys: set = set()  # guarded-by: _active_lock
        self._max_cached = self.conf.transport_max_cached_channels
        # multi-tenant QoS (qos/): the process-global tenant registry
        # when policy is on for this node's conf — pools classify and
        # broker through it; None keeps every edge plain FIFO
        self.qos = get_qos() if self.conf.qos_enabled else None
        # fixed borrowable data-lane budget for striped reads
        # (transport/stripe.py borrows per read, releases on completion);
        # QoS withholds a reserve slice from bulk-class borrows
        self.lane_pool = _LanePool(
            self.conf.transport_lane_pool_size,
            reserve=(
                self.conf.qos_lane_reserve if self.qos is not None else 0
            ),
        )
        self._m_cached = gauge("transport_cached_channels")
        self._m_evictions = counter("transport_channel_evictions_total")
        self._m_evict_refusals = counter(
            "transport_channel_evict_refusals_total")
        self._m_reconnects = counter("transport_channel_reconnects_total")
        # per-peer striped read groups (lazy; share the channel cache)
        self._read_groups: Dict[Address, object] = {}  # guarded-by: _read_groups_lock
        self._read_groups_lock = dbg_lock("node.read_groups", 44)
        # per-peer recovery state (faults/breaker.py): circuit breaker
        # + stripe health.  Node-resident — NOT on the ReadGroup, which
        # invalidate_read_group destroys on exactly the failures this
        # history must survive
        self._peer_health = PeerHealthRegistry(self.conf)
        self._passive: List[Channel] = []  # guarded-by: _passive_lock
        self._passive_lock = dbg_lock("node.passive", 46)
        # completion/dispatch pool — the RdmaThread analog: completions and
        # inbound frames are delivered off the caller's thread.  When
        # conf dispatcherCpuList (legacy alias: spark.shuffle.rdma
        # .cpuList) names a CPU subset, every worker pins itself to it
        # — the RdmaThread comp-vector affinity (RdmaNode.java:216-273)
        self._cpu_pins = self._parse_cpu_pins()
        self._dispatcher = ThreadPoolExecutor(
            max_workers=4,
            thread_name_prefix=f"node-{address[0]}:{address[1]}",
            initializer=self._init_pool_thread,
        )
        # the read service runs on its OWN bounded serve pool so
        # multi-MB block serves can never starve control-plane traffic
        # (a starved heartbeat ack would get a healthy executor pruned)
        # nor the channel reader loops, and its byte credits bound how
        # much registered memory concurrent serves pin
        self._serve_pool: Optional[_ServePool] = None
        self._serve_lock = dbg_lock("node.serve_pool", 40)
        # async transport core (transport/dispatcher.py): ONE selector
        # event-loop thread owning every transport socket, created
        # lazily by the first socket-backed registration under
        # conf transportAsyncDispatcher
        self._async_dispatcher = None
        self._disp_lock = dbg_lock("node.disp", 41)
        self._stopped = threading.Event()

    # -- dispatcher thread placement ----------------------------------------
    def _parse_cpu_pins(self) -> Optional[frozenset]:
        """Expand conf dispatcherCpuList against this host's CPUs for
        dispatcher-thread affinity.  None (no pinning) when the knob is
        unset, the platform has no ``sched_setaffinity``, or the parse
        resolves to every CPU anyway."""
        spec = self.conf.dispatcher_cpu_list.strip()
        if not spec or not hasattr(os, "sched_setaffinity"):
            return None
        ncpu = os.cpu_count() or 1
        pins = frozenset(self.conf.parse_dispatcher_cpu_list(ncpu))
        if not pins or pins == frozenset(range(ncpu)):
            return None
        return pins

    def _init_pool_thread(self) -> None:
        gauge("transport_threads", role="completion_pool").inc()
        self._pin_worker_thread()

    def _pin_worker_thread(self) -> None:
        if not self._cpu_pins:
            return
        try:
            os.sched_setaffinity(0, self._cpu_pins)
            counter("transport_threads_pinned_total").inc()
        except OSError as e:
            logger.warning(
                "%s: could not pin dispatcher thread to CPUs %s: %s",
                self, sorted(self._cpu_pins), e,
            )

    # -- receive dispatch ---------------------------------------------------
    def set_receive_listener(self, listener: ReceiveListener) -> None:
        self._receive_listener = listener

    def dispatch_frame(self, channel: Channel, frame: bytes,
                       on_consumed=None) -> None:
        """Deliver one inbound control-plane frame on the dispatcher.
        ``on_consumed`` fires once the frame's recv slot is free (credit
        accounting) — including on drop paths, so senders never starve."""
        listener = self._receive_listener
        if self._stopped.is_set() or listener is None:
            if listener is None and not self._stopped.is_set():
                logger.warning("%s: dropping frame, no receive listener", self)
            if on_consumed is not None:
                try:
                    on_consumed()
                except BaseException:
                    pass
            return
        self._dispatcher.submit(
            self._safe_dispatch, listener, channel, frame, on_consumed
        )

    @staticmethod
    def _safe_dispatch(listener, channel, frame, on_consumed=None) -> None:
        try:
            listener(channel, frame)
        except BaseException:
            logger.exception("receive listener raised")
        finally:
            if on_consumed is not None:
                try:
                    on_consumed()
                except BaseException:
                    pass

    def submit(self, fn, *args):
        """Run fn on the dispatcher (async completion delivery)."""
        return self._dispatcher.submit(fn, *args)

    def tenant_of_mkey(self, mkey) -> Optional[object]:
        """Resolve the QoS tenant owning a registered segment: the
        serve path classifies an incoming read by the TARGET block's
        owner (mkey → segment → shuffle → tenant), so the responder
        applies per-tenant policy with zero wire-format change.  None
        without QoS, for unknown mkeys, or for unbound shuffles."""
        qos = self.qos
        if qos is None or mkey is None:
            return None
        with self._block_store_lock:
            store = self._block_stores.get(mkey)
        get = getattr(store, "get", None)  # ArenaManager-backed stores
        if get is None:
            return None
        try:
            seg = get(mkey)
        except Exception:
            return None
        return qos.tenant_of_shuffle(getattr(seg, "shuffle_id", None))

    def submit_serve(self, fn, args: tuple = (), cost: int = 0,
                     deferred: bool = False, mkey=None,
                     cls: Optional[str] = None):
        """Run one read serve on the node's bounded serve pool (created
        on first use; workers pin to ``dispatcherCpuList`` like the
        dispatcher).  ``cost`` is the serve's requested byte total —
        the pool's credit budget throttles admission on it.
        ``deferred=True`` hands ``fn`` an idempotent ``release``
        callable that returns the credits (the async dispatcher's
        send-completion events release there instead of a worker
        blocking through the send).  ``mkey`` (the read's first target
        segment) resolves the owning tenant for QoS accounting;
        ``cls`` pins the priority class (tier warms pass BULK so a
        prefetch storm can never outrank demand serves)."""
        if self._stopped.is_set():
            raise TransportError(f"{self}: stopped")
        pool = self._serve_pool
        if pool is None:
            with self._serve_lock:
                if self._serve_pool is None:
                    self._serve_pool = _ServePool(
                        f"{self.address[0]}:{self.address[1]}",
                        self.conf.transport_serve_threads,
                        self.conf.transport_serve_credit_bytes,
                        init_fn=self._pin_worker_thread,
                        conf=self.conf,
                    )
                pool = self._serve_pool
        pool.submit(fn, args, cost, deferred,
                    tenant=self.tenant_of_mkey(mkey), cls=cls)

    def warm_blocks(self, locations) -> int:
        """Serve-side warm-before-read: promote the hinted block spans
        into the tier store's hot rows through the bounded serve pool —
        each warm is byte-credited exactly like a real serve, so a
        prefetch storm queues behind (and can never starve or out-pin)
        the serves it is trying to accelerate.  Returns warms
        submitted; a no-op without a tier store or for non-tiered
        mkeys."""
        tier = self.tier_store
        if tier is None:
            return 0
        n = 0
        for loc in locations:
            if loc.is_empty or not tier.would_warm(loc.mkey):
                continue
            try:
                self.submit_serve(
                    tier.warm, (loc.mkey, loc.address, loc.length),
                    cost=loc.length, mkey=loc.mkey, cls=BULK,
                )
            except TransportError:
                break  # node stopping: drop the remaining hints
            n += 1
        return n

    def get_dispatcher(self):
        """The node's async transport event loop (the submission/
        completion-queue progress engine, transport/dispatcher.py) —
        created lazily so loopback-only nodes never pay for it.
        Completion batches dispatch onto this node's completion pool
        (``submit``)."""
        d = self._async_dispatcher
        if d is not None:
            return d
        with self._disp_lock:
            if self._async_dispatcher is None:
                if self._stopped.is_set():
                    raise TransportError(f"{self}: stopped")
                from sparkrdma_tpu.transport.dispatcher import Dispatcher

                self._async_dispatcher = Dispatcher(
                    f"{self.address[0]}:{self.address[1]}",
                    self.conf, self.submit,
                    pin_fn=self._pin_worker_thread,
                )
            return self._async_dispatcher

    # -- block stores (registered memory domains) ---------------------------
    def register_block_store(self, mkey: int, store: BlockStore) -> None:
        with self._block_store_lock:
            self._block_stores[mkey] = store

    def unregister_block_store(self, mkey: int) -> None:
        with self._block_store_lock:
            self._block_stores.pop(mkey, None)

    def read_local_block(self, location: BlockLocation) -> bytes:
        """Serve a one-sided read against this node's registered memory."""
        with self._block_store_lock:
            store = self._block_stores.get(location.mkey)
        if store is None:
            # fatal: the shuffle was unregistered (or never registered)
            # here — a retry would just re-ask the same dead question
            raise FatalTransportError(
                f"{self}: no block store registered for mkey={location.mkey}"
            )
        return store.read_block(location)

    def read_local_blocks(self, locations) -> list:
        """Batched one-sided read service: groups by owning store and
        uses its ``read_blocks`` (per-segment batched transfers on the
        arena store; the BlockStore base falls back per block)."""
        by_store: dict = {}
        with self._block_store_lock:
            for i, loc in enumerate(locations):
                store = self._block_stores.get(loc.mkey)
                if store is None:
                    raise FatalTransportError(
                        f"{self}: no block store registered for "
                        f"mkey={loc.mkey}"
                    )
                by_store.setdefault(id(store), (store, []))[1].append(i)
        out: list = [b""] * len(locations)
        for store, idxs in by_store.values():
            blocks = store.read_blocks([locations[i] for i in idxs])
            if len(blocks) != len(idxs):
                raise TransportError(
                    f"{store!r}.read_blocks returned {len(blocks)} "
                    f"blocks for {len(idxs)} locations"
                )
            for i, b in zip(idxs, blocks):
                out[i] = b
        return out

    # -- channel cache ------------------------------------------------------
    def get_channel(
        self,
        peer: Address,
        channel_type: ChannelType,
        connect: Callable[["Node", Address, ChannelType], Channel],
        must_retry: bool = True,
        slot: int = 0,
    ) -> Channel:
        """Get-or-create a channel to ``peer``.

        ``connect`` is the backend's connector.  Mirrors the reference's
        racy-create + retry loop (RdmaNode.java:277-351): concurrent
        callers race benignly, losers close their extra channel; dead
        cached channels are replaced up to ``connectRetries`` attempts
        with jittered exponential backoff (``connectBackoffMs`` base,
        doubling per attempt, capped at 16x).
        ``slot`` distinguishes the parallel data lanes of a striped
        channel group — each slot is its own cached connection.

        The cache is BOUNDED at ``transportMaxCachedChannels`` (0 =
        unbounded): inserting past the cap evicts the idle-coldest
        cached channels, and a key evicted earlier transparently
        reconnects here (counted as a reconnect).  A caller that loses
        the tiny race between receiving a cached channel and posting on
        it sees a synchronous ``TransportError`` and simply calls
        get_channel again — the evicted key is gone from the cache, so
        the retry reconnects (transport/stripe.py and the manager's
        control-plane send helpers do exactly that).
        """
        attempts = 0
        last_err: Optional[BaseException] = None
        max_attempts = self.conf.connect_retries if must_retry else 1
        backoff_s = self.conf.connect_backoff_ms / 1000.0
        key = (peer, channel_type, slot)
        while attempts < max_attempts and not self._stopped.is_set():
            attempts += 1
            if attempts > 1:
                counter("transport_connect_retries_total").inc()
            with self._active_lock:
                ch = self._active.get(key)
                if ch is not None and ch.is_connected():
                    self._touch_locked(key)
                    return ch
            try:
                new_ch = connect(self, peer, channel_type)
            except BaseException as e:
                last_err = e
                # jittered exponential backoff (equal jitter: half
                # fixed, half uniform — lockstep reconnect storms after
                # a shared-fabric blip decorrelate) on the stop event,
                # not time.sleep: node teardown mid-retry interrupts
                # the wait immediately instead of blocking stop()
                base = min(backoff_s * (2.0 ** (attempts - 1)),
                           backoff_s * 16.0)
                delay = base / 2.0 + random.uniform(0.0, base / 2.0)
                if self._stopped.wait(delay):
                    break
                continue
            with self._active_lock:
                cur = self._active.get(key)
                if cur is not None and cur.is_connected():
                    winner, loser = cur, new_ch  # lost the race
                else:
                    self._active[key] = new_ch
                    winner, loser = new_ch, cur
                self._touch_locked(key)
                reconnected = (
                    winner is new_ch and key in self._evicted_keys
                )
                if reconnected:
                    self._evicted_keys.discard(key)
                self._m_cached.set(len(self._active))
            if reconnected:
                self._m_reconnects.inc()
            if loser is not None:
                loser.stop()
            if winner.is_connected():
                if winner is new_ch:
                    self._maybe_evict(keep=key)
                return winner
            with self._active_lock:
                if self._active.get(key) is winner:
                    del self._active[key]
                    self._last_use.pop(key, None)
                self._m_cached.set(len(self._active))
            # stop the dead winner: nothing else references it, and
            # skipping teardown would leak its outstanding listeners
            # and the active-channel gauge increment
            winner.stop()
            last_err = TransportError("channel died immediately after connect")
        counter("transport_connect_exhausted_total").inc()
        # the peer is unreachable: a cached read group must not pin its
        # lane bookkeeping (and gauge) for the node's lifetime
        self.invalidate_read_group(peer)
        raise TransportError(
            f"{self}: could not connect to {peer} ({channel_type.name}) "
            f"after {attempts} attempts"
        ) from last_err

    def _touch_locked(
        self, key: Tuple[Address, ChannelType, int]
    ) -> None:
        """Record a cache use for LRU ordering — caller holds
        ``_active_lock``."""
        self._use_seq += 1  # noqa: CK03 - caller holds _active_lock
        self._last_use[key] = self._use_seq  # noqa: CK03 - caller holds _active_lock

    def _maybe_evict(self, keep=None) -> None:
        """Shrink the channel cache back under the conf cap: victims
        are the idle-coldest cached channels (LRU by last use), never
        one with in-flight ops — the listener/descriptor machinery is
        the refcount (``Channel.in_flight``) — and never ``keep`` (the
        key whose channel the caller is about to hand out).  Victims
        are stopped OUTSIDE the cache lock; a racing user that already
        holds a victim sees a synchronous post error and re-resolves
        through get_channel, which reconnects the evicted key."""
        cap = self._max_cached
        if cap <= 0:
            return
        victims: List[Tuple[Tuple[Address, ChannelType, int], Channel]] = []
        with self._active_lock:
            need = len(self._active) - cap
            if need <= 0:
                return
            order = sorted(
                self._active,
                # the lambda runs inside this with-block (sorted is
                # eager) — the analyzer just can't see through it
                key=lambda k: self._last_use.get(k, 0),  # noqa: CK03
            )
            for k in order:
                if need <= 0:
                    break
                if k == keep:
                    continue
                ch = self._active[k]
                if ch.in_flight():
                    self._m_evict_refusals.inc()
                    continue
                del self._active[k]
                self._last_use.pop(k, None)
                self._evicted_keys.add(k)
                victims.append((k, ch))
                need -= 1
            live_peers = {k[0] for k in self._active}
            self._m_cached.set(len(self._active))
        if not victims:
            return  # everything over cap is busy: tolerate overflow
        self._m_evictions.inc(len(victims))
        for _k, ch in victims:
            try:
                ch.stop()
            except Exception:
                logger.exception("evicted channel stop failed")
        for p in {k[0] for k, _ch in victims} - live_peers:
            # the peer's LAST cached channel left: its read group has
            # nothing to multiplex over until a fetch recreates it
            self.invalidate_read_group(p)

    def on_channel_dead(self, channel: Channel) -> None:
        """Death hook from the engines' channel-teardown paths (tcp
        reader-loop failure, async loop death): drop the dead channel
        from the caches it occupies so a dead peer does not pin cache
        slots, passive-list entries, or a stale read group until node
        teardown.  Idempotent and safe from any thread."""
        if self._stopped.is_set():
            return
        peer: Optional[Address] = None
        with self._active_lock:
            for k, ch in self._active.items():
                if ch is channel:
                    del self._active[k]
                    self._last_use.pop(k, None)
                    peer = k[0]
                    break
            peer_live = peer is not None and any(
                k[0] == peer for k in self._active
            )
            self._m_cached.set(len(self._active))
        with self._passive_lock:
            try:
                self._passive.remove(channel)
            except ValueError:
                pass
        if peer is not None and not peer_live:
            self.invalidate_read_group(peer)

    def get_read_group(self, peer: Address, connect):
        """Get-or-create ``peer``'s striped read group (one small-read
        lane + data lanes BORROWED per read from the node's fixed lane
        pool, over the channel cache) — the bulk-fetch entry point for
        readers.  Invalidated when the peer dies or its last cached
        channel is evicted; the next fetch just recreates it."""
        with self._read_groups_lock:
            group = self._read_groups.get(peer)
            if group is None:
                from sparkrdma_tpu.transport.stripe import ReadGroup

                group = self._read_groups[peer] = ReadGroup(
                    self, peer, connect
                )
                gauge("transport_read_groups").inc()
        return group

    def peer_health(self, peer: Address):
        """``peer``'s recovery state (breaker + stripe health) —
        created on first use, survives read-group invalidation, cleared
        only at node stop."""
        return self._peer_health.get(peer)

    def invalidate_read_group(self, peer: Address) -> None:
        """Drop ``peer``'s cached read group (dead peer / evicted
        lanes): a group object already held by a reader keeps working —
        it re-resolves channels through the cache per read — this only
        stops a dead peer from pinning the cache entry and its gauge
        for the node's lifetime."""
        with self._read_groups_lock:
            group = self._read_groups.pop(peer, None)
        if group is not None:
            gauge("transport_read_groups").dec()
            counter("transport_read_group_invalidations_total").inc()

    def register_passive_channel(self, channel: Channel) -> None:
        if self._stopped.is_set():
            # an acceptor racing node teardown would otherwise hand out
            # a channel nothing ever stops — the peer's reads against
            # it would hang instead of failing fast
            channel.stop()
            return
        with self._passive_lock:
            self._passive.append(channel)

    def active_channels(self) -> List[Channel]:
        with self._active_lock:
            return list(self._active.values())

    # -- teardown -----------------------------------------------------------
    def stop(self) -> None:
        """Parallel teardown of all channels (RdmaNode.java:353-394)."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._active_lock:
            actives = list(self._active.values())
            self._active.clear()
            self._last_use.clear()
            self._evicted_keys.clear()
            self._m_cached.set(0)
        with self._passive_lock:
            passives = list(self._passive)
            self._passive.clear()
        channels = actives + passives
        if channels:
            # bounded parallel teardown (reference: stop() waits a
            # teardownListenTimeout window, RdmaNode.java:367-394): a
            # hung channel must not wedge shutdown forever.  Plain
            # DAEMON threads, not a ThreadPoolExecutor: its workers are
            # non-daemon and concurrent.futures' atexit hook joins
            # them, so an abandoned wedged stop would still hang
            # interpreter exit.
            budget = max(
                self.conf.teardown_listen_timeout_ms / 1000.0,
                0.05,
            ) * max(1, len(channels))
            work: "queue.Queue[Channel]" = queue.Queue()
            for c in channels:
                work.put(c)

            def _stop_worker() -> None:
                while True:
                    try:
                        c = work.get_nowait()
                    except queue.Empty:
                        return
                    try:
                        c.stop()
                    except Exception:
                        logger.exception("channel stop failed")
                    finally:
                        work.task_done()

            workers = [
                threading.Thread(
                    target=_stop_worker, daemon=True,
                    name=f"node-stop-{i}",
                )
                for i in range(min(8, len(channels)))
            ]
            for t in workers:
                t.start()
            deadline = time.monotonic() + budget
            for t in workers:
                t.join(max(0.0, deadline - time.monotonic()))
            hung = sum(1 for t in workers if t.is_alive())
            if hung:
                logger.warning(
                    "node %s teardown: %d stop worker(s) still busy "
                    "after %.1fs — abandoning (daemon threads; they "
                    "cannot block process exit)", self.address,
                    hung, budget,
                )
        # the async event loop stops AFTER channels (their _loop_close
        # descriptors must drain) and BEFORE the completion pool (its
        # teardown completion batch still needs an executor)
        with self._disp_lock:
            disp, self._async_dispatcher = self._async_dispatcher, None
        if disp is not None:
            disp.stop()
        self._dispatcher.shutdown(wait=True)
        gauge("transport_threads", role="completion_pool").dec(
            len(getattr(self._dispatcher, "_threads", ()))
        )
        with self._serve_lock:
            serve, self._serve_pool = self._serve_pool, None
        if serve is not None:
            serve.stop()
        with self._read_groups_lock:
            n_groups = len(self._read_groups)
            self._read_groups.clear()
        if n_groups:
            gauge("transport_read_groups").dec(n_groups)
        self._peer_health.clear()
        with self._block_store_lock:
            self._block_stores.clear()

    def __repr__(self) -> str:
        return f"Node({self.address[0]}:{self.address[1]})"
