"""Channel abstraction: two traffic classes, flow control, completions.

TPU-native re-design of the reference's RdmaChannel
(RdmaChannel.java:35-873).  Kept semantics:

- Four channel roles (RdmaChannel.java:41): RPC requestor/responder for
  the driver↔executor control plane, READ requestor/responder for the
  executor↔executor bulk plane.
- Send-budget semaphore + FIFO pending queue so posting more work than
  the queue depth never blocks the caller or drops work
  (RdmaChannel.java:61-71,379-439).
- Async completion listeners; ``on_failure`` must tolerate multiple
  invocations (RdmaCompletionListener.java:25).
- Channel state machine IDLE → CONNECTING → CONNECTED → ERROR/STOPPED,
  with sticky ERROR and ``stop()`` failing all outstanding listeners
  (RdmaChannel.java:103-110,788-869).

Dropped (no analog on TPU): QP/CQ plumbing, recv WR pools, credit
immediates — XLA owns scheduling on the bulk plane; the loopback backend
models completion dispatch with a dispatcher thread instead of a CQ
polling thread.
"""

from __future__ import annotations

import enum
import threading
from collections import deque
from typing import Callable, List, Optional, Sequence

from sparkrdma_tpu.metrics import gauge
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.statemachine import StateMachine
from sparkrdma_tpu.utils.types import BlockLocation


class TransportError(Exception):
    """Raised for channel/node failures (connect, send, read, teardown).

    ``transient`` classifies the failure for the reader's in-task
    retry policy: transient errors (the default — connection drops,
    lane deaths, injected faults) are worth retrying; fatal ones
    (:class:`FatalTransportError` — protocol violations, missing
    block stores) convert straight to ``FetchFailedError``.
    """

    transient = True


class FatalTransportError(TransportError):
    """A transport failure retrying cannot fix (bad frame, unknown
    mkey, wire-version mismatch)."""

    transient = False


def is_transient(err: BaseException) -> bool:
    """Retry classification: only transport errors marked transient
    qualify — anything else (decode bugs, serialization errors) is a
    program error a retry would just repeat."""
    return isinstance(err, TransportError) and err.transient


_FATAL_PREFIX = "FATAL:"


def encode_remote_error(err: BaseException) -> str:
    """Serve-side error -> status-frame reason string.  Fatal errors
    carry a classification prefix so the requester's taxonomy survives
    the wire without a frame change."""
    reason = str(err)
    if not is_transient(err) and isinstance(err, TransportError):
        return _FATAL_PREFIX + reason
    return reason


def decode_remote_error(reason: str) -> TransportError:
    """Status-frame reason string -> classified transport error."""
    if reason.startswith(_FATAL_PREFIX):
        return FatalTransportError(reason[len(_FATAL_PREFIX):])
    return TransportError(reason)


class ChannelType(enum.Enum):
    RPC_REQUESTOR = "rpc_requestor"
    RPC_RESPONDER = "rpc_responder"
    RPC_WRAPPER = "rpc_wrapper"  # bidirectional (driver side of hello-back)
    READ_REQUESTOR = "read_requestor"
    READ_RESPONDER = "read_responder"


class ChannelState(enum.Enum):
    IDLE = 0
    CONNECTING = 1
    CONNECTED = 2
    ERROR = 3
    STOPPED = 4


class CompletionListener:
    """Async completion contract (reference: RdmaCompletionListener.java).

    on_failure may be invoked more than once and must tolerate it.
    """

    def on_success(self, result) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def on_failure(self, error: BaseException) -> None:  # pragma: no cover
        raise NotImplementedError


class FnCompletionListener(CompletionListener):
    def __init__(self, on_success: Callable = None, on_failure: Callable = None):
        self._ok = on_success or (lambda r: None)
        self._err = on_failure or (lambda e: None)

    def on_success(self, result) -> None:
        self._ok(result)

    def on_failure(self, error: BaseException) -> None:
        self._err(error)


class Channel(StateMachine):
    """Base channel: state machine + send budgeting.

    Subclasses implement ``_post_rpc`` and ``_post_read`` which perform
    the actual transfer and MUST call ``_complete(listener, result)`` or
    ``_fail(listener, err)`` exactly once when done (possibly on another
    thread), then ``_release_budget()``.
    """

    MACHINE = "channel.lifecycle"
    STATES = ("idle", "connecting", "connected", "error", "stopped")
    INITIAL = "idle"
    TERMINAL = ("stopped",)
    TRANSITIONS = {
        "idle": ("connecting", "connected", "error", "stopped"),
        "connecting": ("connected", "error", "stopped"),
        "connected": ("error", "stopped"),
        "error": ("stopped",),
    }

    #: whether this channel's ``_post_read`` honors ``dest`` scatter
    #: buffers and ``on_progress`` callbacks (the striped-read group
    #: only stripes across channels that do)
    supports_scatter = False

    def __init__(self, channel_type: ChannelType, send_queue_depth: int = 4096):
        self.channel_type = channel_type
        #: negotiated wire generation — 0 means "unversioned" (in-process
        #: channels, tests), treated as current; the TCP engines stamp
        #: the handshake's accepted/negotiated version here, and senders
        #: suppress v2-only bytes when it reads 1
        self.wire_version = 0
        self._state = ChannelState.IDLE  # state: channel.lifecycle
        self._state_lock = dbg_lock("channel.state", 60)
        # send-WR budget: number of outstanding posted operations
        self._budget = threading.Semaphore(send_queue_depth)
        self._send_queue_depth = send_queue_depth
        # (post_fn, listener) pairs
        self._pending: deque = deque()  # guarded-by: _pending_lock
        self._pending_lock = dbg_lock("channel.pending", 62)
        # listeners awaiting completion
        self._outstanding: set = set()  # guarded-by: _outstanding_lock
        self._outstanding_lock = dbg_lock("channel.outstanding", 64)
        # active-channel gauge handle, held between CONNECTED and stop()
        self._m_active_gauge = None

    # -- state machine ------------------------------------------------------
    @property
    def state(self) -> ChannelState:
        return self._state

    def is_connected(self) -> bool:
        return self._state == ChannelState.CONNECTED

    def _set_state(self, new: ChannelState) -> None:
        with self._state_lock:
            if self._state in (ChannelState.ERROR, ChannelState.STOPPED):
                return  # sticky terminal states
            prev = self._state
            self._transition(new)
        if (new == ChannelState.CONNECTED
                and prev != ChannelState.CONNECTED
                and self._m_active_gauge is None):
            g = gauge("transport_active_channels")
            g.inc()
            self._m_active_gauge = g

    def _check_usable(self) -> None:
        if self._state != ChannelState.CONNECTED:
            raise TransportError(
                f"channel not connected (state={self._state.name})"
            )

    # -- public API ---------------------------------------------------------
    def send_rpc(self, frames: Sequence[bytes], listener: CompletionListener) -> None:
        """Post control-plane frames (reference: rdmaSendInQueue,
        RdmaChannel.java:476-505).  Never blocks: if the send budget is
        exhausted the operation is queued FIFO."""
        self._check_usable()
        self._enqueue(lambda: self._post_rpc(list(frames), listener), listener)

    def read_blocks(
        self,
        locations: Sequence[BlockLocation],
        listener: CompletionListener,
        dest: Optional[Sequence] = None,
        on_progress: Optional[Callable[[int], None]] = None,
        ctx=None,
    ) -> None:
        """Post a scatter read of remote blocks — the one-sided RDMA READ
        analog (reference: rdmaReadInQueue, RdmaChannel.java:441-474).
        Completion delivers a list of bytes-like payloads, one per
        location.

        Channels with ``supports_scatter`` additionally honor:

        - ``dest``: per-location writable uint8 buffers (or None
          entries) the payloads land in DIRECTLY — the striped
          reassembly path; completion then delivers the dest buffers
          themselves in place of fresh payloads.
        - ``on_progress(nbytes)``: fires as each location's payload
          arrives, before completion — stripe-granular in-flight-window
          accounting for the reader.

        ``ctx`` is an optional trace context (obs/) the engine carries
        to the serving node — the v2 read-request tail — so serve-side
        spans join the requester's trace; None costs nothing."""
        self._check_usable()
        if dest is None and on_progress is None and ctx is None:
            self._enqueue(
                lambda: self._post_read(list(locations), listener), listener
            )
        else:
            self._enqueue(
                lambda: self._post_read(
                    list(locations), listener, dest, on_progress, ctx
                ),
                listener,
            )

    def in_flight(self) -> int:
        """Operations posted but not yet completed (outstanding
        listeners + budget-queued posts) — the refcount the node's LRU
        channel cache consults before evicting: a channel with work in
        flight is never torn out from under its listeners.  Both
        engines route every op through the base-class listener
        machinery, so this covers reads and RPC sends alike."""
        with self._outstanding_lock:
            n = len(self._outstanding)
        with self._pending_lock:
            return n + len(self._pending)

    def stop(self) -> None:
        """Teardown: fail every outstanding / pending listener
        (reference: RdmaChannel.java:788-869)."""
        with self._state_lock:
            if self._state == ChannelState.STOPPED:
                return
            self._transition(ChannelState.STOPPED)
        g, self._m_active_gauge = self._m_active_gauge, None
        if g is not None:
            g.dec()
        err = TransportError("channel stopped")
        with self._pending_lock:
            pending = list(self._pending)
            self._pending.clear()
        for _, listener in pending:
            self._safe_fail(listener, err)
        with self._outstanding_lock:
            outstanding = list(self._outstanding)
            self._outstanding.clear()
        for listener in outstanding:
            self._safe_fail(listener, err)

    # -- budget / pending machinery -----------------------------------------
    def _enqueue(self, post_fn: Callable[[], None], listener: CompletionListener):
        if self._budget.acquire(blocking=False):
            self._track(listener)
            if self._state == ChannelState.STOPPED:
                # raced stop() between _check_usable and _track: its
                # outstanding drain may have run before this op was
                # visible, so nothing would ever fail it — fail it
                # here (a drain that DID see it double-fails, which
                # listeners absorb as first-outcome-wins)
                self._fail(listener, TransportError("channel stopped"))
                self._budget.release()
                return
            self._run_post(post_fn, listener)
        else:
            with self._pending_lock:
                if self._state != ChannelState.STOPPED:
                    self._pending.append((post_fn, listener))
                    return
            # stop() set STOPPED before draining _pending under this
            # same lock: reaching here means the drain already ran and
            # an append would be orphaned on a dead channel forever
            self._fail(listener, TransportError("channel stopped"))

    def _run_post(self, post_fn, listener) -> None:
        try:
            post_fn()
        except BaseException as e:  # posting failed synchronously
            self._error(e)
            self._fail(listener, e)
            self._release_budget()

    def _track(self, listener) -> None:
        with self._outstanding_lock:
            self._outstanding.add(listener)

    def _release_budget(self) -> None:
        """Called after each completion; drains one pending op
        (reference: exhaustCq draining pendingSends)."""
        with self._pending_lock:
            nxt = self._pending.popleft() if self._pending else None
        if nxt is None:
            self._budget.release()
            return
        post_fn, listener = nxt
        self._track(listener)
        self._run_post(post_fn, listener)

    # -- completion plumbing ------------------------------------------------
    def _untrack(self, listener) -> None:
        with self._outstanding_lock:
            self._outstanding.discard(listener)

    def _complete(self, listener: CompletionListener, result) -> None:
        self._untrack(listener)
        try:
            listener.on_success(result)
        except BaseException:
            pass

    def _fail(self, listener: CompletionListener, err: BaseException) -> None:
        self._untrack(listener)
        self._safe_fail(listener, err)

    @staticmethod
    def _safe_fail(listener: CompletionListener, err: BaseException) -> None:
        try:
            listener.on_failure(err)
        except BaseException:
            pass

    def _error(self, err: BaseException) -> None:
        """Flip to sticky ERROR (reference: completion-with-error path,
        RdmaChannel.java:611-637)."""
        with self._state_lock:
            if self._state not in (ChannelState.STOPPED,):
                self._transition(ChannelState.ERROR)

    # -- subclass hooks -----------------------------------------------------
    def _post_rpc(self, frames: List[bytes], listener: CompletionListener) -> None:
        raise NotImplementedError

    def _post_read(
        self,
        locations: List[BlockLocation],
        listener: CompletionListener,
        dest=None,
        on_progress=None,
        ctx=None,
    ) -> None:
        raise NotImplementedError


class BlockStore:
    """Registered-memory domain served by a node: resolves a
    BlockLocation's (mkey, address, length) to bytes — what the NIC does
    for a one-sided READ against an lkey/rkey in the reference."""

    def read_block(self, location: BlockLocation) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def read_blocks(self, locations) -> list:
        """Batched read; stores with a cheaper grouped path override
        this (ArenaManager batches per backing segment)."""
        return [self.read_block(loc) for loc in locations]


class BytesBlockStore(BlockStore):
    """Host-memory block store over one contiguous buffer; ``address``
    is the byte offset within it.  Blocks serve as zero-copy chunk
    views of the backing buffer (the transport sends views
    scatter-gather; the view keeps the buffer alive by refcount)."""

    def __init__(self, data: bytes):
        self._view = memoryview(data)

    def read_block(self, location: BlockLocation):
        end = location.address + location.length
        if location.address < 0 or end > len(self._view):
            raise TransportError(
                f"read [{location.address},{end}) outside store of "
                f"{len(self._view)}B"
            )
        return self._view[location.address : end]
