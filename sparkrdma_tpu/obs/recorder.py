"""Flight recorder: per-plane bounded rings of structured events.

Each plane (transport, reader, decode, tier, qos, faults — the keys of
``obs.events.EVENTS``) owns ONE fixed-capacity ring of events under its
own lock (lock-striped by plane, so a busy transport plane never
contends with reader events).  An event is a fixed-shape tuple
``(t_epoch_s, name, fields)`` — ``fields`` a small flat dict of
scalars.  A full ring drops the OLDEST event and counts the drop
(``obs_events_dropped_total{plane=...}``) — recording never blocks and
never grows.

Dumps are JSON snapshots of every ring plus process identity, written

- automatically on FetchFailed, breaker trip, ledger leak, or wire
  reject (``auto_dump`` — rate-capped so an error storm costs one file
  per interval, not thousands), and
- on demand via the metrics HTTP server's ``/flightrecorder`` endpoint
  or :func:`sparkrdma_tpu.obs.collect.write_dump` at fixture teardown.

``tools/trace_report.py`` renders a dump (or several merged across
processes) as a text waterfall / Chrome trace.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from collections import deque
from typing import Dict, Optional

from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.obs.events import EVENTS

logger = logging.getLogger(__name__)

#: minimum seconds between automatic dumps (an error storm costs one
#: file per interval, not one per failure)
AUTO_DUMP_INTERVAL_S = 1.0


class _Ring:
    """One plane's bounded event ring (deque drops oldest when full)."""

    __slots__ = ("lock", "events", "dropped", "cap")

    def __init__(self, cap: int):
        self.lock = threading.Lock()  # lock-order: 99
        self.events = deque(maxlen=cap)
        self.dropped = 0  # guarded-by: lock
        self.cap = cap


class FlightRecorder:
    """Process-global recorder; ``enabled`` is the one hot-path check
    (the metrics-registry no-op idiom — ``fr_event`` costs an attribute
    read when off)."""

    def __init__(self):
        self.enabled = False
        self._rings: Dict[str, _Ring] = {}
        self._drop_counters: Dict[str, object] = {}
        self._dump_dir = ""
        self._owners = 0
        self._dump_lock = threading.Lock()  # lock-order: 89
        self._last_auto = 0.0   # guarded-by: _dump_lock
        self._dump_seq = 0      # guarded-by: _dump_lock

    # -- lifecycle (owner-counted, like the fault plane) --------------------
    def retain(self, ring_size: int = 4096, dump_dir: str = "") -> None:
        if self._owners == 0:
            self._rings = {p: _Ring(max(int(ring_size), 1)) for p in EVENTS}
            self._drop_counters = {
                p: counter("obs_events_dropped_total", plane=p)
                for p in EVENTS
            }
            # a fresh recorder lifecycle starts with an open rate-cap
            # window (the dump SEQUENCE keeps advancing so filenames
            # from consecutive lifecycles in one process never collide)
            with self._dump_lock:
                self._last_auto = 0.0
        self._owners += 1
        if dump_dir:
            self._dump_dir = dump_dir
        self.enabled = True

    def release(self) -> None:
        self._owners = max(0, self._owners - 1)
        if self._owners == 0:
            self.enabled = False
            self._dump_dir = ""

    # -- recording (any thread) ---------------------------------------------
    def record(self, plane: str, name: str, fields: dict) -> None:
        ring = self._rings.get(plane)
        if ring is None:
            return
        t = time.time()
        with ring.lock:
            full = len(ring.events) == ring.cap
            ring.events.append((t, name, fields))
            if full:
                ring.dropped += 1
        if full:
            # outside the ring lock: the registry's stripe locks rank
            # below the rings in the hierarchy
            self._drop_counters[plane].inc()

    # -- snapshot / dump -----------------------------------------------------
    def snapshot(self) -> dict:
        planes = {}
        for plane, ring in self._rings.items():
            with ring.lock:
                events = [[t, name, fields] for t, name, fields in ring.events]
                dropped = ring.dropped
            planes[plane] = {"dropped": dropped, "events": events}
        return {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "time": time.time(),
            "planes": planes,
        }

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write one JSON dump; ``path`` overrides the configured dump
        directory (in which the filename is pid- and sequence-tagged so
        per-process dumps of one fleet never collide)."""
        if path is None:
            if not self._dump_dir:
                return None
            with self._dump_lock:
                self._dump_seq += 1
                seq = self._dump_seq
            path = os.path.join(
                self._dump_dir,
                f"flightrec-{os.getpid()}-{seq}-{reason}.json",
            )
        snap = self.snapshot()
        snap["reason"] = reason
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(snap, f)
        except OSError:
            logger.exception("flight-recorder dump to %s failed", path)
            return None
        counter("obs_dumps_total", reason=reason).inc()
        return path

    def auto_dump(self, reason: str) -> Optional[str]:
        """Rate-capped automatic dump (failure-path hook sites)."""
        if not self.enabled or not self._dump_dir:
            return None
        now = time.time()
        with self._dump_lock:
            if now - self._last_auto < AUTO_DUMP_INTERVAL_S:
                return None
            self._last_auto = now
        return self.dump(reason)


RECORDER = FlightRecorder()


def fr_event(plane: str, event: str, **fields) -> None:
    """Record one structured event (no-op when the recorder is off).
    ``plane`` and ``event`` must be string literals declared in
    ``obs.events.EVENTS`` — lint rule PY12 enforces it."""
    rec = RECORDER
    if rec.enabled:
        rec.record(plane, event, fields)
