"""Distributed trace context: request-scoped ids that cross the wire.

A :class:`TraceContext` is two 64-bit ids — ``trace_id`` names one
logical read (a reducer task's fetch plan), ``span_id`` one unit of
work within it (a fetch group, a serve, a decode).  The reader stamps a
context on every fetch group; the transport carries it to the serving
node (an optional ``<QQ`` tail on read requests, trace fields on the
fetch-status RPC under wire version 2), so the responder's serve /
tier / credit events join the requester's trace in one merged timeline
(tools/trace_report.py).

Zero-overhead when off, like the metrics registry: ``TRACING.start()``
is one attribute check returning ``None``, and every carrier treats a
``None`` context as "emit nothing" — the wire bytes are identical to a
pre-tracing build (golden-frame pinned).

Id 0 is reserved as "no trace" on the wire; generated ids are pid- and
time-salted so independently-started processes do not collide within a
merged fleet trace.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import NamedTuple, Optional

_ID_MASK = (1 << 64) - 1


class TraceContext(NamedTuple):
    """One (trace, span) identity, carried on the wire as two u64s."""

    trace_id: int
    span_id: int

    def child(self) -> "TraceContext":
        """New span under the same trace."""
        return TraceContext(self.trace_id, _next_id())


_counter = itertools.count(1)
_base = 0


def _next_id() -> int:
    """Unique nonzero 64-bit id: pid + coarse start-time salt in the
    high bits, a process-local counter in the low bits."""
    global _base
    if _base == 0:
        _base = (
            ((os.getpid() & 0xFFFF) << 48)
            | ((int(time.time() * 1000.0) & 0xFFFFFFFF) << 16)
        )
    return ((_base + next(_counter)) & _ID_MASK) or 1


class Tracing:
    """Process-global tracing switch + sampler.

    ``enabled`` is flipped by the manager from conf ``traceEnabled``
    (owner-counted so nested managers in one process compose);
    ``sample_stride`` derives from conf ``traceSampleRate`` — a rate of
    1.0 samples every trace, 0.1 every 10th, 0 none.
    """

    __slots__ = ("enabled", "sample_stride", "_seq", "_owners")

    def __init__(self):
        self.enabled = False
        self.sample_stride = 1
        self._seq = itertools.count()
        self._owners = 0

    def retain(self, sample_rate: float = 1.0) -> None:
        self._owners += 1
        if sample_rate <= 0.0:
            self.sample_stride = 0
        else:
            self.sample_stride = max(1, round(1.0 / min(sample_rate, 1.0)))
        self.enabled = True

    def release(self) -> None:
        self._owners = max(0, self._owners - 1)
        if self._owners == 0:
            self.enabled = False

    def start(self) -> Optional[TraceContext]:
        """Root context for one logical read, or None (off/sampled out)."""
        if not self.enabled:
            return None
        stride = self.sample_stride
        if stride == 0 or next(self._seq) % stride:
            return None
        return TraceContext(_next_id(), _next_id())


TRACING = Tracing()
