"""Flight-recorder event registry: THE single declaration of every
structured event name the recorder may emit, grouped by plane.

Every ``fr_event(plane, name, ...)`` call site in the tree must name a
plane and event declared here — enforced statically by tools/lint.py
rule PY12 (the PY11 conf-drift shape applied to events), so the names
``tools/trace_report.py`` renders can never silently diverge from what
the code emits.  Add the declaration FIRST, then the call site.
"""

#: plane -> tuple of event names (the recorder keeps one bounded ring
#: per plane; see obs/recorder.py)
EVENTS = {
    "transport": (
        "stripe_land",        # one stripe/block landed in its dest row
        "wire_send",          # a read request hit the wire
        "serve_admit",        # serve dequeued + credits granted
        "serve_read",         # blocks resolved from store/tier
        "serve_send",         # response frame handed to the socket
        "version_downgrade",  # connector re-helloed at the peer's version
        "wire_reject",        # wiredbg rejected a frame/header
    ),
    "reader": (
        "fetch_enqueue",      # fetch group queued behind the window
        "fetch_issue",        # fetch group issued to its read group
        "fetch_land",         # fetch group fully landed
        "fetch_retry",        # in-task retry scheduled
        "fetch_fail",         # fetch group failed terminally
        "decode_wait",        # reader blocked on a decode ticket
        "consume_wait",       # reader blocked on the results queue
        "merged_enqueue",     # push mode: one merged span planned
        "merged_fallback",    # merged fetch failed -> provenance re-pulled
    ),
    "decode": (
        "credit_wait",        # decode worker waited for pool credits
        "decode_done",        # one block decoded
        "ticket_steal",       # consumer stole the decode from the pool
    ),
    "tier": (
        "promote",            # block promoted disk -> memory
        "demote",             # block demoted memory -> disk
        "disk_read",          # serve resolved a block from disk tier
        "warm",               # prefetch-hint warm executed
    ),
    "qos": (
        "credit_block",       # admission blocked on the credit broker
    ),
    "faults": (
        "fault_fired",        # injected fault fired at a point
        "breaker_trip",       # circuit breaker CLOSED -> OPEN
        "breaker_probe",      # half-open probe issued
        "ledger_leak",        # resource ledger found leaked resources
    ),
    "state": (
        "transition",         # validated lifecycle state transition
        "illegal",            # transition absent from the declared table
    ),
}


def is_declared(plane: str, event: str) -> bool:
    """True when ``event`` is a declared event of ``plane``."""
    return event in EVENTS.get(plane, ())
