"""Observability plane: distributed trace propagation + flight recorder.

- :mod:`obs.context` — ``TraceContext`` ids stamped on every fetch
  group and carried on the wire (read-request ``<QQ`` tail, RPC trace
  fields) so serve-side spans join the requester's trace.
- :mod:`obs.events` — the single registry of flight-recorder event
  names (lint rule PY12 pins call sites to it).
- :mod:`obs.recorder` — per-plane bounded event rings with counted
  drops, JSON dumps on failure triggers or on demand.
- :mod:`obs.collect` — per-process dump collection + cross-process
  merge for the simfleet/cluster harnesses.

Everything is a no-op while off: ``TRACING.start()`` returns ``None``
and ``fr_event`` is one attribute check — the metrics-registry idiom.
"""

from sparkrdma_tpu.obs.context import TRACING, TraceContext
from sparkrdma_tpu.obs.events import EVENTS
from sparkrdma_tpu.obs.recorder import RECORDER, fr_event

__all__ = [
    "EVENTS",
    "RECORDER",
    "TRACING",
    "TraceContext",
    "fr_event",
]
