"""Fleet-wide observability collection: per-process dump + merge.

Multi-process runs (the simfleet harness, the test cluster fixtures)
each write ONE flight-recorder dump at teardown via
:func:`write_dump`; :func:`merge_dumps` folds any number of per-process
dumps into one merged document whose events carry their origin
``pid``/``host`` — the input ``tools/trace_report.py`` renders as a
single cross-process waterfall (requester and server spans of one
``trace_id`` interleaved on the shared epoch clock).
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

from sparkrdma_tpu.obs.recorder import RECORDER


def write_dump(path: str, reason: str = "collect") -> Optional[str]:
    """Dump THIS process's recorder to ``path`` (fixture teardown /
    simfleet close hook).  None when the recorder is off."""
    if not RECORDER.enabled:
        return None
    return RECORDER.dump(reason, path=path)


def load_dump(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def merge_dumps(paths: Sequence[str]) -> dict:
    """Fold per-process dumps into one merged trace document."""
    processes: List[dict] = []
    for p in sorted(paths):
        processes.append(load_dump(p))
    return {"merged": True, "processes": processes}


def merged_events(doc: dict) -> List[dict]:
    """Flatten a dump or merged document into one time-sorted event
    list; each event dict carries t / plane / name / fields / pid /
    host.  This is the normal form trace_report renders from."""
    procs = doc["processes"] if doc.get("merged") else [doc]
    out: List[dict] = []
    for proc in procs:
        pid, host = proc.get("pid"), proc.get("host")
        for plane, rec in proc.get("planes", {}).items():
            for t, name, fields in rec.get("events", []):
                out.append({
                    "t": t, "plane": plane, "name": name,
                    "fields": fields or {}, "pid": pid, "host": host,
                })
    out.sort(key=lambda e: e["t"])
    return out
