"""sparkrdma_tpu — a TPU-native shuffle framework.

A ground-up re-design of the capability set of SparkRDMA (the Mellanox
RDMA shuffle plugin for Apache Spark, see ``/root/reference``): a pluggable
shuffle manager whose data plane moves map-output blocks through
registered, zero-copy memory instead of the TCP/Netty stack.

Here the "NIC" is the TPU interconnect (ICI): map outputs are serialized
into HBM-resident arenas and exchanged between chips with XLA collectives
(``jax.lax.all_to_all`` / ``ppermute``) driven by a tile-round scheduler,
while a driver-side control plane (hello/announce/publish/fetch-status)
tracks block locations exactly like the reference's driver-mediated
metadata path (reference: RdmaShuffleManager.scala:38-388).

Layer map (mirrors SURVEY.md §1):

    L1  api       TpuShuffleManager        (shuffle/manager.py)
    L2  data      writer/reader/resolver   (shuffle/)
    L3  control   rpc messages + driver    (rpc/, control/)
    L4  transport node/channel/loopback    (transport/), exchange (parallel/)
    L5  device    arenas, pallas kernels   (memory/, ops/)
"""

# jax compatibility: every collective program here builds on
# ``jax.shard_map``, which older jax releases (< 0.4.38, e.g. the
# 0.4.37 this image ships) only expose as
# ``jax.experimental.shard_map.shard_map``.  Bridge it once at package
# import so all call sites (and the test fixtures that mirror them)
# keep the one modern spelling.
import jax as _jax

if not hasattr(_jax, "shard_map"):  # pragma: no cover - version-dependent
    try:
        import functools as _functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @_functools.wraps(_shard_map)
        def _shard_map_compat(f, *args, **kw):
            # the modern kwarg spelling on the experimental signature
            if "check_vma" in kw:
                kw["check_rep"] = kw.pop("check_vma")
            return _shard_map(f, *args, **kw)

        _jax.shard_map = _shard_map_compat
    except ImportError:
        pass  # truly ancient jax: call sites fail loudly as before

if not hasattr(_jax.lax, "axis_size"):  # pragma: no cover
    def _axis_size(axis_name):
        """jax<0.4.38 spelling: the static mesh-axis size lives on the
        core axis frame (older frames ARE the size)."""
        frame = _jax.core.axis_frame(axis_name)
        return getattr(frame, "size", frame)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax.lax, "pcast"):  # pragma: no cover
    def _pcast(x, axis_name=None, *, to=None):
        """jax<0.5 has no varying/unvarying mesh-axis typing (vma), so
        the cast that converts between them is the identity there."""
        del axis_name, to
        return x

    _jax.lax.pcast = _pcast

try:  # pragma: no cover - version-dependent
    _jax.ShapeDtypeStruct((1,), "uint8", vma=frozenset())
except TypeError:
    _OrigSDS = _jax.ShapeDtypeStruct

    class _ShapeDtypeStructCompat(_OrigSDS):
        """Pre-vma jax: accept and drop the varying-mesh-axes kwarg
        (no vma typing exists to propagate it to)."""

        def __init__(self, shape, dtype, **kw):
            kw.pop("vma", None)
            super().__init__(shape, dtype, **kw)

    _jax.ShapeDtypeStruct = _ShapeDtypeStructCompat
except BaseException:
    pass

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.utils.columns import ColumnBatch
from sparkrdma_tpu.utils.types import (
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

__version__ = "0.1.0"

__all__ = [
    "TpuShuffleConf",
    "ColumnBatch",
    "BlockLocation",
    "BlockManagerId",
    "ShuffleManagerId",
    "__version__",
]
