"""sparkrdma_tpu — a TPU-native shuffle framework.

A ground-up re-design of the capability set of SparkRDMA (the Mellanox
RDMA shuffle plugin for Apache Spark, see ``/root/reference``): a pluggable
shuffle manager whose data plane moves map-output blocks through
registered, zero-copy memory instead of the TCP/Netty stack.

Here the "NIC" is the TPU interconnect (ICI): map outputs are serialized
into HBM-resident arenas and exchanged between chips with XLA collectives
(``jax.lax.all_to_all`` / ``ppermute``) driven by a tile-round scheduler,
while a driver-side control plane (hello/announce/publish/fetch-status)
tracks block locations exactly like the reference's driver-mediated
metadata path (reference: RdmaShuffleManager.scala:38-388).

Layer map (mirrors SURVEY.md §1):

    L1  api       TpuShuffleManager        (shuffle/manager.py)
    L2  data      writer/reader/resolver   (shuffle/)
    L3  control   rpc messages + driver    (rpc/, control/)
    L4  transport node/channel/loopback    (transport/), exchange (parallel/)
    L5  device    arenas, pallas kernels   (memory/, ops/)
"""

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.utils.columns import ColumnBatch
from sparkrdma_tpu.utils.types import (
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

__version__ = "0.1.0"

__all__ = [
    "TpuShuffleConf",
    "ColumnBatch",
    "BlockLocation",
    "BlockManagerId",
    "ShuffleManagerId",
    "__version__",
]
