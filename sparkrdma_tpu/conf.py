"""Typed, range-validated configuration for the TPU shuffle framework.

Analog of the reference's RdmaShuffleConf (RdmaShuffleConf.scala:34-126):
namespaced keys under ``spark.shuffle.tpu.*`` with clamped int and
byte-size parsers falling back to defaults.  Every knob from the
reference's `spark.shuffle.rdma.*` namespace has an equivalent here
(SURVEY.md §2 row "Shuffle conf"); knobs that only make sense for
ibverbs (recv WR sizing, ODP) map onto their ICI/arena analogs.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Mapping, Optional

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kmgt]?)b?\s*$", re.IGNORECASE)
_SIZE_MULT = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_byte_size(value: object) -> int:
    """Parse '8m', '256k', '10g', 4096 → bytes."""
    if isinstance(value, (int, float)):
        return int(value)
    m = _SIZE_RE.match(str(value))
    if not m:
        raise ValueError(f"cannot parse byte size: {value!r}")
    return int(float(m.group(1)) * _SIZE_MULT[m.group(2).lower()])


def parse_time_ms(value: object) -> int:
    """Parse '20s', '50ms', '2s', 120 (seconds) → milliseconds."""
    if isinstance(value, (int, float)):
        return int(value) * 1000
    s = str(value).strip().lower()
    if s.endswith("ms"):
        return int(float(s[:-2]))
    if s.endswith("s"):
        return int(float(s[:-1]) * 1000)
    return int(float(s)) * 1000


def host_core_census() -> int:
    """Cores actually runnable by THIS process.

    ``os.cpu_count()`` reports the machine; a containerized or
    ``taskset``-pinned executor may be allowed far fewer.  Prefer the
    scheduler-affinity mask (which cgroup cpusets and
    ``sched_setaffinity`` both shrink) and fall back to the machine
    count where the platform has no affinity API."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


_FORCED_DEVICES_RE = re.compile(
    r"--xla_force_host_platform_device_count=(\d+)"
)


def device_census() -> int:
    """Accelerator devices THIS process's jax backend will expose — the
    ``host_core_census`` analog every multi-device default keys off.

    Resolution order: when the process is pinned to the cpu backend
    (``JAX_PLATFORMS``/``JAX_PLATFORM_NAME``), trust an
    ``XLA_FLAGS --xla_force_host_platform_device_count=N`` forcing —
    readable WITHOUT initializing jax, so conf defaults never pin the
    backend choice for the whole process.  Otherwise ask
    ``jax.device_count()`` (authoritative on real TPU/GPU hosts; the
    forced-count flag only applies to the cpu platform, so it must not
    be trusted there).  Answers 1 when jax is unavailable — a 1-device
    host can then never silently gate (or fake-pass) a
    multi-device-only default."""
    platform = os.getenv(
        "JAX_PLATFORMS", os.getenv("JAX_PLATFORM_NAME", "")
    ).strip().lower()
    if platform == "cpu":
        m = _FORCED_DEVICES_RE.search(os.getenv("XLA_FLAGS", ""))
        if m:
            return max(1, int(m.group(1)))
    try:
        import jax

        return max(1, jax.device_count())
    except Exception:
        return 1


class TpuShuffleConf:
    """Config accessor over a plain dict of ``spark.shuffle.tpu.*`` keys.

    Each accessor clamps to a [min, max] range and falls back to a default
    on missing/garbage values, like the reference's getRdmaConfIntInRange /
    getConfBytesInRange (RdmaShuffleConf.scala:36-47).
    """

    PREFIX = "spark.shuffle.tpu."
    LEGACY_PREFIX = "spark.shuffle.rdma."
    # reference knobs (RdmaShuffleConf.scala:34-126) accepted verbatim
    # under the legacy namespace; names that map onto a different TPU
    # analog are translated, the rest alias one-to-one.  An explicit
    # spark.shuffle.tpu.* key always wins over its legacy alias.
    LEGACY_RENAMES = {
        "useOdp": "lazyStaging",          # on-demand registration analog
        # RdmaNode's cpuList pinned the completion-vector THREADS, not
        # devices — it maps onto the dispatcher-thread affinity knob,
        # keeping deviceList free for mesh-device selection
        "cpuList": "dispatcherCpuList",
        # the reference's connect-attempt knob maps onto the jittered
        # retry policy (connectRetries + connectBackoffMs)
        "maxConnectionAttempts": "connectRetries",
    }

    def __init__(self, conf: Optional[Mapping[str, object]] = None):
        self._conf: Dict[str, object] = dict(conf or {})
        # legacy namespace support: a reference user's existing
        # spark.shuffle.rdma.* settings apply unchanged
        for key, value in list(self._conf.items()):
            if not key.startswith(self.LEGACY_PREFIX):
                continue
            short = key[len(self.LEGACY_PREFIX):]
            mapped = self.LEGACY_RENAMES.get(short, short)
            new_key = self.PREFIX + mapped
            self._conf.setdefault(new_key, value)

    # -- raw access ---------------------------------------------------------
    def get(self, short_key: str, default=None):
        return self._conf.get(self.PREFIX + short_key, default)

    def set(self, short_key: str, value: object) -> "TpuShuffleConf":
        self._conf[self.PREFIX + short_key] = value
        return self

    def _int_in_range(self, key: str, default: int, lo: int, hi: int) -> int:
        raw = self.get(key)
        if raw is None:
            return default
        try:
            v = int(raw)
        except (TypeError, ValueError):
            return default
        return max(lo, min(hi, v))

    def _bytes_in_range(self, key: str, default: int, lo: int, hi: int) -> int:
        raw = self.get(key)
        if raw is None:
            return default
        try:
            v = parse_byte_size(raw)
        except ValueError:
            return default
        return max(lo, min(hi, v))

    def _bool(self, key: str, default: bool) -> bool:
        raw = self.get(key)
        if raw is None:
            return default
        if isinstance(raw, bool):
            return raw
        return str(raw).strip().lower() in ("1", "true", "yes", "on")

    def _time_ms(self, key: str, default_ms: int) -> int:
        raw = self.get(key)
        if raw is None:
            return default_ms
        try:
            return parse_time_ms(raw)
        except ValueError:
            return default_ms

    def _float_in_range(self, key: str, default: float, lo: float,
                        hi: float) -> float:
        raw = self.get(key)
        if raw is None:
            return default
        try:
            v = float(raw)
        except (TypeError, ValueError):
            return default
        return max(lo, min(hi, v))

    # -- core census (every cpu_count-derived default reads this) ----------
    @property
    def core_census(self) -> int:
        """The core count that parallelism defaults key off.

        Resolution order: an explicit ``coreCensus`` setting wins
        (> 0); else a ``dispatcherCpuList`` pin implies the executor
        will run on that many cores; else the process affinity mask
        (``host_core_census``), NOT ``os.cpu_count()`` — a CPU-pinned
        containerized executor sees the machine's count but can only
        run on its mask, and sizing decode/serve/spin defaults off the
        machine count oversubscribes the pin (the bug this key fixes).
        Every conf default that used to read ``os.cpu_count()``
        (``decodeThreads``, ``bulkPipelineWindows``,
        ``transportPollSpinUs``, ``tierPrefetch``,
        ``transportNumStripes``, ``transportServeThreads``) now reads
        this."""
        explicit = self._int_in_range("coreCensus", 0, 0, 4096)
        if explicit > 0:
            return explicit
        if self.dispatcher_cpu_list.strip():
            machine = os.cpu_count() or 1
            pinned = self.parse_dispatcher_cpu_list(machine)
            # _parse_index_list answers all-cores for garbage specs;
            # a full-machine answer is not a pin, fall through to the
            # affinity mask
            if pinned and len(pinned) < machine:
                return len(pinned)
        return host_core_census()

    # -- device census (every device_count-derived default reads this) ------
    @property
    def device_census(self) -> int:
        """The device count that multi-device defaults key off
        (``deviceExchangeEnabled``, bench host notes).  An explicit
        ``deviceCensus`` setting wins (> 0); else the module-level
        :func:`device_census` (XLA_FLAGS forcing on a cpu-pinned
        process, ``jax.device_count()`` otherwise) — NOT a hardcoded
        mesh size, so a 1-device host can never silently gate (or
        fake-pass) a multi-device-only path."""
        explicit = self._int_in_range("deviceCensus", 0, 0, 1 << 16)
        if explicit > 0:
            return explicit
        return device_census()

    # -- transport / control-plane queues (reference: recv/sendQueueDepth) --
    @property
    def recv_queue_depth(self) -> int:
        return self._int_in_range("recvQueueDepth", 1024, 256, 65535)

    @property
    def send_queue_depth(self) -> int:
        return self._int_in_range("sendQueueDepth", 4096, 256, 65535)

    @property
    def recv_wr_size(self) -> int:
        """Max size of one control-plane message segment (reference: 4 KiB
        registered recv buffers, RdmaShuffleConf recvWrSize)."""
        return self._bytes_in_range("recvWrSize", 4096, 2048, 1 << 20)

    @property
    def sw_flow_control(self) -> bool:
        """Receiver-credit flow control on the control plane (reference:
        credit reports via RDMA_WRITE_WITH_IMM, RdmaChannel.java:508-520)."""
        return self._bool("swFlowControl", True)

    @property
    def trace(self) -> bool:
        """Enable span tracing (chrome://tracing JSON via Tracer.dump)."""
        return self._bool("trace", False)

    @property
    def trace_path(self) -> str:
        """Where manager.stop() dumps the collected trace."""
        return str(self.get("tracePath", "sparkrdma_tpu_trace.json"))

    @property
    def compress(self) -> bool:
        """Compress serialized shuffle blocks (reference: Spark codec
        stream wrapping, RdmaShuffleReader.scala:51-58)."""
        return self._bool("compress", False)

    @property
    def compress_codec(self) -> str:
        return str(self.get("compressCodec", "zlib"))

    @property
    def serializer_name(self) -> str:
        """Record serializer: ``pickle`` (default; arbitrary objects) or
        ``columnar`` (fixed-width key/value columns, the unsafe-row
        analog — the record plane's fast path)."""
        return str(self.get("serializer", "")).lower()

    @property
    def lazy_staging(self) -> bool:
        """ODP analog (reference: useOdp, RdmaShuffleConf.scala:68-83):
        keep committed map output in host memory and stage to HBM on
        demand at exchange time, instead of eagerly at commit."""
        return self._bool("lazyStaging", False)

    @property
    def compress_frame_records(self) -> int:
        """Records per compression frame (CompressedSerializer): one
        frame is the unit of decode parallelism on the reduce side AND
        the unit the 4 GiB frame-length field bounds — lower it when
        individual records are huge (a FrameTooLargeError names this
        knob)."""
        return self._int_in_range(
            "compressFrameRecords", 65536, 1, 1 << 24
        )

    @property
    def decode_threads(self) -> int:
        """Worker threads on the reduce-side decode pool
        (shuffle/decode.py): blocks deserialize/decompress on workers
        AS STRIPES LAND, overlapping fetch, decode and consumption.
        0 keeps the legacy serial decode on the task thread.  Default:
        ``min(4, cpus)`` on multi-core hosts; 0 on a single-core host
        (decode workers would only timeslice against the task thread —
        the ``bulkPipelineWindows`` convention)."""
        ncpu = self.core_census
        return self._int_in_range(
            "decodeThreads", min(4, ncpu) if ncpu > 1 else 0, 0, 64
        )

    @property
    def decode_ahead_bytes(self) -> int:
        """Byte-credit budget of the decode pool: the total encoded
        bytes of blocks decoding or decoded-but-not-yet-consumed is
        capped here, bounding how far decode runs ahead of the task
        thread (the maxBytesInFlight analog for the decode stage).  A
        single block larger than the whole budget clamps to it and
        decodes alone instead of deadlocking."""
        return self._bytes_in_range(
            "decodeAheadBytes", 32 << 20, 64 << 10, 1 << 40
        )

    @property
    def shuffle_spill_record_threshold(self) -> int:
        """Writer spill trigger: when a map task holds this many
        buffered records, serialize current buckets to a spill file and
        release the memory (the role Spark's sort-shuffle spill plays
        inside the writers the reference wraps,
        RdmaWrapperShuffleWriter.scala:85-101).  0 disables spilling."""
        return self._int_in_range("shuffleSpillRecordThreshold", 0, 0, 1 << 31)

    @property
    def spill_dir(self) -> str:
        """Directory for writer spill files and file-backed commits."""
        import tempfile

        return str(self.get("spillDir", tempfile.gettempdir()))

    @property
    def file_backed_commit_bytes(self) -> int:
        """Commit map outputs at or above this size to an mmapped file
        segment instead of memory (the RdmaMappedFile path,
        RdmaMappedFile.java:76-199) — the larger-than-arena escape
        hatch.  0 disables (all commits stay in memory/HBM)."""
        return self._bytes_in_range("fileBackedCommitBytes", 0, 0, 1 << 44)

    # -- memory tiering / out-of-core prefetch (memory/tier.py) -------------
    @property
    def tier_hot_bytes(self) -> int:
        """Byte budget of the tiered block store's HOT tier: promoted
        blocks of file-backed map outputs live in pooled staging rows
        up to this total; promotion past it demotes the LRU unpinned
        blocks back to their cold (on-disk) tier.  The serve path never
        fails on a full hot tier — a block that cannot be promoted is
        served straight from disk.  0 = unbounded (every touched block
        stays hot — the pre-tier behavior for working sets that fit)."""
        return self._bytes_in_range("tierHotBytes", 256 << 20, 0, 1 << 44)

    @property
    def tier_prefetch(self) -> bool:
        """Predictive promotion into the hot tier: serve-side
        sequential readahead plus reader-sent PrefetchHintMsg warming
        (the RdmaMappedFile ODP-prefetch sweep, RdmaMappedFile.java:
        158-168, re-aimed at the disk tier).  ``off`` keeps the tier a
        plain demand cache — every cold block pays its disk read on
        the serve path (the A/B the out-of-core bench measures).
        Default: enabled on multi-core hosts; on a single core the
        warm work only timeslices against the serves it is meant to
        hide (measured net-negative there — the ``decodeThreads`` /
        ``bulkPipelineWindows`` single-core-fallback precedent).  An
        explicit setting always wins."""
        return self._bool("tierPrefetch", self.core_census > 1)

    @property
    def tier_prefetch_blocks(self) -> int:
        """Serve-side readahead depth: a (promoting) read of block i
        schedules async promotion of blocks i+1..i+this of the same
        map output through the serve pool — the request-stream signal
        (shuffle reads are near-sequential per segment)."""
        return self._int_in_range("tierPrefetchBlocks", 2, 0, 64)

    @property
    def tier_hint_blocks(self) -> int:
        """Reader-side prefetch-hint depth: before issuing a grouped
        fetch, the reader sends the serving peer a PrefetchHintMsg
        listing up to this many upcoming block locations from its
        fetch plan, so the responder warms them through its serve-pool
        credits before the read RPCs arrive.  0 disables hints."""
        return self._int_in_range("tierHintBlocks", 16, 0, 4096)

    # -- transport striping / scatter-gather / read serving -----------------
    @property
    def transport_num_stripes(self) -> int:
        """Data channels per peer for striped block reads (the channel
        group's bulk lanes).  Block reads larger than
        ``transportStripeThreshold`` are chunked round-robin across this
        many dedicated READ channels and reassembled zero-copy into one
        pooled destination row; small reads and RPCs keep their own
        channel so metadata never queues behind bulk bytes (the
        reference's RPC vs RDMA_READ channel split, RdmaChannel.java:41,
        extended with fabric-lib-style striping).  1 disables striping
        (single data channel per peer)."""
        return self._int_in_range(
            "transportNumStripes", min(4, self.core_census), 1, 16
        )

    @property
    def transport_stripe_threshold(self) -> int:
        """Block reads strictly larger than this are striped across the
        peer's data channels; smaller reads ride the dedicated
        small-read channel whole."""
        return self._bytes_in_range(
            "transportStripeThreshold", 512 << 10, 64 << 10, 1 << 30
        )

    @property
    def transport_scatter_gather(self) -> bool:
        """Scatter-gather socket I/O on the TCP data path: frames go
        out as ``sendmsg`` iovecs (header + length prefixes + block
        views, no concatenation copy) and read responses land via
        ``recv_into`` pre-sized pooled/destination buffers.  ``off``
        restores the pre-striping concat+``sendall`` wire path (same
        framing — the two interoperate) for A/B measurement."""
        return self._bool("transportScatterGather", True)

    @property
    def transport_async_dispatcher(self) -> bool:
        """Completion-driven transport core (transport/dispatcher.py):
        one ``selectors`` event-loop thread per node owns every TCP
        transport socket in non-blocking mode — sends post as
        descriptors to a submission queue, receives run as partial
        ``recv_into``/``sendmsg`` continuations, and batched completion
        events dispatch to the striped/decode callbacks (the fabric-lib
        / RAMC submission-queue + completion-queue idiom).  Thread
        count per node drops from O(peers × stripes) reader threads to
        O(1).  ``off`` restores the legacy thread-per-lane blocking
        path for A/B and bit-exactness — the two speak the same wire
        format and interoperate."""
        return self._bool("transportAsyncDispatcher", True)

    @property
    def transport_socket_buffer_bytes(self) -> int:
        """Explicit SO_SNDBUF/SO_RCVBUF on async-dispatcher sockets
        (the registered-ring-size analog of the RDMA QP); the kernel
        doubles the requested value and caps it at
        ``net.core.{w,r}mem_max``.  ``0`` — the default — keeps kernel
        autotuning: pinning at 4 MiB was A/B'd ~15% SLOWER than
        autotune on the loopback bench (setting SO_RCVBUF freezes the
        buffer where autotune keeps growing it with the BDP), so the
        knob exists for real fabrics with known ring budgets, not as a
        default."""
        return self._bytes_in_range(
            "transportSocketBufferBytes", 0, 0, 1 << 30
        )

    @property
    def transport_recv_coalesce_bytes(self) -> int:
        """Receive-wakeup coalescing on the async dispatcher (the
        completion-moderation analog of NIC interrupt coalescing):
        while a channel is mid-way through a large response body the
        loop sets ``SO_RCVLOWAT`` to this value, so ``epoll`` wakes it
        once per ~this many queued bytes instead of per arriving
        skb — fewer loop iterations and GIL round-trips per MiB.
        Headers and body tails drop the watermark back to 1 byte, and
        EOF/errors always wake regardless (kernel semantics), so
        dead-peer detection is unaffected.  ``0`` disables."""
        return self._bytes_in_range(
            "transportRecvCoalesceBytes", 1 << 20, 0, 64 << 20
        )

    @property
    def transport_stream_offload_bytes(self) -> int:
        """Lane streaming on the async dispatcher: when a bulk
        channel has at least this many response bytes outstanding, its
        whole recv machine moves to a completion-pool worker doing
        BLOCKING ``recv`` with inline completion delivery (the
        CQ-poller vs completion-worker split of fabric-lib) until the
        lane drains idle, then returns to the event loop.  A busy lane
        gets the threaded reader's exact syscall-and-delivery shape —
        one handoff per burst — while idle lanes cost no thread at
        all; at most a bounded number of lanes stream at a time and the
        rest stay on-loop.  ``0`` disables (every landing stays on the
        loop)."""
        return self._bytes_in_range(
            "transportStreamOffloadBytes", 1 << 20, 0, 1 << 40
        )

    @property
    def transport_poll_spin_us(self) -> int:
        """Adaptive busy-poll window (µs) on the async dispatcher loop:
        after an iteration that did real work the loop re-polls the
        selector non-blocking for this long before re-arming the
        blocking ``select`` — the poll-mode progress engine of the
        RDMA designs this core follows.  Back-to-back events (an RPC
        pong chased by the next ping, successive chunks of a draining
        stripe) are serviced at ``epoll_wait(0)`` cost with no
        sleep/wake transition.  ``0`` disables (always block) — the
        default on single-core hosts, where A/B showed the spin steals
        the very core the peer and the serve workers need (RPC p50
        DOUBLED spinning there); the decodeThreads/bulkPipelineWindows
        single-core-fallback precedent."""
        return self._int_in_range(
            "transportPollSpinUs",
            40 if self.core_census > 1 else 0, 0, 10000,
        )

    @property
    def transport_send_backlog_bytes(self) -> int:
        """Per-channel write backpressure on the async dispatcher: when
        a channel's queued-but-unsent response bytes exceed this, the
        loop stops READING that socket (new requests queue in the
        kernel and eventually in the requester's TCP window) until the
        backlog drains below half — so a requester that never drains
        its responses throttles itself, not the node."""
        return self._bytes_in_range(
            "transportSendBacklogBytes", 16 << 20, 64 << 10, 1 << 40
        )

    @property
    def transport_max_cached_channels(self) -> int:
        """Cap on the node's active-channel cache (``Node._active`` —
        the RdmaNode channel-cache lineage, bounded): when a connect
        would push the cache past this many live channels, the
        idle-coldest cached channels (LRU by last use; never one with
        in-flight ops) are evicted and their sockets closed.
        ``get_channel`` transparently reconnects an evicted key on next
        use, so at datacenter fan-out a node pays O(cap) sockets, not
        O(peers × stripes) — the RDMAvisor bounded-channel design.
        ``0`` disables the cap entirely (the pre-fabric unbounded
        behavior, kept for A/B)."""
        return self._int_in_range(
            "transportMaxCachedChannels", 512, 0, 1 << 20
        )

    @property
    def transport_lane_pool_size(self) -> int:
        """Fixed per-node budget of borrowable data lanes: a striped
        read borrows up to ``transportNumStripes`` lanes from this pool
        for its duration and returns them at completion, so concurrent
        stripe parallelism across ALL peers is bounded here instead of
        costing ``transportNumStripes`` dedicated sockets per peer.
        When the pool is empty a read falls back to the peer's
        dedicated small-read lane, unstriped (correct, just narrower).
        ``0`` disables the budget (every read stripes fully — the
        pre-fabric behavior, kept for A/B)."""
        return self._int_in_range("transportLanePoolSize", 32, 0, 4096)

    @property
    def transport_serve_threads(self) -> int:
        """Worker threads on the node's read-serve pool (one-sided READ
        service).  Serving runs off the channel reader loops so one
        large serve never head-of-line-blocks completions on its
        channel."""
        return self._int_in_range(
            "transportServeThreads", min(4, self.core_census), 1, 64
        )

    @property
    def transport_serve_credit_bytes(self) -> int:
        """Byte-credit budget of the read-serve pool: the total
        requested bytes of serves running concurrently is capped here,
        so a slow reducer draining many bulk responses cannot pin
        unbounded server memory (responder-side flow control; the
        recv-WR credit scheme's serve-side analog)."""
        return self._bytes_in_range(
            "transportServeCreditBytes", 64 << 20, 1 << 20, 1 << 40
        )

    # -- memory / arenas (reference: maxBufferAllocationSize, ODP) ----------
    @property
    def max_buffer_allocation_size(self) -> int:
        return self._bytes_in_range("maxBufferAllocationSize", 10 << 30, 0, 1 << 44)

    @property
    def max_agg_prealloc(self) -> int:
        return self._bytes_in_range("maxAggPrealloc", 0, 0, 1 << 40)

    @property
    def max_agg_block(self) -> int:
        """Cap on one aggregated fetch tile (reference: maxAggBlock 2m)."""
        return self._bytes_in_range("maxAggBlock", 2 << 20, 128 << 10, 1 << 30)

    # -- data plane block sizing -------------------------------------------
    @property
    def shuffle_write_block_size(self) -> int:
        """Arena segment granularity on the write side (reference: 8m
        mmap chunks, shuffleWriteBlockSize)."""
        return self._bytes_in_range("shuffleWriteBlockSize", 8 << 20, 64 << 10, 1 << 30)

    @property
    def shuffle_read_block_size(self) -> int:
        """Target size of one grouped fetch (reference: 256k)."""
        return self._bytes_in_range(
            "shuffleReadBlockSize", 256 << 10, 16 << 10, 1 << 30
        )

    @property
    def max_bytes_in_flight(self) -> int:
        """Reader-side in-flight window (reference: 1m)."""
        return self._bytes_in_range("maxBytesInFlight", 1 << 20, 128 << 10, 1 << 40)

    # -- exchange engine (TPU-specific; no reference analog) ----------------
    @property
    def exchange_tile_bytes(self) -> int:
        """Payload bytes per chip per all_to_all tile round.  The SPMD
        analog of shuffle_read_block_size: every chip contributes exactly
        one padded tile of this size per round."""
        return self._bytes_in_range(
            "exchangeTileBytes", 4 << 20, 64 << 10, 1 << 30
        )

    @property
    def read_plane(self) -> str:
        """Bulk fetch plane: ``host`` (loopback/TCP one-sided byte
        reads), ``windowed`` (the unified device plane — reducers issue
        reads through get_reader and the bytes ride driver-planned
        window collectives, reactive AND multi-process; SURVEY §7
        "one-sided READ pull model" inversion), or ``bulk``
        (bulk-synchronous whole-shuffle exchange via BulkExchangeReader
        — shuffle/bulk.py).  ``collective`` (the in-process
        opportunistic coordinator, tests/collective_read_fixture.py) is a
        test fixture superseded by ``windowed``."""
        return str(self.get("readPlane", "host")).lower()

    @property
    def direct_io(self) -> str:
        """Disk write mode for spills and file-backed commits:
        ``auto`` (O_DIRECT when the spill directory supports it —
        virtualized hosts writeback-throttle buffered writes to a
        fraction of device bandwidth), ``on`` (force, still falls back
        per-file if the open fails), or ``off`` (buffered)."""
        v = str(self.get("directIO", "auto")).lower()
        return v if v in ("auto", "on", "off") else "auto"

    @property
    def spill_partition_files(self) -> int:
        """Spills write one file PER PARTITION up to this many
        partitions (the zero-copy commit: each spill file registers
        directly as the shuffle file, no consolidation rewrite).
        Shuffles with more partitions use the legacy single spill file
        to bound open descriptors; 0 disables the per-partition
        layout."""
        return self._int_in_range("spillPartitionFiles", 64, 0, 4096)

    @property
    def bulk_window_maps(self) -> int:
        """Bulk mode's incremental-plan window: the driver cuts an
        exchange plan every time this many NEW maps have published and
        filled (the last window takes the remainder), so reducers start
        moving bytes while stragglers still write — the collective
        analog of the reference's windowed fetch overlap
        (RdmaShuffleFetcherIterator.scala:241-251 +
        RdmaMapTaskOutput.scala:41-44 partial fills).  0 (default)
        keeps the single all-maps barrier."""
        return self._int_in_range("bulkWindowMaps", 0, 0, 1 << 20)

    @property
    def bulk_pipeline_windows(self) -> bool:
        """Double-buffer the windowed plane: while window N's
        collective runs, window N+1's plan barrier AND stream assembly
        proceed on a background stage into a second pooled source row
        (shuffle/bulk.py).  Abort/poison semantics are unchanged and
        output is bit-identical to the serial loop.  Default: enabled
        on multi-core hosts; a single-core host cannot overlap — the
        stage thread would only timeslice against the collective — so
        it falls back to the serial loop there.  An explicit setting
        always wins."""
        return self._bool(
            "bulkPipelineWindows", self.core_census > 1
        )

    @property
    def bulk_barrier_timeout_ms(self) -> int:
        """How long an in-process bulk-session contributor waits for
        the other participating executors before failing the
        exchange."""
        return self._time_ms("bulkBarrierTimeout", 120_000)

    @property
    def device_arena_bytes(self) -> int:
        """Capacity of each executor's persistent HBM arena on the
        collective plane (all arenas share one capacity so the pack
        program compiles once)."""
        return self._bytes_in_range("deviceArenaBytes", 64 << 20,
                                    1 << 20, 1 << 40)

    @property
    def exchange_flush_ms(self) -> int:
        """How long the exchange coordinator batches pending fetches
        before running a collective round."""
        return self._time_ms("exchangeFlush", 2)

    @property
    def exchange_max_rounds_in_flight(self) -> int:
        """Bounded outstanding exchange rounds (maxBytesInFlight analog
        for the collective data plane)."""
        return self._int_in_range("exchangeMaxRoundsInFlight", 2, 1, 64)

    @property
    def device_exchange_enabled(self) -> bool:
        """Device-native exchange data path
        (``TileExchange.exchange_padded``): staged source rows are
        assembled ONCE into pooled padded device-layout buffers and
        ride the mesh as device arrays — on-device tile staging
        (reshape + index, no per-round host matrix fills) and zero
        intermediate ``bytes`` materialization between the map-output
        store and HBM.  Output is bit-identical to the host-staged
        path.  Default: enabled on ≥2-device hosts; a 1-device mesh
        has no collective to win (the ``decodeThreads`` convention).
        An explicit setting always wins."""
        return self._bool("deviceExchangeEnabled", self.device_census > 1)

    @property
    def device_exchange_window_rounds(self) -> int:
        """Bounded in-flight window of DEVICE exchange tile rounds:
        round k's collective dispatches while round k-1's landed rows
        are collected (and, on the windowed plane, handed to the
        decode pool) — the collective/decode overlap.  0 runs the
        whole exchange as ONE fused program instead (zero-copy result
        views, no per-round collect), trading overlap for the lowest
        total copy cost; the windowed plane wants rounds, bulk batch
        readers want the fused shot."""
        return self._int_in_range("deviceExchangeWindowRounds", 2, 0, 64)

    @property
    def device_bucketize_enabled(self) -> bool:
        """On-device partition prep before the exchange
        (``ops.partition.bucketize_segments``): partition fan-out runs
        as a jit'd bucketize+counts+segment-offsets kernel so the
        collective moves already-bucketed contiguous segments.  Same
        ≥2-device default as ``deviceExchangeEnabled``."""
        return self._bool("deviceBucketizeEnabled", self.device_census > 1)

    @property
    def verify_exchange_integrity(self) -> bool:
        """Opt-in end-to-end CRC of every (src, dst) exchanged stream
        (ExchangeIntegrityError on mismatch).  Costs O(payload) host
        time; healthy ICI links already carry hardware CRC."""
        return self._bool("verifyExchangeIntegrity", False)

    # -- multi-tenant QoS (sparkrdma_tpu/qos/) ------------------------------
    @property
    def qos_enabled(self) -> bool:
        """Multi-tenant QoS policy (qos/): the byte-credit pools
        (serve, decode, reader in-flight window, tier hot budget)
        acquire through weighted max-min credit brokers, the serve
        queue and lane pool honor priority classes, and admission
        control enforces per-tenant quotas.  Off by default — the
        brokers then compile down to the existing pools (plain FIFO
        credits, unclassed queues) for A/B."""
        return self._bool("qosEnabled", False)

    @property
    def tenant(self) -> str:
        """Tenant id this manager's shuffles register under.  Empty
        (the default) gives every shuffle its own tenant
        (``shuffle-<id>``) — isolation without configuration; name a
        tenant to pool several shuffles under one weight/quota."""
        return str(self.get("tenant", ""))

    @property
    def qos_tenant_weight(self) -> int:
        """This tenant's weight in the brokered max-min share of every
        credit budget (a weight-4 tenant gets 4x a weight-1 tenant's
        share under contention; idle shares stay borrowable)."""
        return self._int_in_range("qosTenantWeight", 1, 1, 1_000_000)

    @property
    def qos_tenant_priority(self) -> str:
        """Priority class: ``interactive`` work dequeues ahead of
        ``bulk`` (default) on the serve pool and may borrow from the
        lane pool's reserved slice; anti-starvation aging keeps bulk
        from starving behind a steady interactive stream."""
        v = str(self.get("qosTenantPriority", "bulk")).lower()
        return v if v in ("interactive", "bulk") else "bulk"

    @property
    def qos_tenant_max_bytes(self) -> int:
        """Admission-control quota on the tenant's registered
        (committed) map-output bytes: past it, a commit queues up to
        ``qosAdmissionWait`` then the tenant DEGRADES (narrower
        stripes, cold-tier serves) instead of OOMing the node.  0 (the
        default) = unlimited."""
        return self._bytes_in_range("qosTenantMaxBytes", 0, 0, 1 << 44)

    @property
    def qos_tenant_max_inflight(self) -> int:
        """Per-tenant cap on brokered in-flight fetch bytes across all
        of the tenant's concurrent readers (enforced by the reader
        window's broker).  0 (the default) = unlimited — the weighted
        share alone bounds it under contention."""
        return self._bytes_in_range(
            "qosTenantMaxInFlight", 0, 0, 1 << 40
        )

    @property
    def qos_aging_ms(self) -> int:
        """Anti-starvation aging on the classed edges: a bulk-class
        task or credit waiter older than this is promoted to
        interactive priority, so bulk never starves outright."""
        return self._time_ms("qosAging", 100)

    @property
    def qos_interactive_bytes(self) -> int:
        """Serve-size cutoff for the interactive class: serves at or
        below this many requested bytes (metadata reads, small blocks
        — the small-read-lane lineage) classify interactive regardless
        of tenant; larger serves take the owning tenant's class."""
        return self._bytes_in_range(
            "qosInteractiveBytes", 512 << 10, 0, 1 << 30
        )

    @property
    def qos_lane_reserve(self) -> int:
        """Stripe-lane tokens held back from bulk-class borrows so an
        interactive-class striped read always finds width (the lane
        pool's priority grant).  Clamped to the pool size at use."""
        return self._int_in_range("qosLaneReserve", 4, 0, 4096)

    @property
    def qos_admission_wait_ms(self) -> int:
        """How long an over-quota commit queues for earlier shuffles
        to release registered bytes before proceeding degraded."""
        return self._time_ms("qosAdmissionWait", 100)

    # -- skew-adaptive partitioning (sparkrdma_tpu/skew/) -------------------
    @property
    def skew_enabled(self) -> bool:
        """Skew-adaptive partitioning (skew/): writers classify
        partitions at commit from the streaming size/record sketch, and
        a partition over ``skewSplitThreshold`` (or ``skewSplitFactor``
        x the map output's median partition) commits as independently
        sorted SUB-BLOCKS at serializer frame boundaries — distinct
        map-output entries the reader fetches interleaved across the
        stripe/lane plan and k-way-merges as extra sorted runs.  Off by
        default: the writer commits one block per partition and the
        reader's plan is byte-identical to the pre-skew tree.  Only the
        pull read plane (``readPlane=host``) splits — the collective
        planes move whole partition blocks by construction."""
        return self._bool("skewEnabled", False)

    @property
    def skew_split_threshold(self) -> int:
        """Absolute hot-partition cutoff AND the sub-block target size:
        a partition at least this large always splits, into sub-blocks
        of roughly this many bytes each (whole serializer frames — a
        single frame larger than the target cannot split further)."""
        return self._bytes_in_range("skewSplitThreshold", 8 << 20,
                                    4 << 10, 1 << 40)

    @property
    def skew_split_factor(self) -> float:
        """Relative cutoff: a partition over this multiple of the map
        output's median non-empty partition size also splits (Zipfian
        heads dwarf the median long before any absolute threshold
        trips).  0 disables the relative test."""
        raw = self.get("skewSplitFactor", 4.0)
        try:
            v = float(raw)
        except (TypeError, ValueError):
            return 4.0
        return max(0.0, min(1e6, v))

    @property
    def skew_max_sub_blocks(self) -> int:
        """Cap on sub-blocks per split partition (each costs one
        16-byte location entry and one fetch-plan slot)."""
        return self._int_in_range("skewMaxSubBlocks", 16, 2, 1024)

    @property
    def skew_sample_stride(self) -> int:
        """Heavy-hitter sketch sampling stride on aggregating writers:
        every Nth record's key feeds the Misra-Gries sketch whose top
        share is published in the shuffle's skew telemetry (hot-KEY
        attribution — splitting itself keys off partition bytes)."""
        return self._int_in_range("skewSampleStride", 64, 1, 1 << 20)

    # -- push-based merged shuffle (sparkrdma_tpu/shuffle/push.py) ----------
    @property
    def push_enabled(self) -> bool:
        """Push-based merged shuffle (the magnet idiom): at commit,
        writers push per-partition sub-blocks to deterministic
        per-reduce-partition merger nodes, which append them into one
        merged per-reduce span; readers resolve the merged span first
        and fetch it as ONE large sequential read, pulling only the
        unmerged stragglers block-by-block through the unchanged pull
        path.  Best-effort by construction: a dropped push, a dead
        merger, or an old-wire-version peer only means more pull
        traffic — never wrong bytes.  Off by default: the reader plan
        is then byte-identical to the pure pull tree."""
        return self._bool("pushEnabled", False)

    @property
    def push_block_target(self) -> int:
        """Target size of each pushed sub-block: partition payloads are
        cut at serializer frame boundaries (the skew splitter's
        ``sub_spans``) into chunks of roughly this many bytes before
        being pushed, so no single push RPC carries an unbounded
        frame train."""
        return self._bytes_in_range("pushBlockTarget", 512 << 10,
                                    4 << 10, 1 << 30)

    @property
    def push_merge_timeout_ms(self) -> int:
        """Reader-side bound on the merged-location query: mergers that
        have not answered the merge-status RPC within this window are
        treated as offering no merged coverage and their partitions
        fall back to the pull path (best-effort push, bounded reader
        latency)."""
        return self._time_ms("pushMergeTimeout", 2000)

    @property
    def push_max_merged_bytes(self) -> int:
        """Per-(shuffle, reduce-partition) cap on merged bytes a merger
        will accept.  Sub-blocks arriving over the cap are dropped
        (counted ``push_drops_total{reason="cap"}``) and their map
        outputs served
        by the pull fallback — a merger never balloons past its
        provisioned spill budget because one reduce key ran hot."""
        return self._bytes_in_range("pushMaxMergedBytes", 256 << 20,
                                    1 << 20, 1 << 40)

    # -- observability ------------------------------------------------------
    @property
    def metrics_http_port(self) -> int:
        """Live Prometheus scrape endpoint (qos/http.py): serve
        ``/metrics`` (text exposition), ``/metrics.json`` and
        ``/tenants`` on this port for the manager's lifetime.  -1 (the
        default) disables; 0 binds an ephemeral port (tests/one-off
        runs — the bound address is ``manager.metrics_http.address``).
        Setting it implies ``metrics`` (a scrape endpoint over a
        disabled registry would be an empty page)."""
        return self._int_in_range("metricsHttpPort", -1, -1, 65535)

    @property
    def metrics_http_host(self) -> str:
        """Bind address of the scrape endpoint.  Defaults to loopback
        (a metrics port should be opt-in reachable); set ``0.0.0.0``
        for a fleet scraper to reach executors remotely."""
        return str(self.get("metricsHttpHost", "127.0.0.1"))

    @property
    def metrics_enabled(self) -> bool:
        """Enable the process-wide metrics registry (metrics/registry.py):
        labeled counters/gauges/histograms across transport, shuffle and
        memory.  Off by default — instrumented call sites then hold
        zero-overhead no-op handles.  A live scrape endpoint
        (``metricsHttpPort``) implies metrics."""
        return self._bool("metrics", False) or self.metrics_http_port >= 0

    @property
    def lock_debug(self) -> bool:
        """Runtime lock sanitizer (utils/dbglock.py): rank-checked lock
        wrappers with per-thread acquisition stacks and hold-time
        histograms; raises LockOrderViolation on a same-thread rank
        inversion.  Off by default — the transport/shuffle planes then
        allocate plain ``threading`` primitives (zero overhead).  The
        manager flips the process-global LockFactory on BEFORE building
        its node, so every lock created under it is instrumented."""
        return self._bool("lockDebug", False)

    @property
    def resource_debug(self) -> bool:
        """Runtime resource-lifecycle sanitizer (utils/ledger.py):
        every annotated acquire of a countable resource (serve
        credits, lane tokens, tier pins, window bytes, registered
        bytes, fds, send descriptors) returns a ledger ticket with an
        acquisition-site stack; double releases raise
        DoubleReleaseError and manager.stop() renders a loud leak
        report (``resource_leaked_total{resource=}``).  Off by default
        — call sites then share one no-op ticket (zero overhead).  The
        static half is tools/flowcheck.py; the manager flips the
        process-global ledger on BEFORE building its node."""
        return self._bool("resourceDebug", False)

    @property
    def wire_debug(self) -> bool:
        """Runtime wire-protocol frame validator (utils/wiredbg.py):
        both TCP engines' receive paths and the loopback dispatch plane
        validate every frame as it arrives — header sanity (known
        opcode, bounded length) and full schema-derived decode of RPC
        frames BEFORE the application listener sees them, with
        ``wire_frames_{validated,rejected}_total`` counters labeled by
        engine/opcode and hexdump context on every rejection.  Off by
        default — the receive paths then pay one module-global read
        per frame.  The static half is tools/wirecheck.py; the manager
        flips the process-global validator on BEFORE building its
        node."""
        return self._bool("wireDebug", False)

    @property
    def state_debug(self) -> bool:
        """Runtime lifecycle state-machine validator
        (utils/statemachine.py): every annotated machine's
        ``_transition()`` validates the edge against its declared
        TRANSITIONS table, counts
        ``state_transitions_total{machine=,from=,to=}`` and raises
        IllegalTransition (both states + 4-frame call site) on an
        undeclared edge.  Off by default — transitions then cost one
        module-global read and the plain assignment (identity-tested).
        The static half is tools/statecheck.py; the manager flips the
        process-global validator on BEFORE building its node."""
        return self._bool("stateDebug", False) or self.sched_shake != 0

    @property
    def sched_shake(self) -> int:
        """Deterministic schedule shaker seed (0 = off).  Non-zero
        arms stateDebug and injects a seeded 0-2ms yield/sleep at
        every validated lifecycle transition — widening the race
        window at exactly the points where lifecycle races live.
        Per-machine streams are seeded ``seed ^ crc32(machine)``, so a
        fixed seed replays the same perturbation schedule."""
        return self._int_in_range("schedShake", 0, 0, 2**31 - 1)

    @property
    def metrics_json_path(self) -> str:
        """When set, manager.stop() writes a JSON snapshot of the
        registry here (executors suffix ``.<executor_id>`` so
        multi-process runs don't clobber each other)."""
        return str(self.get("metricsJsonPath", ""))

    @property
    def metrics_prom_path(self) -> str:
        """When set, manager.stop() writes a Prometheus text-exposition
        dump here (same executor suffix rule as metricsJsonPath)."""
        return str(self.get("metricsPromPath", ""))

    @property
    def metrics_trace_bridge(self) -> bool:
        """When metrics AND tracing are both enabled, publish registry
        counters into the Tracer.counter() stream (Perfetto counter
        tracks) at shuffle unregister and manager stop."""
        return self._bool("metricsTraceBridge", True)

    @property
    def trace_enabled(self) -> bool:
        """Distributed fetch tracing (obs/): readers mint a trace
        context per reduce task and stamp every fetch-status RPC and
        read request with it (the v2 wire tail), so serve-side events
        on remote peers join the requester's trace.  Off by default —
        every instrumentation site then short-circuits on one
        attribute read, and all wire frames stay byte-identical to the
        trace-off encoding."""
        return self._bool("traceEnabled", False)

    @property
    def trace_sample_rate(self) -> float:
        """Fraction of reduce tasks that mint a trace context when
        ``traceEnabled`` (1.0 = every task).  Sampled-out tasks pay
        the same near-zero cost as tracing off."""
        return self._float_in_range("traceSampleRate", 1.0, 0.0, 1.0)

    @property
    def flight_recorder(self) -> bool:
        """Flight recorder (obs/recorder.py): per-plane bounded rings
        of structured events (transport, reader, decode, tier, qos,
        faults), dumped to JSON automatically on FetchFailed / breaker
        trip / ledger leak / wire reject and on demand via the metrics
        server's ``/flightrecorder`` endpoint.  On by default — the
        black box should be recording when the incident happens; each
        event costs one deque append under an uncontended per-plane
        lock."""
        return self._bool("flightRecorder", True)

    @property
    def flight_recorder_ring_size(self) -> int:
        """Events retained per plane ring (oldest drop first, drops
        counted in ``obs_events_dropped_total{plane=}``)."""
        return self._int_in_range("flightRecorderRingSize", 4096, 64, 1 << 20)

    @property
    def flight_recorder_dump_path(self) -> str:
        """Directory for flight-recorder dumps (pid- and sequence-
        tagged filenames, so one fleet's processes never collide).
        Empty (the default) disables automatic dumps — the rings still
        record and ``/flightrecorder`` still serves them."""
        return str(self.get("flightRecorderDumpPath", ""))

    @property
    def collect_shuffle_reader_stats(self) -> bool:
        return self._bool("collectShuffleReaderStats", False)

    @property
    def fetch_time_bucket_size_ms(self) -> int:
        return self._int_in_range("fetchTimeBucketSizeInMs", 300, 1, 60000)

    @property
    def fetch_time_num_buckets(self) -> int:
        return self._int_in_range("fetchTimeNumBuckets", 5, 2, 100)

    # -- control plane endpoints / timeouts ---------------------------------
    @property
    def driver_host(self) -> str:
        return str(self.get("driverHost", "127.0.0.1"))

    @property
    def driver_port(self) -> int:
        return self._int_in_range("driverPort", 0, 0, 65535)

    def set_driver_port(self, port: int) -> None:
        """Driver's bound port written back so executors inherit it
        (reference: RdmaShuffleConf.scala:56)."""
        self.set("driverPort", port)

    @property
    def executor_port(self) -> int:
        return self._int_in_range("executorPort", 0, 0, 65535)

    @property
    def port_max_retries(self) -> int:
        return self._int_in_range("portMaxRetries", 16, 1, 1000)

    @property
    def partition_location_fetch_timeout_ms(self) -> int:
        return self._time_ms("partitionLocationFetchTimeout", 120_000)

    @property
    def heartbeat_interval_ms(self) -> int:
        """Driver→executor liveness probe period on the hello/announce
        plane; 0 disables the heartbeat monitor.  Plays the role of RDMA
        CM DISCONNECTED events (RdmaNode.java:176-189) — the transport
        here has no connection-level death notification."""
        return self._time_ms("heartbeatInterval", 5_000)

    @property
    def heartbeat_timeout_ms(self) -> int:
        """How long an executor may go without acking a heartbeat
        before the driver prunes it (remove_executor — the
        onBlockManagerRemoved analog, RdmaShuffleManager.scala:253-263)."""
        return self._time_ms("heartbeatTimeout", 15_000)

    @property
    def connect_timeout_ms(self) -> int:
        """Reference: rdmaCmEventTimeout (20s)."""
        return self._time_ms("connectTimeout", 20_000)

    @property
    def teardown_listen_timeout_ms(self) -> int:
        return self._time_ms("teardownListenTimeout", 50)

    @property
    def connect_retries(self) -> int:
        """Connect attempts per channel before the peer is declared
        unreachable (reference: maxConnectionAttempts, accepted as a
        legacy alias; an older ``spark.shuffle.tpu.maxConnectionAttempts``
        setting still applies when ``connectRetries`` is unset)."""
        legacy = self._int_in_range("maxConnectionAttempts", 5, 1, 100)
        return self._int_in_range("connectRetries", legacy, 1, 100)

    @property
    def connect_backoff_ms(self) -> int:
        """Base backoff between connect attempts; doubles per attempt
        with equal jitter, capped at 16x base.  The wait stays
        stop-interruptible (node teardown never blocks on it)."""
        return self._time_ms("connectBackoffMs", 50)

    # -- fault injection & in-task recovery ---------------------------------
    @property
    def fault_inject(self) -> str:
        """Seeded deterministic fault-injection spec, e.g.
        ``connect:p=0.1;read_resp:p=0.05;serve_delay:ms=30;seed=42``
        (see faults/injector.py for the grammar and the point list).
        Empty (the default) compiles every woven point to a no-op
        bool check."""
        return str(self.get("faultInject", ""))

    @property
    def fetch_retry_count(self) -> int:
        """In-task retries per failed block fetch before converting to
        FetchFailedError (0 = the reference posture: first failure is
        terminal, byte-identical to the pre-retry path)."""
        return self._int_in_range("fetchRetryCount", 3, 0, 100)

    @property
    def fetch_retry_wait_ms(self) -> int:
        """Base fetch-retry backoff; doubles per attempt with equal
        jitter (Spark lineage: spark.shuffle.io.retryWait)."""
        return self._time_ms("fetchRetryWaitMs", 50)

    @property
    def fetch_retry_max_ms(self) -> int:
        """Total retry deadline budget per fetch: attempts stop when
        the elapsed retry time crosses this, whatever fetchRetryCount
        still allows."""
        return self._time_ms("fetchRetryMaxMs", 10_000)

    @property
    def fetch_breaker_failures(self) -> int:
        """Consecutive terminal-bound failures against one peer that
        trip its circuit breaker (further fetches fail fast instead of
        each burning the full backoff budget); 0 disables the
        breaker."""
        return self._int_in_range("fetchBreakerFailures", 4, 0, 1000)

    @property
    def fetch_breaker_reset_ms(self) -> int:
        """Open-breaker hold time before a single half-open probe
        fetch is admitted (success closes, failure re-opens)."""
        return self._time_ms("fetchBreakerResetMs", 2_000)

    @property
    def stripe_demote_failures(self) -> int:
        """Consecutive striped-lane failures against one peer that
        demote its large reads to the unstriped small-read lane; 0
        disables demotion."""
        return self._int_in_range("stripeDemoteFailures", 2, 0, 1000)

    @property
    def stripe_demote_ms(self) -> int:
        """How long a stripe demotion lasts before striped reads are
        re-attempted against the peer."""
        return self._time_ms("stripeDemoteMs", 5_000)

    # -- device placement (reference: cpuList comp-vector pinning) ----------
    @property
    def device_list(self) -> str:
        """Comma/range list restricting which local devices serve the
        exchange, e.g. '0-3,6' (reference: cpuList, RdmaShuffleConf)."""
        return str(self.get("deviceList", ""))

    def parse_device_list(self, n_devices: int) -> list:
        """Expand device_list against n_devices, dropping out-of-range
        entries; empty/invalid → all devices (reference semantics of
        initCpuArrayList, RdmaNode.java:216-273)."""
        return self._parse_index_list(self.device_list, n_devices)

    @property
    def dispatcher_cpu_list(self) -> str:
        """Comma/range CPU list pinning the transport dispatcher and
        bulk-pool threads via ``sched_setaffinity`` (the RdmaThread
        comp-vector affinity, RdmaNode.java:216-273).  The reference's
        ``spark.shuffle.rdma.cpuList`` aliases here.  Distinct from
        ``deviceList`` — that names accelerator devices, this names
        host CPUs."""
        return str(self.get("dispatcherCpuList", ""))

    def parse_dispatcher_cpu_list(self, n_cpus: int) -> list:
        """Expand dispatcher_cpu_list against this host's CPU count;
        empty/invalid → all CPUs (no pinning)."""
        return self._parse_index_list(self.dispatcher_cpu_list, n_cpus)

    @staticmethod
    def _parse_index_list(spec: str, n: int) -> list:
        spec = spec.strip()
        if not spec:
            return list(range(n))
        out = []
        try:
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                if "-" in part:
                    a, b = part.split("-", 1)
                    out.extend(range(int(a), int(b) + 1))
                else:
                    out.append(int(part))
        except ValueError:
            return list(range(n))
        out = [d for d in out if 0 <= d < n]
        return out or list(range(n))
