"""Snapshot writers over the metrics registry.

Two dump formats plus a diff helper shared with the CLI renderer
(tools/metrics_report.py):

- :func:`to_prometheus` — Prometheus text exposition (v0.0.4): one
  ``# TYPE`` line per family, cumulative ``_bucket{le=...}`` series per
  histogram.  Note the registry's buckets use EXCLUSIVE upper bounds
  (a sample on an edge lands above it — the reference reader-stats
  placement), a hair stricter than Prometheus' inclusive ``le``.
- :func:`write_json_snapshot` — the ``registry.snapshot()`` dict as a
  JSON file; :func:`diff_snapshots` subtracts two of them so a bench
  or test can attribute deltas to one run.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from sparkrdma_tpu.metrics.registry import MetricsRegistry, get_registry


def _escape(v: object) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def to_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry as Prometheus text exposition."""
    snap = (registry or get_registry()).snapshot()
    lines = []
    seen_type = set()

    def type_line(name: str, kind: str) -> None:
        if name not in seen_type:
            seen_type.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for c in snap["counters"]:
        type_line(c["name"], "counter")
        lines.append(
            f'{c["name"]}{_fmt_labels(c["labels"])} '
            f'{_fmt_value(c["value"])}'
        )
    for g in snap["gauges"]:
        type_line(g["name"], "gauge")
        lines.append(
            f'{g["name"]}{_fmt_labels(g["labels"])} '
            f'{_fmt_value(g["value"])}'
        )
    for h in snap["histograms"]:
        type_line(h["name"], "histogram")
        cum = 0
        for edge, n in zip(h["edges"], h["counts"]):
            cum += n
            lab = dict(h["labels"], le=_fmt_value(edge))
            lines.append(f'{h["name"]}_bucket{_fmt_labels(lab)} {cum}')
        lab = dict(h["labels"], le="+Inf")
        lines.append(
            f'{h["name"]}_bucket{_fmt_labels(lab)} {h["count"]}'
        )
        lines.append(
            f'{h["name"]}_sum{_fmt_labels(h["labels"])} '
            f'{_fmt_value(h["sum"])}'
        )
        lines.append(
            f'{h["name"]}_count{_fmt_labels(h["labels"])} {h["count"]}'
        )
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path: str,
                     registry: Optional[MetricsRegistry] = None) -> None:
    with open(path, "w") as f:
        f.write(to_prometheus(registry))


def write_json_snapshot(path: str,
                        registry: Optional[MetricsRegistry] = None) -> None:
    with open(path, "w") as f:
        json.dump((registry or get_registry()).snapshot(), f, indent=1)


def _series_key(rec: Dict) -> tuple:
    return (rec["name"], tuple(sorted(rec["labels"].items())))


def diff_snapshots(new: Dict, old: Dict) -> Dict:
    """``new - old`` over the snapshot dict shape: counter values and
    histogram counts/sums subtract (series missing from ``old`` keep
    their ``new`` value); gauges are point-in-time, so the diff keeps
    the NEW reading."""
    old_counters = {_series_key(c): c for c in old.get("counters", [])}
    old_hists = {_series_key(h): h for h in old.get("histograms", [])}
    out = {
        "ts": new.get("ts"),
        "ts_base": old.get("ts"),
        "counters": [],
        "gauges": [dict(g) for g in new.get("gauges", [])],
        "histograms": [],
    }
    for c in new.get("counters", []):
        base = old_counters.get(_series_key(c))
        out["counters"].append({
            "name": c["name"], "labels": dict(c["labels"]),
            "value": c["value"] - (base["value"] if base else 0),
        })
    for h in new.get("histograms", []):
        base = old_hists.get(_series_key(h))
        counts = list(h["counts"])
        hsum, cnt = h["sum"], h["count"]
        if base and list(base.get("edges", [])) == list(h["edges"]):
            counts = [a - b for a, b in zip(counts, base["counts"])]
            hsum -= base["sum"]
            cnt -= base["count"]
        out["histograms"].append({
            "name": h["name"], "labels": dict(h["labels"]),
            "edges": list(h["edges"]), "counts": counts,
            "sum": hsum, "count": cnt,
        })
    return out
