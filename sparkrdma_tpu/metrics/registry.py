"""Process-wide metrics registry: counters, gauges, log-scale histograms.

The reference's only runtime observability is the reader-side fetch
histograms printed at manager stop (RdmaShuffleReaderStats.scala:29-79).
RDMA-era systems ship per-transfer counters as a first-class API
(PAPERS.md: fabric-lib exposes transfer counters and completion
latencies; RDMAbox attributes throughput loss to specific stages only
because every stage is counted) — this module is that layer for the
rebuild: one process-wide :class:`MetricsRegistry` of labeled
instruments that every runtime layer (transport, shuffle, memory)
records into.

Design constraints:

- **Zero overhead when disabled** (the default): the module-level
  ``counter()``/``gauge()``/``histogram()`` helpers return shared no-op
  singletons while the global registry is disabled, so instrumented hot
  paths cost one attribute call on a ``pass`` method.  Enabled via conf
  ``spark.shuffle.tpu.metrics`` (TpuShuffleManager flips the global
  registry on, exactly like the tracer).
- **Thread safety**: counters are lock-striped (8 cells, one assigned
  per thread round-robin) so concurrent writers on the transport pools
  don't serialize on one lock; gauges and histograms take one leaf
  lock each.
- **Stable identity**: an instrument is (kind, name, sorted labels);
  repeated lookups return the same object, so call sites may fetch
  handles at construction time or per call.

Snapshots/exposition live in :mod:`sparkrdma_tpu.metrics.export`.
"""

from __future__ import annotations

import bisect
import contextlib
import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_N_STRIPES = 8

# per-thread stripe index, assigned round-robin on first use.  NOT
# derived from get_ident(): CPython thread ids are aligned pthread
# struct addresses, so ``get_ident() % 8`` is 0 for every thread and
# would collapse the striping onto one lock.
_STRIPE_TLS = threading.local()
_STRIPE_SEQ = itertools.count()


def _stripe() -> int:
    idx = getattr(_STRIPE_TLS, "idx", None)
    if idx is None:
        idx = _STRIPE_TLS.idx = next(_STRIPE_SEQ) % _N_STRIPES
    return idx


LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def default_latency_buckets() -> List[float]:
    """Log-scale (1-2.5-5 decade ladder) bucket upper bounds, tuned for
    millisecond latencies: 0.05ms .. 10s, open-ended above."""
    edges: List[float] = []
    for decade in (0.1, 1.0, 10.0, 100.0, 1000.0, 10000.0):
        for m in (0.5, 1.0, 2.5, 5.0):
            edges.append(decade * m)
    # 0.5 * next decade == 5 * this one: dedupe
    return sorted(set(round(x, 6) for x in edges))


def default_size_buckets() -> List[float]:
    """Power-of-4 byte-size ladder: 256B .. 4GiB."""
    return [float(1 << s) for s in range(8, 33, 2)]


class Counter:
    """Monotonic counter, lock-striped across ``_N_STRIPES`` cells."""

    __slots__ = ("name", "labels", "_cells", "_locks")

    def __init__(self, name: str, labels: LabelKey = ()):
        self.name = name
        self.labels = labels
        self._cells = [0] * _N_STRIPES
        # metric leaf locks rank LAST (98): instruments record from
        # under every other lock in the process; plain threading (not
        # dbglock) because the sanitizer's own telemetry lands here
        self._locks = [threading.Lock() for _ in range(_N_STRIPES)]  # lock-order: 98

    def inc(self, n: int = 1) -> None:
        i = _stripe()
        with self._locks[i]:
            self._cells[i] += n

    @property
    def value(self) -> float:
        total = 0
        for i in range(_N_STRIPES):
            with self._locks[i]:
                total += self._cells[i]
        return total


class Gauge:
    """Point-in-time value (set/inc/dec)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: LabelKey = ()):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()  # lock-order: 98

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bucketed distribution.  ``edges`` are EXCLUSIVE upper bounds:
    a sample exactly on an edge lands in the NEXT bucket (matching the
    reference reader-stats placement ``latency // bucket_ms``,
    RdmaShuffleReaderStats.scala:38-44); one overflow bucket catches
    everything past the last edge.  Default edges are the log-scale
    latency ladder."""

    __slots__ = ("name", "labels", "edges", "_counts", "_sum", "_lock")

    def __init__(self, name: str, labels: LabelKey = (),
                 edges: Optional[Sequence[float]] = None):
        self.name = name
        self.labels = labels
        self.edges = list(edges) if edges is not None \
            else default_latency_buckets()
        if sorted(self.edges) != self.edges:
            raise ValueError(f"bucket edges must ascend: {self.edges}")
        self._counts = [0] * (len(self.edges) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()  # lock-order: 98

    def observe(self, v: float) -> None:
        idx = bisect.bisect_right(self.edges, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v

    @contextlib.contextmanager
    def time(self):
        """Observe the wall-clock milliseconds of the with-block."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe((time.perf_counter() - t0) * 1000.0)

    @property
    def counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class _NullCounter:
    """Shared no-op counter handle (registry disabled)."""

    __slots__ = ()
    name = ""
    labels: LabelKey = ()
    value = 0.0

    def inc(self, n: int = 1) -> None:
        pass


class _NullGauge:
    __slots__ = ()
    name = ""
    labels: LabelKey = ()
    value = 0.0

    def set(self, v: float) -> None:
        pass

    def inc(self, n: float = 1) -> None:
        pass

    def dec(self, n: float = 1) -> None:
        pass


class _NullHistogram:
    __slots__ = ()
    name = ""
    labels: LabelKey = ()
    edges: List[float] = []
    counts: List[int] = []
    count = 0
    sum = 0.0

    def observe(self, v: float) -> None:
        pass

    @contextlib.contextmanager
    def time(self):
        yield


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


class MetricsRegistry:
    """Registry of labeled instruments.

    ``enabled`` gates the handle factories: while False they hand back
    the shared no-op singletons (unless ``force=True`` — used by
    subsystems with their own conf gate, e.g. the reader stats).  Real
    instruments created while enabled keep recording even if the flag
    is later cleared — only NEW handle lookups become no-ops."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._instruments: Dict[Tuple[str, str, LabelKey], object] = {}
        self._lock = threading.Lock()  # lock-order: 96

    # -- handle factories ---------------------------------------------------
    def counter(self, name: str, force: bool = False, **labels) -> Counter:
        if not (self.enabled or force):
            return NULL_COUNTER
        return self._get("counter", name, _label_key(labels),
                         lambda k: Counter(name, k))

    def gauge(self, name: str, force: bool = False, **labels) -> Gauge:
        if not (self.enabled or force):
            return NULL_GAUGE
        return self._get("gauge", name, _label_key(labels),
                         lambda k: Gauge(name, k))

    def histogram(self, name: str, edges: Optional[Sequence[float]] = None,
                  force: bool = False, **labels) -> Histogram:
        if not (self.enabled or force):
            return NULL_HISTOGRAM
        return self._get("histogram", name, _label_key(labels),
                         lambda k: Histogram(name, k, edges=edges))

    def _get(self, kind: str, name: str, key: LabelKey, make):
        full = (kind, name, key)
        with self._lock:
            inst = self._instruments.get(full)
            if inst is None:
                inst = self._instruments[full] = make(key)
            return inst

    # -- introspection ------------------------------------------------------
    def instruments(self) -> List[Tuple[str, object]]:
        """[(kind, instrument)] sorted by (kind, name, labels)."""
        with self._lock:
            items = list(self._instruments.items())
        items.sort(key=lambda kv: kv[0])
        return [(kind, inst) for (kind, _n, _l), inst in items]

    def snapshot(self) -> Dict:
        """JSON-able point-in-time dump of every instrument (see
        metrics/export.py for the writers over this)."""
        counters, gauges, histograms = [], [], []
        for kind, inst in self.instruments():
            labels = dict(inst.labels)
            if kind == "counter":
                counters.append({
                    "name": inst.name, "labels": labels,
                    "value": inst.value,
                })
            elif kind == "gauge":
                gauges.append({
                    "name": inst.name, "labels": labels,
                    "value": inst.value,
                })
            else:
                histograms.append({
                    "name": inst.name, "labels": labels,
                    "edges": list(inst.edges),
                    "counts": inst.counts,
                    "sum": inst.sum, "count": inst.count,
                })
        return {
            "ts": time.time(),
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }

    def publish_to_tracer(self, tracer) -> None:
        """Bridge counters/gauges into the ``Tracer.counter()`` event
        stream so they render as counter tracks on the Perfetto
        timeline (one sample per call — call at interesting moments,
        e.g. shuffle unregister and manager stop)."""
        for kind, inst in self.instruments():
            if kind not in ("counter", "gauge"):
                continue
            suffix = ",".join(f"{k}={v}" for k, v in inst.labels)
            name = f"{inst.name}{{{suffix}}}" if suffix else inst.name
            tracer.counter(name, value=inst.value)

    def reset(self) -> None:
        """Drop every instrument (tests)."""
        with self._lock:
            self._instruments.clear()


# the process-global registry; managers enable it from conf
GLOBAL_REGISTRY = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return GLOBAL_REGISTRY


def counter(name: str, **labels) -> Counter:
    return GLOBAL_REGISTRY.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return GLOBAL_REGISTRY.gauge(name, **labels)


def histogram(name: str, edges: Optional[Sequence[float]] = None,
              **labels) -> Histogram:
    return GLOBAL_REGISTRY.histogram(name, edges=edges, **labels)
