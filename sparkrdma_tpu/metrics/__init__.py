"""Unified observability: the process-wide metrics registry.

``counter()``/``gauge()``/``histogram()`` are the instrumented layers'
entry points — no-ops until a manager enables the global registry from
conf (``spark.shuffle.tpu.metrics``).  See registry.py for the model
and export.py for the Prometheus/JSON snapshot writers.
"""

from sparkrdma_tpu.metrics.registry import (  # noqa: F401
    GLOBAL_REGISTRY,
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    default_latency_buckets,
    default_size_buckets,
    gauge,
    get_registry,
    histogram,
)
from sparkrdma_tpu.metrics.export import (  # noqa: F401
    diff_snapshots,
    to_prometheus,
    write_json_snapshot,
    write_prometheus,
)
