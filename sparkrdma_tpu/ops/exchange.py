"""The shared shard-level hash-exchange body.

One canonical implementation of "hash-partition my local records and
move every bucket to its owner" — the device-side analog of the
reference's map-side partition + shuffle transfer, used by every
hash-partitioned exchange model (wordcount's reduceByKey, the hash
join's both sides).  Must run inside ``shard_map`` over the mesh's
exchange axis.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from sparkrdma_tpu.ops.partition import (
    hash_partition_ids,
    partition_to_buckets_dropping,
)
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def hash_exchange(
    keys: jax.Array,
    vals: jax.Array,
    valid: jax.Array,
    n_devices: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Hash-partition local (keys, vals, valid) columns into n_devices
    buckets of ``capacity`` and all_to_all them to their owners.

    Padding (valid == 0) is routed to a TRASH bucket (id = n_devices)
    that is never exchanged, so it consumes zero real capacity and can
    never displace a real record or signal a false overflow — routing
    it to the home bucket (round 1) overflowed on heavily padded
    streams such as post-join validity masks.  Bucket fill slots carry
    (dtype-max key, 0 value, 0 valid).

    Returns (keys', vals', valid', max_fill): flat [D * capacity] local
    columns of everything this device now owns, plus the max TRUE bucket
    fill (> capacity signals overflow — caller retries bigger).

    Degenerate ``n_devices == 1`` is the identity: every key already
    lives here, so the bucketing sort and its capacity padding are
    skipped entirely (outputs keep the input length, max_fill = 0).
    """
    if n_devices == 1:
        return keys, vals, valid, jnp.int32(0)
    ids = hash_partition_ids(keys, n_devices)
    (bk, bv, bm), counts = partition_to_buckets_dropping(
        ids, valid > 0, (keys, vals, valid), n_devices, capacity,
        fill_values=(
            jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype),
            jnp.zeros((), vals.dtype),
            jnp.zeros((), jnp.int32),
        ),
    )
    ek = jax.lax.all_to_all(bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    ev = jax.lax.all_to_all(bv, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    em = jax.lax.all_to_all(bm, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    return (
        ek.reshape(-1), ev.reshape(-1), em.reshape(-1),
        jnp.max(counts).astype(jnp.int32),
    )
