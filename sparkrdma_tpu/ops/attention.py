"""Blockwise attention kernel (Pallas): the ring-attention hot op.

One call computes the flash-style partial results of attention between
the local queries and ONE circulating K/V block:

    m_blk[i] = max_j s[i, j]                  (row max of masked scores)
    l_blk[i] = Σ_j exp(s[i, j] - m_blk[i])    (unnormalized denominator)
    o_blk[i] = Σ_j exp(s[i, j] - m_blk[i]) v[j]

with ``s = (q @ kᵀ) · scale`` and optional causal masking by global
positions.  The ring step then folds the partials into its running
(m, l, o) accumulator with two exponentials — an EXACT online softmax
(models/ring_attention.py).

Rows fully masked within this block keep ``m_blk = NEG_INF``; their
(garbage) l/o partials are annihilated by the fold's
``exp(m_blk - m_new) = 0`` factor, so no in-kernel special-casing is
needed — but this is why NEG_INF is a large finite number, not -inf
(inf - inf would poison the fold with NaNs).

The Pallas kernel tiles q × k over a 2-D grid, accumulating in VMEM
scratch, scores on the MXU in float32 (pallas_guide.md: MXU matmul +
scratch-accumulator pattern); ``impl="xla"`` is the plain-jnp reference
used on non-TPU backends and in equivalence tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _pick_block(n: int, preferred: int) -> int:
    b = min(preferred, n)
    while n % b:
        b -= 1
    return b


def _xla_block_attention(q, k, v, q_offset, k_offset, causal, scale):
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    s = (q32 @ k32.T) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[0])
        k_pos = k_offset + jnp.arange(k.shape[0])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_blk[:, None])
    return m_blk, p.sum(axis=-1), p @ v32


def _kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
            o_ref, m_ref, l_ref, acc, m_s, l_s,
            *, causal: bool, scale: float, block_q: int, block_k: int):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    # feed the MXU its native input dtype (bf16×bf16→f32 runs at full
    # rate; an up-front astype(f32) would force the slow fp32 path),
    # accumulate in float32 either way via preferred_element_type
    s = jax.lax.dot_general(
        q_ref[:], k_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = qoff_ref[0, 0] + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = koff_ref[0, 0] + j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_s[:, :1]
    l_prev = l_s[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    # PV matmul: cast the probabilities down to V's dtype so bf16 V
    # rides the fast MXU path too (the standard flash-attention trade;
    # f32 V keeps the exact path since the cast is then a no-op)
    acc[:] = acc[:] * alpha + jnp.dot(
        p.astype(v_ref.dtype), v_ref[:], preferred_element_type=jnp.float32
    )
    m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        o_ref[:] = acc[:]
        m_ref[:] = m_s[:]
        l_ref[:] = l_s[:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "interpret",
    ),
)
def _pallas_block_attention(q, k, v, q_offset, k_offset, *, causal, scale,
                            block_q, block_k, interpret):
    s_q, d = q.shape
    s_k = k.shape[0]
    # under shard_map the outputs vary over the same mesh axes as the
    # inputs; out_shape must carry that annotation explicitly
    try:
        vma = jax.typeof(q).vma
    except (AttributeError, TypeError):
        vma = frozenset()
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    grid = (s_q // bq, s_k // bk)
    kernel = functools.partial(
        _kernel, causal=causal, scale=scale, block_q=bq, block_k=bk
    )
    smem = pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                        memory_space=pltpu.SMEM)
    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem,
            smem,
            pl.BlockSpec((bq, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bq, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, _LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, _LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_q, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((s_q, _LANES), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((s_q, _LANES), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(q_offset, jnp.int32).reshape(1, 1),
        jnp.asarray(k_offset, jnp.int32).reshape(1, 1),
        q, k, v,
    )
    return m[:, 0], l[:, 0], o


def block_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offset,
    k_offset,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: Optional[str] = None,
    block_q: int = 512,
    block_k: int = 1024,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Partial attention of ``q`` [s_q, d] against one K/V block
    [s_k, d].  Returns float32 ``(m_blk [s_q], l_blk [s_q],
    o_blk [s_q, d])``.

    ``impl``: "pallas" (TPU kernel; interpreted elsewhere), "xla"
    (plain jnp), or None = pallas on TPU backends, xla otherwise.

    Default blocks (512, 1024) measure ~98% of the best swept
    configuration for bf16 at d_head=128 on a real chip while keeping
    the f32 score/probability temporaries (block_q x block_k) and
    double-buffered operand blocks comfortably inside the ~16 MB VMEM
    budget even for float32 inputs; (1024, 1024) is marginally faster
    for bf16 but within ~3% and tighter on VMEM.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return _xla_block_attention(q, k, v, q_offset, k_offset, causal, scale)
    if impl != "pallas":
        raise ValueError(f"unknown impl {impl!r}")
    return _pallas_block_attention(
        q, k, v, q_offset, k_offset, causal=causal, scale=float(scale),
        block_q=block_q, block_k=block_k,
        interpret=jax.default_backend() != "tpu",
    )
