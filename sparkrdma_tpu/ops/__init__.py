"""Device-side ops: partitioning, hashing, segment reductions, sort helpers."""

from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.ops.partition import (
    hash_partition_ids,
    make_range_splitters,
    partition_to_buckets,
    range_partition_ids,
)

__all__ = [
    "hash_partition_ids",
    "range_partition_ids",
    "make_range_splitters",
    "partition_to_buckets",
    "hash_exchange",
]
