"""Device-side keyed reductions: the combiner step as an XLA program.

The reference's aggregation runs on the CPU during the read path
(RdmaShuffleReader.scala:82-97, Spark's Aggregator); on TPU the
post-exchange combine is a device program: sort the received keys once,
take prefix sums, and extract per-run totals at run-end positions with
a log-step forward fill — all static shapes with sentinel padding.

Round-1 ran a SECOND full sort to compact run-end rows to the front;
the host pulls full-length arrays either way (static shapes), so the
compaction bought nothing but ~40% of the step time.  Results now stay
at their run-end positions: entries are valid where ``counts > 0`` and
consumers extract by that mask (sums 46.8 -> 30.1 ms at 8.4M rows on
one chip).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def segmented_scan(vals, heads, op, identity):
    """Inclusive segmented scan: ``out[i]`` combines ``vals`` with
    ``op`` from the nearest segment head at or before ``i`` through
    ``i``.  ``heads`` is a bool column marking segment starts (position
    0 need not be flagged — out-of-range acts as a boundary).

    On TPU backends large scans run as ONE Pallas pass
    (ops/scan_kernels.py: O(n) HBM traffic instead of the log-step's
    O(n log n)); elsewhere the Hillis–Steele loop below — ~log2(n)
    passes of shift + where, no gathers.  ``identity`` is ``op``'s
    neutral element (0 for add, dtype max for min, ...).
    """
    from sparkrdma_tpu.ops.scan_kernels import (
        MIN_KERNEL_ELEMS,
        kernel_eligible,
        scan_flagged,
        use_scan_kernels,
    )

    n = int(vals.shape[0])
    kind = {jnp.add: "add", jnp.minimum: "min", jnp.maximum: "max"}.get(op)
    if (kind and n >= MIN_KERNEL_ELEMS and kernel_eligible(vals)
            and use_scan_kernels()):
        _f, (out,) = scan_flagged(kind, heads, (vals,))
        return out
    x = vals
    f = heads
    ident = jnp.full((1,), identity, vals.dtype)
    s = 1
    while s < n:
        px = jnp.concatenate([jnp.broadcast_to(ident, (s,)), x[:-s]])
        pf = jnp.concatenate([jnp.ones(s, bool), f[:-s]])
        x = jnp.where(f, x, op(px, x))
        f = f | pf
        s <<= 1
    return x


def _ff_run_carry(is_last, columns):
    """Log-step forward fill of ``columns`` from run-END positions:
    after the fill, position i holds each column's value at the latest
    run end AT OR BEFORE i (positions before the first end keep
    UNSPECIFIED values, flagged False — consumers mask by the flag).
    Returns (filled_flag, columns).  Large TPU fills run as one Pallas
    pass (ops/scan_kernels.py)."""
    from sparkrdma_tpu.ops.scan_kernels import (
        MIN_KERNEL_ELEMS,
        kernel_eligible,
        scan_flagged,
        use_scan_kernels,
    )

    if (
        int(is_last.shape[0]) >= MIN_KERNEL_ELEMS
        and kernel_eligible(*columns)
        and use_scan_kernels()
    ):
        flag, cols = scan_flagged("fill", is_last, tuple(columns))
        return flag, cols
    flag = is_last
    cols = list(columns)
    n = int(flag.shape[0])
    s = 1
    while s < n:
        pf = jnp.concatenate([flag[:s], flag[:-s]])
        prev = [jnp.concatenate([c[:s], c[:-s]]) for c in cols]
        need = ~flag
        cols = [jnp.where(need, p, c) for p, c in zip(prev, cols)]
        flag = flag | pf
        s <<= 1
    return flag, cols


def _prev_end(flag, cols):
    """Shift the filled run-end carry right by one: position i sees the
    latest run end STRICTLY before i (zeros when there is none)."""
    out = []
    for c in cols:
        masked = jnp.where(flag, c, jnp.zeros((), c.dtype))
        out.append(
            jnp.concatenate([jnp.zeros(1, c.dtype), masked[:-1]])
        )
    return out


def reduce_by_key_local(
    keys: jax.Array, vals: jax.Array, valid: Optional[jax.Array]
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Reduce (sum) values by key over one device's elements.

    ``valid`` is an int32 0/1 indicator per slot — or ``None``, the
    every-slot-real fast path that drops the validity operand from the
    sort entirely (the D == 1 / unpadded case: one third less sort
    traffic, and the sort IS the step's cost).  Invalid slots must be
    pre-masked to (key = dtype max, value = 0, valid = 0) so they all
    group into the single final run; REAL keys equal to the dtype max
    are still counted correctly because validity is tracked explicitly
    (unlike a sentinel-only scheme).  Valid entries may sit anywhere
    (post-exchange buckets are row-scattered).

    Returns:
      (unique_keys, sums, counts, n_unique): full-length arrays whose
      RUN-END positions hold each distinct real key, the sum of its
      values, and how many valid elements it had; every other position
      carries (key dtype max, 0, 0).  Extract with ``counts > 0``
      (n_unique positions match).
    """
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    if valid is None:
        ks, vs = jax.lax.sort((keys, vals), num_keys=1, is_stable=False)
        ms = jnp.ones(keys.shape[0], jnp.int32)
    else:
        m = valid.astype(jnp.int32)
        # one sort groups runs; valids order before invalids in a run
        ks, ms, vs = jax.lax.sort(
            (keys, jnp.int32(1) - m, vals), num_keys=2, is_stable=False
        )
        ms = jnp.int32(1) - ms
    from sparkrdma_tpu.ops.scan_kernels import cumsum_1d

    csum_v = cumsum_1d(vs)
    csum_m = cumsum_1d(ms)
    is_last = jnp.concatenate([ks[1:] != ks[:-1], jnp.ones(1, bool)])
    flag, (fv, fm) = _ff_run_carry(is_last, (csum_v, csum_m))
    prev_v, prev_m = _prev_end(flag, (fv, fm))
    counts = jnp.where(is_last, csum_m - prev_m, 0).astype(jnp.int32)
    real = counts > 0
    counts = jnp.where(real, counts, 0)
    sums = jnp.where(real, csum_v - prev_v, 0).astype(vals.dtype)
    uniq = jnp.where(real, ks, sentinel)
    n_unique = jnp.sum(real.astype(jnp.int32))
    return uniq, sums, counts, n_unique


def aggregate_by_key_local(
    keys: jax.Array, vals: jax.Array, valid: Optional[jax.Array]
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full keyed aggregation over one device's elements: sum, count,
    min, and max per distinct key in one pass (the device-side
    combineByKey; Spark's Aggregator on the read path,
    RdmaShuffleReader.scala:82-97).

    Same masking contract as :func:`reduce_by_key_local` (invalid slots
    pre-masked to key = dtype max, value = 0, valid = 0; ``valid=None``
    is the every-slot-real fast path dropping the validity sort
    operand), and the same run-end output layout: extract with
    ``counts > 0``.

    Sums accumulate in the value dtype and wrap on overflow — the JVM
    Int/Long semantics Spark's reduceByKey(_+_) has.  (Widening to
    int64 on TPU requires the global ``jax_enable_x64`` flag; callers
    wanting wide sums pass int64 columns with that flag on.)

    Mechanics: values join the SORT KEY (num_keys=3) so a run's slots
    order ascending by value; runs are delimited on (key, validity) so
    a real run is all-valid even when a real key equals the sentinel —
    its max is then its LAST slot (the run-end row itself) and its min
    is the slot right after the PREVIOUS run's end, which rides the
    forward fill as a next-value column.  No gathers, no second sort.
    """
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    if valid is None:
        # values stay in the sort key (min/max ride run order)
        ks, vs = jax.lax.sort((keys, vals), num_keys=2, is_stable=False)
        ms = jnp.ones(keys.shape[0], jnp.int32)
        bound = ks[1:] != ks[:-1]
    else:
        m = valid.astype(jnp.int32)
        inv = jnp.int32(1) - m
        ks, inv_s, vs = jax.lax.sort(
            (keys, inv, vals), num_keys=3, is_stable=False
        )
        ms = jnp.int32(1) - inv_s
        bound = (ks[1:] != ks[:-1]) | (inv_s[1:] != inv_s[:-1])
    from sparkrdma_tpu.ops.scan_kernels import cumsum_1d

    csum_v = cumsum_1d(vs)
    csum_m = cumsum_1d(ms)
    is_last = jnp.concatenate([bound, jnp.ones(1, bool)])
    # the slot after a run's end opens the NEXT run = its min
    vs_next = jnp.concatenate([vs[1:], jnp.zeros(1, vs.dtype)])
    flag, (fv, fm, fnext) = _ff_run_carry(
        is_last, (csum_v, csum_m, vs_next)
    )
    prev_v, prev_m, prev_next = _prev_end(flag, (fv, fm, fnext))
    counts = jnp.where(is_last, csum_m - prev_m, 0).astype(jnp.int32)
    real = counts > 0
    counts = jnp.where(real, counts, 0)
    sums = jnp.where(real, csum_v - prev_v, 0).astype(vals.dtype)
    maxs = jnp.where(real, vs, 0).astype(vals.dtype)
    # run 0 has no previous end: its min is the globally first slot
    had_prev = jnp.concatenate([jnp.zeros(1, bool), flag[:-1]])
    mins = jnp.where(had_prev, prev_next, vs[0])
    mins = jnp.where(real, mins, 0).astype(vals.dtype)
    uniq = jnp.where(real, ks, sentinel)
    n_unique = jnp.sum(real.astype(jnp.int32))
    return uniq, sums, counts, mins, maxs, n_unique
