"""Device-side keyed reductions: the combiner step as an XLA program.

The reference's aggregation runs on the CPU during the read path
(RdmaShuffleReader.scala:82-97, Spark's Aggregator); on TPU the
post-exchange combine is a device program: sort the received keys, find
segment boundaries, segment-sum the values — all static shapes with
sentinel padding.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def reduce_by_key_local(
    keys: jax.Array, vals: jax.Array, valid: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Reduce (sum) values by key over one device's elements.

    ``valid`` is an int32 0/1 indicator per slot.  Invalid slots must be
    pre-masked to (key = dtype max, value = 0, valid = 0) so they all
    group into the single final run; REAL keys equal to the dtype max
    are still counted correctly because validity is tracked explicitly
    (unlike a sentinel-only scheme).  Valid entries may sit anywhere
    (post-exchange buckets are row-scattered).

    Returns:
      (unique_keys, sums, counts, n_unique): [n] arrays where the first
      n_unique slots hold each distinct real key, the sum of its values,
      and how many valid elements it had; the rest is padding (key dtype
      max, zeros).
    """
    n = keys.shape[0]
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    # TPU-critical: scatter-free.  Sort triples, then extract per-run
    # totals as differences of prefix sums at run ends; compact the run
    # ends to the front with a second (cheap) sort instead of a scatter.
    m = valid.astype(jnp.int32)
    # push invalid slots to the very end so they merge into (at most) the
    # tail of the final run and never split a real run
    ks, ms, vs = jax.lax.sort(
        (keys, jnp.int32(1) - m, vals), num_keys=2, is_stable=False
    )
    ms = jnp.int32(1) - ms
    csum_v = jnp.cumsum(vs)
    csum_m = jnp.cumsum(ms)
    is_last = jnp.concatenate([ks[1:] != ks[:-1], jnp.ones(1, bool)])
    # compact run-end rows to the front, in key order: non-last rows get
    # (sentinel key, tiebreak 1) so they sort after every run-end row,
    # including a run-end row whose real key IS the sentinel (tiebreak 0)
    sel_key = jnp.where(is_last, ks, sentinel)
    tiebreak = jnp.where(is_last, jnp.int32(0), jnp.int32(1))
    sel_v = jnp.where(is_last, csum_v, jnp.zeros((), csum_v.dtype))
    sel_m = jnp.where(is_last, csum_m, jnp.zeros((), csum_m.dtype))
    uniq, _, ends_v, ends_m = jax.lax.sort(
        (sel_key, tiebreak, sel_v, sel_m), num_keys=2, is_stable=False
    )
    n_runs = jnp.sum(is_last.astype(jnp.int32))
    slot = jnp.arange(n, dtype=jnp.int32)
    in_runs = slot < n_runs
    prev_v = jnp.concatenate([jnp.zeros(1, ends_v.dtype), ends_v[:-1]])
    prev_m = jnp.concatenate([jnp.zeros(1, ends_m.dtype), ends_m[:-1]])
    counts = jnp.where(in_runs, ends_m - prev_m, 0).astype(jnp.int32)
    real = counts > 0
    sums = jnp.where(real, ends_v - prev_v, 0).astype(vals.dtype)
    uniq = jnp.where(real, uniq, sentinel)
    # valid runs form a prefix: every non-final run holds ≥1 valid slot
    # (invalid slots all carry the same arbitrary key content only in the
    # final run thanks to the validity tiebreak in the first sort)
    n_unique = jnp.sum(real.astype(jnp.int32))
    return uniq, sums, counts, n_unique


def aggregate_by_key_local(
    keys: jax.Array, vals: jax.Array, valid: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full keyed aggregation over one device's elements: sum, count,
    min, and max per distinct key in one pass (the device-side
    combineByKey; Spark's Aggregator on the read path,
    RdmaShuffleReader.scala:82-97).

    Same masking contract as :func:`reduce_by_key_local` (invalid slots
    pre-masked to key = dtype max, value = 0, valid = 0).

    Sums accumulate in the value dtype and wrap on overflow — the JVM
    Int/Long semantics Spark's reduceByKey(_+_) has.  (Widening to
    int64 on TPU requires the global ``jax_enable_x64`` flag; callers
    wanting wide sums pass int64 columns with that flag on.)

    Returns (unique_keys, sums, counts, mins, maxs, n_unique); min/max
    slots for padding runs carry zeros.
    """
    n = keys.shape[0]
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    m = valid.astype(jnp.int32)
    inv = jnp.int32(1) - m
    # values join the SORT KEY (num_keys=3): within a run, slots order
    # ascending by value, so a run's min is its FIRST slot and its max
    # its LAST.  Runs are delimited on (key, validity) so a real run is
    # all-valid even when a real key equals the sentinel (invalid slots
    # are pre-masked to the sentinel key and split into their own run) —
    # min and max then ride the compaction sort as extra operands, with
    # NO gathers (a full-size TPU gather costs ~10 cycles/element; two
    # of them were 80% of this function's runtime at 4M rows).
    ks, inv_s, vs = jax.lax.sort(
        (keys, inv, vals), num_keys=3, is_stable=False
    )
    ms = jnp.int32(1) - inv_s
    csum_v = jnp.cumsum(vs)
    csum_m = jnp.cumsum(ms)
    bound = (ks[1:] != ks[:-1]) | (inv_s[1:] != inv_s[:-1])
    is_last = jnp.concatenate([bound, jnp.ones(1, bool)])
    # run-end row of a REAL run is valid by construction; invalid runs
    # are excluded from compaction entirely (they sort last globally,
    # so real-run csum differences stay adjacent)
    is_real_end = is_last & (ms > 0)
    # the slot after a run's end is the NEXT run's first slot = its min
    vs_next = jnp.concatenate([vs[1:], jnp.zeros(1, vs.dtype)])
    sel_key = jnp.where(is_real_end, ks, sentinel)
    tiebreak = jnp.where(is_real_end, jnp.int32(0), jnp.int32(1))
    sel_v = jnp.where(is_real_end, csum_v, jnp.zeros((), csum_v.dtype))
    sel_m = jnp.where(is_real_end, csum_m, jnp.zeros((), csum_m.dtype))
    sel_max = jnp.where(is_real_end, vs, jnp.zeros((), vs.dtype))
    sel_next = jnp.where(is_real_end, vs_next, jnp.zeros((), vs.dtype))
    uniq, _, ends_v, ends_m, ends_max, ends_next = jax.lax.sort(
        (sel_key, tiebreak, sel_v, sel_m, sel_max, sel_next),
        num_keys=2, is_stable=False,
    )
    prev_v = jnp.concatenate([jnp.zeros(1, ends_v.dtype), ends_v[:-1]])
    prev_m = jnp.concatenate([jnp.zeros(1, ends_m.dtype), ends_m[:-1]])
    counts = (ends_m - prev_m).astype(jnp.int32)
    real = counts > 0
    counts = jnp.where(real, counts, 0)  # padding slots go negative
    sums = jnp.where(real, ends_v - prev_v, 0).astype(vals.dtype)
    maxs = jnp.where(real, ends_max, 0).astype(vals.dtype)
    # run 0's min is the globally first slot; run i's min is the value
    # right after run i-1's end (compacted runs are adjacent in the
    # sorted order, real runs first)
    mins = jnp.where(
        real, jnp.concatenate([vs[:1], ends_next[:-1]]), 0
    ).astype(vals.dtype)
    uniq = jnp.where(real, uniq, sentinel)
    n_unique = jnp.sum(real.astype(jnp.int32))
    return uniq, sums, counts, mins, maxs, n_unique
