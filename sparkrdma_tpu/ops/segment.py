"""Device-side keyed reductions: the combiner step as an XLA program.

The reference's aggregation runs on the CPU during the read path
(RdmaShuffleReader.scala:82-97, Spark's Aggregator); on TPU the
post-exchange combine is a device program: sort the received keys, find
segment boundaries, segment-sum the values — all static shapes with
sentinel padding.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def reduce_by_key_local(
    keys: jax.Array, vals: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Reduce (sum) values by key over one device's elements.

    Invalid slots must be PRE-MASKED by the caller: key == dtype max
    (the sentinel) and value == 0.  Valid entries may sit anywhere (they
    need not form a prefix — post-exchange buckets are row-scattered).

    Returns:
      (unique_keys, sums, n_unique): [n] arrays where the first n_unique
      slots hold each distinct real key and the sum of its values; the
      rest is sentinel (key dtype max, zero sums).
    """
    n = keys.shape[0]
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    # TPU-critical: scatter-free.  Sort pairs, then extract per-run totals
    # as differences of the value prefix-sum at run ends; compact the run
    # ends to the front with a second (cheap) sort instead of a scatter.
    ks, vs = jax.lax.sort((keys, vals), num_keys=1, is_stable=True)
    csum = jnp.cumsum(vs)
    is_last = jnp.concatenate(
        [ks[1:] != ks[:-1], jnp.ones(1, bool)]
    )  # last element of each run
    real_last = is_last & (ks != sentinel)
    sel_key = jnp.where(real_last, ks, sentinel)
    sel_end = jnp.where(real_last, csum, jnp.zeros((), csum.dtype))
    uniq, ends = jax.lax.sort((sel_key, sel_end), num_keys=1, is_stable=True)
    # runs are contiguous in ks, and uniq preserves key order, so each
    # run's sum = its end-csum minus the previous run's end-csum
    prev = jnp.concatenate([jnp.zeros(1, ends.dtype), ends[:-1]])
    is_real = uniq != sentinel
    sums = jnp.where(is_real, ends - prev, jnp.zeros((), vals.dtype)).astype(
        vals.dtype
    )
    n_unique = jnp.sum(is_real).astype(jnp.int32)
    return uniq, sums, n_unique
