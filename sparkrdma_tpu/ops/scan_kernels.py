"""One-pass Pallas scan kernels for the log-step fill/scan hot loops.

The forward fills and segmented scans in ``ops/segment.py`` /
``models/join.py`` are Hillis–Steele loops over full-length HBM arrays:
~log2(n) passes, each reading and writing every column (3-7 ms per use
at 4M rows — ~40% of a join probe).  They are all instances of one
associative recurrence over (flag, columns) tuples, so ONE sequential
pass can compute them: TPU Pallas grids execute in order, which makes
the classic block-scan-with-carry pattern exact —

  per grid step: load a [C, 128] block (the 1-D column reshaped
  row-major), run the log-step combine IN VMEM (VPU traffic, not HBM),
  fold in the running carry from SMEM-side scratch, write the block,
  update the carry.

HBM traffic drops from O(n log n) to O(n): one read + one write per
column.  Combine kinds:

- ``fill``: forward-fill columns from flagged positions (the probe
  fill of join.py and the run-end carry of segment.py).  Positions
  before the first flag keep an UNSPECIFIED column value with an
  unset output flag — exactly the contract consumers rely on (they
  mask by the returned flag).
- ``add`` / ``min`` / ``max``: inclusive segmented scan with ``flag``
  as segment heads (ops/segment.py ``segmented_scan``).

The kernels are dispatched only on TPU-family backends (including the
tunneled single-chip platform); every caller keeps the jnp log-step
path as the CPU/interpret fallback, and the interpret-mode tests pin
kernel semantics to the jnp reference.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
# rows of 128 lanes per grid block: 1024*128 elements = 512 KiB per
# int32 column in VMEM — small enough for several columns + scratch.
# Env-tunable for the on-chip sweep (tools/TPU_TODO.md); read once at
# import so compiled shapes stay consistent within a process.
import os as _os  # noqa: E402

BLOCK_ROWS = int(_os.environ.get("SPARKRDMA_TPU_SCAN_BLOCK_ROWS", 1024))
_BLOCK = BLOCK_ROWS * LANES

# columns longer than this use the kernel on TPU backends; below it the
# jnp log-step path wins (kernel launch + padding overhead)
MIN_KERNEL_ELEMS = 1 << 16


def use_scan_kernels() -> bool:
    """Kernel dispatch gate: TPU-family backends only (the tunneled
    single-chip platform registers as a distinct name).  Kill switch:
    set SPARKRDMA_TPU_DISABLE_SCAN_KERNELS=1 to force the jnp log-step
    paths (e.g. to bisect a Mosaic lowering issue)."""
    import os

    if os.environ.get("SPARKRDMA_TPU_DISABLE_SCAN_KERNELS"):
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def kernel_eligible(*cols) -> bool:
    """Dtype gate for the kernels: 64-bit integer columns (reachable
    only under ``jax_enable_x64``) stay on the jnp log-step paths —
    Mosaic's emulated 64-bit support is not something to bet the
    x64 join path on."""
    return all(np.dtype(c.dtype).itemsize <= 4 for c in cols)


def _identity(kind: str, dtype) -> np.generic:
    dt = np.dtype(dtype)
    if kind == "min":
        return (
            np.array(np.inf, dt) if np.issubdtype(dt, np.floating)
            else np.array(np.iinfo(dt).max, dt)
        )
    if kind == "max":
        return (
            np.array(-np.inf, dt) if np.issubdtype(dt, np.floating)
            else np.array(np.iinfo(dt).min, dt)
        )
    return np.zeros((), dt)  # add / fill


def _combine(kind: str, pf, pxs, cf, cxs):
    """combine(prev_aggregate, current_aggregate) for the (flag, cols)
    recurrence; prev = elements strictly earlier in scan order."""
    f = pf | cf
    if kind == "fill":
        xs = [jnp.where(cf, cx, px) for px, cx in zip(pxs, cxs)]
    elif kind == "add":
        xs = [jnp.where(cf, cx, px + cx) for px, cx in zip(pxs, cxs)]
    elif kind == "min":
        xs = [
            jnp.where(cf, cx, jnp.minimum(px, cx))
            for px, cx in zip(pxs, cxs)
        ]
    elif kind == "max":
        xs = [
            jnp.where(cf, cx, jnp.maximum(px, cx))
            for px, cx in zip(pxs, cxs)
        ]
    else:  # pragma: no cover - guarded by callers
        raise ValueError(f"unknown scan kind {kind!r}")
    return f, xs


def _flat_shift_one(x, s, fill):
    """Shift a [C, L] block by ``s`` positions along the FLATTENED
    row-major order (earlier elements move toward higher indices),
    filling vacated slots with ``fill``.  s must be < C * L."""
    C, L = x.shape
    fill = jnp.asarray(fill, x.dtype)
    rows, lanes = divmod(s, L)
    if rows:
        pad = jnp.full((rows, L), fill, x.dtype)
        x = jnp.concatenate([pad, x[: C - rows]], axis=0)
    if lanes:
        tail = x[:, L - lanes :]
        down = jnp.concatenate(
            [jnp.full((1, lanes), fill, x.dtype), tail[:-1]], axis=0
        )
        x = jnp.concatenate([down, x[:, : L - lanes]], axis=1)
    return x


def _scan_kernel_body(kind, n_cols, idents, flag_ref, *refs):
    col_refs = refs[:n_cols]
    out_flag_ref = refs[n_cols]
    out_refs = refs[n_cols + 1 : 2 * n_cols + 1]
    scr_flag = refs[2 * n_cols + 1]
    scr_cols = refs[2 * n_cols + 2 :]

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        scr_flag[0, 0] = jnp.int32(0)
        for scr, ident in zip(scr_cols, idents):
            scr[0, 0] = jnp.asarray(ident, scr.dtype)

    f = flag_ref[...] != 0
    xs = [r[...] for r in col_refs]
    s = 1
    while s < _BLOCK:
        pf = _flat_shift_one(f, s, False)
        pxs = [
            _flat_shift_one(x, s, ident)
            for x, ident in zip(xs, idents)
        ]
        f, xs = _combine(kind, pf, pxs, f, xs)
        s <<= 1
    # fold the running carry (aggregate of every element before this
    # block) in as "prev" for the whole block
    cf = (scr_flag[0, 0] != 0) & jnp.ones_like(f)
    cxs = [
        jnp.full_like(x, scr[0, 0]) for x, scr in zip(xs, scr_cols)
    ]
    f, xs = _combine(kind, cf, cxs, f, xs)
    out_flag_ref[...] = f.astype(jnp.int32)
    for out, x in zip(out_refs, xs):
        out[...] = x
    scr_flag[0, 0] = f[BLOCK_ROWS - 1, LANES - 1].astype(jnp.int32)
    for scr, x in zip(scr_cols, xs):
        scr[0, 0] = x[BLOCK_ROWS - 1, LANES - 1]


@functools.partial(
    jax.jit, static_argnames=("kind", "dtypes", "n_pad", "interpret")
)
def _scan_padded(kind, dtypes, n_pad, interpret, flag_i32, *cols):
    """Run the kernel over already padded/reshaped [R, 128] arrays."""
    n_cols = len(cols)
    idents = tuple(_identity(kind, dt) for dt in dtypes)
    R = flag_i32.shape[0]
    grid = (R // BLOCK_ROWS,)
    blk = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    kernel = functools.partial(_scan_kernel_body, kind, n_cols, idents)
    out_flag, *out_cols = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[blk] * (1 + n_cols),
        out_specs=[blk] * (1 + n_cols),
        out_shape=[jax.ShapeDtypeStruct(flag_i32.shape, jnp.int32)]
        + [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in cols],
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.int32)]
        + [pltpu.SMEM((1, 1), np.dtype(dt)) for dt in dtypes],
        interpret=interpret,
    )(flag_i32, *cols)
    return out_flag, out_cols


def cumsum_1d(vals: jax.Array) -> jax.Array:
    """``jnp.cumsum`` through the one-pass add kernel on TPU backends
    (XLA lowers cumulative ops to logarithmic passes too); jnp
    elsewhere or below the size threshold."""
    n = int(vals.shape[0])
    if (n >= MIN_KERNEL_ELEMS and kernel_eligible(vals)
            and use_scan_kernels()):
        _f, (out,) = scan_flagged(
            "add", jnp.zeros(n, bool), (vals,)
        )
        return out
    return jnp.cumsum(vals)


def scan_flagged(
    kind: str,
    flag: jax.Array,
    cols: Sequence[jax.Array],
    interpret: bool = False,
) -> Tuple[jax.Array, list]:
    """One-pass (flag, columns) scan over 1-D arrays; see module docs.

    Returns ``(flag_out: bool[n], cols_out)`` with the same semantics
    as the jnp log-step implementations it replaces.  Works inside jit
    (shapes are static); pad/reshape happens in traced ops.
    """
    n = int(flag.shape[0])
    cols = list(cols)
    dtypes = tuple(np.dtype(c.dtype).name for c in cols)
    n_pad = (-n) % _BLOCK
    idents = [_identity(kind, dt) for dt in dtypes]
    f = flag.astype(jnp.int32)
    if n_pad:
        f = jnp.concatenate([f, jnp.zeros(n_pad, jnp.int32)])
        cols = [
            jnp.concatenate(
                [c, jnp.full((n_pad,), ident, c.dtype)]
            )
            for c, ident in zip(cols, idents)
        ]
    f2 = f.reshape(-1, LANES)
    cols2 = [c.reshape(-1, LANES) for c in cols]
    out_flag, out_cols = _scan_padded(
        kind, dtypes, n_pad, interpret, f2, *cols2
    )
    out_flag = out_flag.reshape(-1)[:n] != 0
    outs = [c.reshape(-1)[:n] for c in out_cols]
    return out_flag, outs
