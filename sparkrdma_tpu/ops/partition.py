"""On-device partitioning: the map side of the shuffle, as XLA programs.

The reference's map side partitions records by ``dependency.partitioner``
on the CPU while sorting/spilling (RdmaWrapperShuffleWriter.scala:126-128
reusing Spark's sort-shuffle writers).  On TPU the records for the
array-native path already live in HBM, so partitioning is a device
program: compute a partition id per element (hash or range), then bucket
elements into a ``[n_parts, capacity]`` layout that all_to_all can move
— static shapes, so buckets are capacity-padded and overflow is
*detected* (count > capacity) rather than spilled; callers re-run with a
larger capacity on overflow (the ``maxAggBlock``-style cap inverted for
SPMD).

Everything here is jit-compatible: no data-dependent shapes, no Python
branches on traced values.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def hash_partition_ids(keys: jax.Array, n_parts: int) -> jax.Array:
    """Partition id per key via an avalanching integer hash (the
    HashPartitioner analog).  Works on any integer dtype; floats/other
    dtypes should be bitcast by the caller."""
    x = keys.astype(jnp.uint32)
    # murmur3-style finalizer: full avalanche so consecutive keys spread
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x % jnp.uint32(n_parts)).astype(jnp.int32)


def make_range_splitters(
    sample: jax.Array, n_parts: int
) -> jax.Array:
    """n_parts-1 ascending splitters from a key sample (the
    RangePartitioner analog used by sortByKey): equal-frequency
    quantiles of the sample."""
    sorted_sample = jnp.sort(sample)
    n = sorted_sample.shape[0]
    # quantile positions 1/n_parts .. (n_parts-1)/n_parts
    idx = (jnp.arange(1, n_parts) * n) // n_parts
    return sorted_sample[jnp.clip(idx, 0, n - 1)]


def range_partition_ids(keys: jax.Array, splitters: jax.Array) -> jax.Array:
    """Partition id per key given ascending splitters:
    id = #splitters <= key (so part 0 gets keys < splitters[0])."""
    return jnp.searchsorted(splitters, keys, side="right").astype(jnp.int32)


def partition_to_buckets(
    part_ids: jax.Array,
    values: Tuple[jax.Array, ...],
    n_parts: int,
    capacity: int,
    fill_values: Optional[Tuple] = None,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Bucket elements into a [n_parts, capacity] padded layout.

    Args:
      part_ids: int32[n] destination partition per element.
      values:   tuple of arrays, each [n, ...], permuted together (e.g.
                (keys, vals) — the sort-shuffle's record columns).
      n_parts:  number of buckets.
      capacity: max elements per bucket (static). Overflowing elements are
                DROPPED from the buckets; detect via counts > capacity.
      fill_values: per-array pad value (default: dtype max for the first
                array — a +inf-style sentinel that sorts last — and 0 for
                the rest).

    Returns:
      (bucketed, counts): bucketed[i] is [n_parts, capacity, ...],
      counts is int32[n_parts] TRUE element counts (may exceed capacity —
      that signals overflow; the caller re-runs with larger capacity).
    """
    n = part_ids.shape[0]
    if fill_values is None:
        fill_values = tuple(
            _default_fill(v.dtype) if i == 0 else jnp.zeros((), v.dtype)
            for i, v in enumerate(values)
        )
    if n == 0:
        # empty local shard (legal under SPMD): all-fill buckets
        counts = jnp.zeros((n_parts,), jnp.int32)
        bucketed = tuple(
            jnp.full((n_parts, capacity) + v.shape[1:], fill, v.dtype)
            for v, fill in zip(values, fill_values)
        )
        return bucketed, counts
    # TPU-critical: NO scatters on the hot path — random scatter is ~30x
    # slower than sort+gather on TPU.  One multi-operand sort (unstable:
    # only the grouping matters, and unstable is ~1.5x faster on TPU)
    # groups elements by destination; buckets are then near-sequential
    # gathers at starts[p] + j.  1-D values ride the sort directly;
    # multi-dim values are gathered through the sorted permutation
    # (lax.sort requires equal operand shapes).
    flat_vals = [v for v in values if v.ndim == 1]
    nd_vals = [v for v in values if v.ndim > 1]
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(
        (part_ids.astype(jnp.int32),)
        + ((iota,) if nd_vals else ())
        + tuple(flat_vals),
        num_keys=1, is_stable=False,
    )
    sorted_ids = sorted_ops[0]
    perm = sorted_ops[1] if nd_vals else None
    sorted_flat = sorted_ops[2:] if nd_vals else sorted_ops[1:]
    edges = jnp.searchsorted(
        sorted_ids, jnp.arange(n_parts + 1, dtype=jnp.int32)
    )  # [n_parts+1] bucket boundaries in the sorted order
    counts = (edges[1:] - edges[:-1]).astype(jnp.int32)
    starts = edges[:-1].astype(jnp.int32)
    slot = jnp.arange(capacity, dtype=jnp.int32)
    idx = starts[:, None] + slot[None, :]              # [n_parts, capacity]
    # overflow entries simply fall outside the capacity window
    valid = slot[None, :] < jnp.minimum(counts, capacity)[:, None]
    bucketed = []
    flat_iter = iter(sorted_flat)
    for v, fill in zip(values, fill_values):
        if v.ndim == 1:
            # buckets are CONTIGUOUS runs of the sorted order: copy them
            # with dynamic_slice per bucket instead of fancy-indexed
            # gather — the general TPU gather costs ~30x the
            # bandwidth-bound copy (same fix as the TeraSort windows)
            b = _window_copy(next(flat_iter), starts, n_parts, capacity)
            b = jnp.where(valid, b, jnp.asarray(fill, v.dtype))
        else:
            gather_idx = jnp.clip(idx, 0, n - 1)
            b = v[perm[gather_idx]]                    # [n_parts, capacity, ...]
            mask = valid.reshape(valid.shape + (1,) * (v.ndim - 1))
            b = jnp.where(mask, b, jnp.asarray(fill, v.dtype))
        bucketed.append(b)
    return tuple(bucketed), counts


def partition_to_buckets_dropping(
    part_ids: jax.Array,
    keep: jax.Array,
    values: Tuple[jax.Array, ...],
    n_parts: int,
    capacity: int,
    fill_values: Optional[Tuple] = None,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """:func:`partition_to_buckets` with a TRASH bucket: rows whose
    ``keep`` (bool) is false route to bucket id ``n_parts``, which is
    sliced off the outputs and the counts.  Dropped rows therefore
    consume zero real capacity and can neither displace a real record
    nor signal a false overflow — routing padding to a real (home)
    bucket overflowed on heavily padded streams (post-join validity
    masks).  The slice happens BEFORE any exchange and the trash
    bucket is excluded from overflow accounting by construction.
    """
    ids = jnp.where(keep, part_ids.astype(jnp.int32), jnp.int32(n_parts))
    bucketed, counts = partition_to_buckets(
        ids, values, n_parts + 1, capacity, fill_values
    )
    return tuple(b[:n_parts] for b in bucketed), counts[:n_parts]


def bucketize_segments(
    part_ids: jax.Array,
    values: Tuple[jax.Array, ...],
    n_parts: int,
    capacity: int,
    fill_values: Optional[Tuple] = None,
    sort_within: bool = False,
) -> Tuple[Tuple[jax.Array, ...], jax.Array, jax.Array]:
    """Partition prep for the device-native exchange: bucketize PLUS
    the segment offsets the padded exchange framing consumes, all in
    one jittable program so the map side never leaves the device
    between partitioning and the collective.

    Returns ``(bucketed, counts, offsets)`` where ``bucketed``/
    ``counts`` are exactly :func:`partition_to_buckets` and ``offsets``
    is the int32 ``[n_parts + 1]`` EXCLUSIVE prefix sum of the
    capacity-clamped counts — element ``p``'s real records occupy
    ``[offsets[p], offsets[p + 1])`` of the compacted stream, which is
    the ``row_offsets`` contract of the exchange plan computed on
    device instead of from a host lengths pass.

    ``sort_within=True`` additionally sorts each bucket by the first
    value column (1-D columns only — the padded layout the collective
    ships), so receivers get per-source runs that merge instead of
    re-sort; pad slots carry the dtype-max fill and stay at the tail.
    """
    bucketed, counts = partition_to_buckets(
        part_ids, values, n_parts, capacity, fill_values
    )
    clamped = jnp.minimum(counts, capacity)
    offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(clamped).astype(jnp.int32)
    ])
    if sort_within:
        if any(b.ndim != 2 for b in bucketed):
            raise ValueError(
                "sort_within requires 1-D value columns (buckets are "
                "[n_parts, capacity]); gather multi-dim payloads after "
                "the key sort instead"
            )
        bucketed = jax.lax.sort(
            tuple(bucketed), dimension=1, num_keys=1, is_stable=False
        )
    return tuple(bucketed), counts, offsets


def _window_copy(sorted_arr: jax.Array, starts: jax.Array,
                 n_parts: int, capacity: int) -> jax.Array:
    """Copy n_parts contiguous windows [starts[p], starts[p]+capacity)
    of ``sorted_arr`` into a [n_parts, capacity] layout with sequential
    dynamic_slice reads.  The tail is padded so slices never clamp; the
    init buffer is broadcast from the data so it carries the same
    device-varying type under shard_map."""
    src = jnp.concatenate([
        sorted_arr, jnp.zeros((capacity,), sorted_arr.dtype)
    ])
    init = jnp.broadcast_to(src[:1], (n_parts, capacity))

    def fill_fn(p, buf):
        w = jax.lax.dynamic_slice(src, (starts[p],), (capacity,))
        return jax.lax.dynamic_update_slice(buf, w[None], (p, 0))

    return jax.lax.fori_loop(0, n_parts, fill_fn, init)


def _default_fill(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)
