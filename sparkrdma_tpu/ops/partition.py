"""On-device partitioning: the map side of the shuffle, as XLA programs.

The reference's map side partitions records by ``dependency.partitioner``
on the CPU while sorting/spilling (RdmaWrapperShuffleWriter.scala:126-128
reusing Spark's sort-shuffle writers).  On TPU the records for the
array-native path already live in HBM, so partitioning is a device
program: compute a partition id per element (hash or range), then bucket
elements into a ``[n_parts, capacity]`` layout that all_to_all can move
— static shapes, so buckets are capacity-padded and overflow is
*detected* (count > capacity) rather than spilled; callers re-run with a
larger capacity on overflow (the ``maxAggBlock``-style cap inverted for
SPMD).

Everything here is jit-compatible: no data-dependent shapes, no Python
branches on traced values.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def hash_partition_ids(keys: jax.Array, n_parts: int) -> jax.Array:
    """Partition id per key via an avalanching integer hash (the
    HashPartitioner analog).  Works on any integer dtype; floats/other
    dtypes should be bitcast by the caller."""
    x = keys.astype(jnp.uint32)
    # murmur3-style finalizer: full avalanche so consecutive keys spread
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x % jnp.uint32(n_parts)).astype(jnp.int32)


def make_range_splitters(
    sample: jax.Array, n_parts: int
) -> jax.Array:
    """n_parts-1 ascending splitters from a key sample (the
    RangePartitioner analog used by sortByKey): equal-frequency
    quantiles of the sample."""
    sorted_sample = jnp.sort(sample)
    n = sorted_sample.shape[0]
    # quantile positions 1/n_parts .. (n_parts-1)/n_parts
    idx = (jnp.arange(1, n_parts) * n) // n_parts
    return sorted_sample[jnp.clip(idx, 0, n - 1)]


def range_partition_ids(keys: jax.Array, splitters: jax.Array) -> jax.Array:
    """Partition id per key given ascending splitters:
    id = #splitters <= key (so part 0 gets keys < splitters[0])."""
    return jnp.searchsorted(splitters, keys, side="right").astype(jnp.int32)


def partition_to_buckets(
    part_ids: jax.Array,
    values: Tuple[jax.Array, ...],
    n_parts: int,
    capacity: int,
    fill_values: Optional[Tuple] = None,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Bucket elements into a [n_parts, capacity] padded layout.

    Args:
      part_ids: int32[n] destination partition per element.
      values:   tuple of arrays, each [n, ...], permuted together (e.g.
                (keys, vals) — the sort-shuffle's record columns).
      n_parts:  number of buckets.
      capacity: max elements per bucket (static). Overflowing elements are
                DROPPED from the buckets; detect via counts > capacity.
      fill_values: per-array pad value (default: dtype max for the first
                array — a +inf-style sentinel that sorts last — and 0 for
                the rest).

    Returns:
      (bucketed, counts): bucketed[i] is [n_parts, capacity, ...],
      counts is int32[n_parts] TRUE element counts (may exceed capacity —
      that signals overflow; the caller re-runs with larger capacity).
    """
    n = part_ids.shape[0]
    counts = jnp.bincount(part_ids, length=n_parts).astype(jnp.int32)
    # stable sort groups elements by destination, preserving order
    order = jnp.argsort(part_ids, stable=True)
    sorted_ids = part_ids[order]
    # position of each element within its bucket
    starts = jnp.searchsorted(sorted_ids, jnp.arange(n_parts, dtype=sorted_ids.dtype))
    pos = jnp.arange(n) - starts[sorted_ids]
    in_cap = pos < capacity
    # overflow entries scatter out-of-bounds and are dropped
    flat_dest = jnp.where(
        in_cap, sorted_ids * capacity + pos, n_parts * capacity
    )
    if fill_values is None:
        fill_values = tuple(
            _default_fill(v.dtype) if i == 0 else jnp.zeros((), v.dtype)
            for i, v in enumerate(values)
        )
    bucketed = []
    for v, fill in zip(values, fill_values):
        sv = v[order]
        flat_shape = (n_parts * capacity,) + v.shape[1:]
        out = jnp.full(flat_shape, fill, dtype=v.dtype)
        out = out.at[flat_dest].set(sv, mode="drop")
        bucketed.append(out.reshape((n_parts, capacity) + v.shape[1:]))
    return tuple(bucketed), counts


def _default_fill(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)
