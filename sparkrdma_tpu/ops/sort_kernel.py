"""Experimental Pallas in-block bitonic sort for (key, value) pairs.

XLA's ``lax.sort`` is the cost ceiling of every sort-bound bench
(terasort, the join probes, the keyed reductions).  This kernel sorts
fixed-size blocks entirely in VMEM with a bitonic network — one HBM
read + one write per block — as the building block of a two-phase
(sort blocks → range-bucket → sort buckets) full sort.

Pairing uses the standard XOR network: at distance ``d`` element ``i``
exchanges with ``i ^ d``.  On the [R, 128] row-major block layout a
distance below 128 is a lane XOR (two ``pltpu.roll``s along lanes +
select) and a distance that is a multiple of 128 is a row XOR (rolls
along sublanes), so no general permutes are needed.  Direction bits and
pair order come from 2-D ``broadcasted_iota``.  Ties break by flat
index, which keeps the two sides of every compare-exchange consistent
(the pair moves key and value together).

UNVALIDATED ON REAL TPU SILICON: the chip was unreachable when this
landed, so only interpret-mode semantics are pinned (tests).  Nothing
dispatches to it by default — call sites must opt in after
``tools/profile_tpu_sort.py`` shows it beating ``lax.sort`` on chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANES = 128


class BucketOverflowError(RuntimeError):
    """A bucket exceeded its capacity in sort_pairs_full: the sorted
    output is garbage (see the overflow contract in its docstring)."""


def bucket_cap(n: int, n_buckets: int = 16,
               cap_factor: float = 1.4) -> int:
    """Per-bucket row capacity sort_pairs_full allocates for ``n``
    rows; a bucket fill above this invalidates the whole result."""
    cap = int(np.ceil(n / n_buckets * cap_factor))
    return (cap + LANES - 1) // LANES * LANES


def _partner(x, d, R, interpret):
    """partner[i] = x[i ^ d] over the flat row-major [R, 128] order."""
    if d < LANES:
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 1)
        take_fwd = (lane & d) == 0
        if interpret:
            fwd = jnp.roll(x, LANES - d, axis=1)
            bwd = jnp.roll(x, d, axis=1)
        else:
            from jax.experimental.pallas import tpu as pltpu

            # pltpu.roll requires non-negative shifts: a circular
            # backward roll by d is a forward roll by size - d
            fwd = pltpu.roll(x, LANES - d, 1)
            bwd = pltpu.roll(x, d, 1)
        return jnp.where(take_fwd, fwd, bwd)
    m = d // LANES
    row = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
    take_fwd = (row & m) == 0
    if interpret:
        fwd = jnp.roll(x, R - m, axis=0)
        bwd = jnp.roll(x, m, axis=0)
    else:
        from jax.experimental.pallas import tpu as pltpu

        fwd = pltpu.roll(x, R - m, 0)
        bwd = pltpu.roll(x, m, 0)
    return jnp.where(take_fwd, fwd, bwd)


def _block_sort_body(R, interpret, k_ref, v_ref, ok_ref, ov_ref):
    B = R * LANES
    k = k_ref[...]
    v = v_ref[...]
    row = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 1)
    flat = row * LANES + lane
    n_stages = B.bit_length() - 1
    for stage in range(1, n_stages + 1):
        # ascending iff bit ``stage`` of the flat index is clear; the
        # final stage has that bit clear everywhere → fully ascending
        up = (flat & (1 << stage)) == 0 if stage < n_stages else (
            jnp.ones((R, LANES), bool)
        )
        for j in range(stage - 1, -1, -1):
            d = 1 << j
            pk = _partner(k, d, R, interpret)
            pv = _partner(v, d, R, interpret)
            is_lower = (flat & d) == 0
            # pair-consistent "my element is the smaller": ties go to
            # the lower flat index
            mine_small = (k < pk) | ((k == pk) & is_lower)
            take_min = up == is_lower
            want_mine = take_min == mine_small
            k = jnp.where(want_mine, k, pk)
            v = jnp.where(want_mine, v, pv)
    ok_ref[...] = k
    ov_ref[...] = v


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret")
)
def sort_pairs_blocks(keys, vals, block_rows: int = 1024,
                      interpret: bool = False):
    """Sort (keys, vals) within consecutive blocks of
    ``block_rows * 128`` elements (each block independently ascending
    by key).  Input length must be a multiple of the block size;
    dtypes: any 32-bit integer keys (compared in their own dtype).
    """
    n = int(keys.shape[0])
    B = block_rows * LANES
    if n % B:
        raise ValueError(f"length {n} not a multiple of block {B}")
    if B & (B - 1):
        raise ValueError(f"block size {B} must be a power of two")
    R = block_rows
    k2 = keys.reshape(-1, LANES)
    v2 = vals.reshape(-1, LANES)
    grid = (n // B,)
    blk = pl.BlockSpec((R, LANES), lambda i: (i, 0))
    kernel = functools.partial(_block_sort_body, R, interpret)
    ok, ov = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[blk, blk],
        out_specs=[blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct(k2.shape, k2.dtype),
            jax.ShapeDtypeStruct(v2.shape, v2.dtype),
        ],
        interpret=interpret,
    )(k2, v2)
    return ok.reshape(-1), ov.reshape(-1)


def sort_pairs_full(keys, vals, block_rows: int = 1024,
                    n_buckets: int = 16, cap_factor: float = 1.4,
                    interpret: bool = False):
    """Full (key, value) sort: Pallas block sorts → equal-frequency
    splitters from block quantiles → window-copy bucket assembly (the
    terasort pattern on one chip) → batched bucket sort.  Returns
    ``(keys', vals', valid, fn, overflow)`` of padded length
    ``n_buckets * cap`` with ``valid`` marking real slots (padding
    sorts to each bucket's tail).

    OVERFLOW CONTRACT: when splitters are badly skewed a bucket can
    receive more than ``cap = bucket_cap(n, n_buckets, cap_factor)``
    rows; the assembly then clamps its writes and ALL outputs are
    garbage (earlier rows silently overwritten, invalid slots marked
    valid).  Callers MUST verify ``overflow <= bucket_cap(...)``
    (device-side, no sync needed: it is the max per-bucket fill) and
    discard the result or retry with a higher ``cap_factor`` when it
    fails — or call :func:`sort_pairs_full_checked`, which raises
    ``BucketOverflowError``.

    Exactness is pinned by tests vs numpy; wire into the sorter only
    after on-chip profiling (module docstring).
    """
    n = int(keys.shape[0])
    B = block_rows * LANES
    if n % B or n == 0:
        raise ValueError(f"length {n} must be a positive multiple of {B}")
    nb = n // B
    sk, sv = sort_pairs_blocks(
        keys, vals, block_rows=block_rows, interpret=interpret
    )
    kb = sk.reshape(nb, B)
    vb = sv.reshape(nb, B)
    # equal-frequency splitters from exact per-block quantiles
    S = min(512, B)
    sample = kb[:, (jnp.arange(S) * B) // S].reshape(-1)
    ssorted = jnp.sort(sample)
    idx = (jnp.arange(1, n_buckets) * ssorted.shape[0]) // n_buckets
    splitters = ssorted[idx]
    edges = jax.vmap(
        lambda row: jnp.searchsorted(row, splitters, side="right")
    )(kb).astype(jnp.int32)                       # [nb, n_buckets-1]
    zeros = jnp.zeros((nb, 1), jnp.int32)
    fulls = jnp.full((nb, 1), B, jnp.int32)
    edges = jnp.concatenate([zeros, edges, fulls], axis=1)
    counts = edges[:, 1:] - edges[:, :-1]         # [nb, n_buckets]
    starts = edges[:, :-1]
    cap = bucket_cap(n, n_buckets, cap_factor)
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    bucket_off = jnp.cumsum(counts, axis=0) - counts  # offset of block b
    kp = jnp.concatenate(
        [kb, jnp.full((nb, cap), sentinel, kb.dtype)], axis=1
    )
    vp = jnp.concatenate([vb, jnp.zeros((nb, cap), vb.dtype)], axis=1)

    def fill(i, bufs):
        fk, fv, fn = bufs
        b = i // n_buckets
        dst = i % n_buckets
        wk = jax.lax.dynamic_slice(kp[b], (starts[b, dst],), (cap,))
        wv = jax.lax.dynamic_slice(vp[b], (starts[b, dst],), (cap,))
        off = bucket_off[b, dst]
        c = counts[b, dst]
        slot = jnp.arange(cap, dtype=jnp.int32)
        old_k = jax.lax.dynamic_slice(fk[dst], (off,), (cap,))
        old_v = jax.lax.dynamic_slice(fv[dst], (off,), (cap,))
        take = slot < c
        fk = jax.lax.dynamic_update_slice(
            fk, jnp.where(take, wk, old_k)[None], (dst, off)
        )
        fv = jax.lax.dynamic_update_slice(
            fv, jnp.where(take, wv, old_v)[None], (dst, off)
        )
        fn = fn.at[dst].add(c)
        return fk, fv, fn

    fk0 = jnp.full((n_buckets, cap + cap), sentinel, kb.dtype)
    fv0 = jnp.zeros((n_buckets, cap + cap), vb.dtype)
    fn0 = jnp.zeros((n_buckets,), jnp.int32)
    fk, fv, fn = jax.lax.fori_loop(
        0, nb * n_buckets, fill, (fk0, fv0, fn0)
    )
    overflow = jnp.max(fn)
    fk = fk[:, :cap]
    fv = fv[:, :cap]
    # bucket sort: padding carries the sentinel and a validity tiebreak
    slot = jnp.arange(cap, dtype=jnp.int32)
    invalid = (slot[None, :] >= fn[:, None]).astype(jnp.int32)
    fk = jnp.where(invalid > 0, sentinel, fk)
    fv = jnp.where(invalid > 0, jnp.zeros((), fv.dtype), fv)
    ok, oinv, ov = jax.lax.sort(
        (fk, invalid, fv), num_keys=2, is_stable=False, dimension=1
    )
    valid = jnp.int32(1) - oinv
    return (
        ok.reshape(-1), ov.reshape(-1), valid.reshape(-1),
        fn, overflow,
    )


def sort_pairs_full_checked(keys, vals, block_rows: int = 1024,
                            n_buckets: int = 16,
                            cap_factor: float = 1.4,
                            interpret: bool = False):
    """sort_pairs_full with the overflow contract enforced: syncs the
    per-bucket max fill to the host and raises
    :class:`BucketOverflowError` instead of returning garbage.  Use the
    raw function + a device-side ``overflow <= bucket_cap(...)`` check
    when the sync is too expensive."""
    ok, ov, valid, fn, overflow = sort_pairs_full(
        keys, vals, block_rows=block_rows, n_buckets=n_buckets,
        cap_factor=cap_factor, interpret=interpret,
    )
    cap = bucket_cap(int(keys.shape[0]), n_buckets, cap_factor)
    ovf = int(jax.device_get(overflow))
    if ovf > cap:
        raise BucketOverflowError(
            f"bucket fill {ovf} > cap {cap} "
            f"(n={int(keys.shape[0])}, n_buckets={n_buckets}, "
            f"cap_factor={cap_factor}) — retry with a higher cap_factor"
        )
    return ok, ov, valid, fn, overflow
