"""Reader-side observability: remote-fetch latency histograms.

Analog of RdmaShuffleReaderStats (RdmaShuffleReaderStats.scala:29-79):
per-remote-host and global fixed-bucket latency histograms, printed at
manager stop.  Bucket geometry from conf
(fetchTimeBucketSizeInMs × fetchTimeNumBuckets; last bucket is
open-ended).

The bespoke histogram storage is retired onto the metrics registry
(metrics/registry.py): each per-host histogram IS a registry
``shuffle_fetch_latency_ms`` instrument (created with ``force=True``,
since these stats have their own conf gate), so fetch latencies appear
in Prometheus/JSON snapshots; this module keeps only the
print-at-stop FORMAT as a view over those instruments.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import Histogram, get_registry

logger = logging.getLogger(__name__)


class FetchHistogram:
    """Fixed linear-bucket view over a registry histogram.

    Bucket ``i`` covers ``[i*bucket_ms, (i+1)*bucket_ms)`` — a sample
    exactly on an edge lands in the upper bucket (the reference's
    ``latency // bucket_ms`` placement) — with the last bucket
    open-ended.  ``hist`` may be a shared registry instrument; when
    omitted a standalone one is created (tests)."""

    def __init__(self, bucket_ms: int, num_buckets: int,
                 hist: Optional[Histogram] = None):
        self.bucket_ms = bucket_ms
        self.num_buckets = num_buckets
        edges = [float(bucket_ms * (i + 1)) for i in range(num_buckets - 1)]
        if hist is None:
            hist = Histogram("shuffle_fetch_latency_ms", edges=edges)
        elif list(hist.edges) != edges:
            raise ValueError(
                f"histogram edges {hist.edges} do not match bucket "
                f"geometry {bucket_ms}ms x {num_buckets}"
            )
        self._hist = hist

    def add_sample(self, latency_ms: float) -> None:
        self._hist.observe(latency_ms)

    @property
    def total(self) -> int:
        return self._hist.count

    def to_string(self) -> str:
        counts = self._hist.counts
        parts = []
        for i, c in enumerate(counts):
            lo = i * self.bucket_ms
            if i == self.num_buckets - 1:
                parts.append(f"[{lo}ms+]: {c}")
            else:
                parts.append(f"[{lo}-{lo + self.bucket_ms}ms]: {c}")
        return ", ".join(parts)


class ShuffleReaderStats:
    """Per-remote-host fetch-latency histograms + a global one."""

    def __init__(self, conf: TpuShuffleConf):
        self.conf = conf
        self._bucket_ms = conf.fetch_time_bucket_size_ms
        self._num_buckets = conf.fetch_time_num_buckets
        self._global = self._make("all")
        self._per_host: Dict[str, FetchHistogram] = {}
        self._lock = threading.Lock()  # lock-order: 90

    def _make(self, host: str) -> FetchHistogram:
        edges = [
            float(self._bucket_ms * (i + 1))
            for i in range(self._num_buckets - 1)
        ]
        # geometry rides in the labels: instruments are process-global,
        # and a registry lookup only applies ``edges`` on FIRST
        # creation — without the geometry key, a second manager with a
        # different fetchTime bucket conf in the same process would get
        # the old instrument back and fail FetchHistogram's edge check
        inst = get_registry().histogram(
            "shuffle_fetch_latency_ms", edges=edges, force=True,
            host=host, bucket_ms=self._bucket_ms,
            buckets=self._num_buckets,
        )
        return FetchHistogram(self._bucket_ms, self._num_buckets, hist=inst)

    def update(self, host: str, latency_ms: float) -> None:
        with self._lock:
            hist = self._per_host.get(host)
            if hist is None:
                hist = self._per_host.setdefault(host, self._make(host))
        hist.add_sample(latency_ms)
        self._global.add_sample(latency_ms)

    def print_stats(self) -> str:
        """Log and return the formatted histograms (called at manager
        stop, reference RdmaShuffleManager.scala:349-351)."""
        lines = [f"remote fetch histogram (all hosts): {self._global.to_string()}"]
        with self._lock:
            hosts = dict(self._per_host)
        for host, hist in sorted(hosts.items()):
            lines.append(f"  {host}: {hist.to_string()}")
        text = "\n".join(lines)
        logger.info(text)
        return text
