"""Reader-side observability: remote-fetch latency histograms.

Analog of RdmaShuffleReaderStats (RdmaShuffleReaderStats.scala:29-79):
per-remote-host and global fixed-bucket latency histograms, printed at
manager stop.  Bucket geometry from conf
(fetchTimeBucketSizeInMs × fetchTimeNumBuckets; last bucket is
open-ended).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

from sparkrdma_tpu.conf import TpuShuffleConf

logger = logging.getLogger(__name__)


class FetchHistogram:
    def __init__(self, bucket_ms: int, num_buckets: int):
        self.bucket_ms = bucket_ms
        self.num_buckets = num_buckets
        self._counts = [0] * num_buckets
        self._lock = threading.Lock()

    def add_sample(self, latency_ms: float) -> None:
        idx = min(int(latency_ms // self.bucket_ms), self.num_buckets - 1)
        with self._lock:
            self._counts[idx] += 1

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._counts)

    def to_string(self) -> str:
        with self._lock:
            counts = list(self._counts)
        parts = []
        for i, c in enumerate(counts):
            lo = i * self.bucket_ms
            if i == self.num_buckets - 1:
                parts.append(f"[{lo}ms+]: {c}")
            else:
                parts.append(f"[{lo}-{lo + self.bucket_ms}ms]: {c}")
        return ", ".join(parts)


class ShuffleReaderStats:
    """Per-remote-host fetch-latency histograms + a global one."""

    def __init__(self, conf: TpuShuffleConf):
        self.conf = conf
        self._bucket_ms = conf.fetch_time_bucket_size_ms
        self._num_buckets = conf.fetch_time_num_buckets
        self._global = FetchHistogram(self._bucket_ms, self._num_buckets)
        self._per_host: Dict[str, FetchHistogram] = {}
        self._lock = threading.Lock()

    def update(self, host: str, latency_ms: float) -> None:
        with self._lock:
            hist = self._per_host.get(host)
            if hist is None:
                hist = self._per_host.setdefault(
                    host, FetchHistogram(self._bucket_ms, self._num_buckets)
                )
        hist.add_sample(latency_ms)
        self._global.add_sample(latency_ms)

    def print_stats(self) -> str:
        """Log and return the formatted histograms (called at manager
        stop, reference RdmaShuffleManager.scala:349-351)."""
        lines = [f"remote fetch histogram (all hosts): {self._global.to_string()}"]
        with self._lock:
            hosts = dict(self._per_host)
        for host, hist in sorted(hosts.items()):
            lines.append(f"  {host}: {hist.to_string()}")
        text = "\n".join(lines)
        logger.info(text)
        return text
