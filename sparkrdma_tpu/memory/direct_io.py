"""O_DIRECT append writers: the spill/commit disk path.

The reference's 175 GB result streams map outputs through the page
cache and lets the NIC read them back (RdmaMappedFile.java:95-171) —
on its bare-metal hosts writeback keeps up with the disks.  On the
virtualized builder hosts this framework targets, buffered writeback
throttles to ~15-20% of the device's bandwidth once dirty-page limits
kick in (measured: 142 MB/s buffered vs 821 MB/s O_DIRECT on the same
VM — BASELINE.md round-3/4 notes), so GB-scale spills and file-backed
commits write through :class:`DirectAppender` instead:

- opens with ``O_DIRECT`` when the directory's filesystem supports it
  (probed once per directory; tmpfs and exotic mounts fall back to
  buffered writes transparently),
- copies payload into page-aligned anonymous-mmap bounce buffers and
  writes only block-aligned spans (the O_DIRECT contract),
- double-buffers: the previous block's ``pwrite`` runs on a shared IO
  executor while the caller fills the next buffer, so serialization
  overlaps disk writes,
- ``finish()`` pads the tail to the alignment block, waits for
  in-flight writes, and truncates the file to its exact logical size
  (mmap readers never see the padding).

Readback goes through a plain buffered descriptor — O_DIRECT reads
would impose alignment on consumers for no gain (the page cache is
exactly what a freshly-written-then-read spill wants).
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# raw Linux fallocate(2) via libc: unlike os.posix_fallocate, it FAILS
# (EOPNOTSUPP) on filesystems without extent preallocation instead of
# glibc silently zero-filling the range (2x write traffic for nothing)
try:
    _libc = ctypes.CDLL(None, use_errno=True)
    _fallocate = _libc.fallocate
    _fallocate.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_long, ctypes.c_long,
    ]
    _fallocate.restype = ctypes.c_int
except (OSError, AttributeError):  # non-Linux libc
    _fallocate = None

# O_DIRECT demands offset/length/memory alignment at the logical block
# size; 4096 covers every sector size in practice
ALIGN = 4096

_support_cache: Dict[str, bool] = {}
_support_lock = threading.Lock()  # lock-order: 88


def direct_supported(directory: str) -> bool:
    """Whether files in ``directory`` accept O_DIRECT (probed once)."""
    if not hasattr(os, "O_DIRECT"):
        return False
    key = os.path.abspath(directory)
    with _support_lock:
        cached = _support_cache.get(key)
    if cached is not None:
        return cached
    ok = False
    probe = None
    try:
        import tempfile

        fd, probe = tempfile.mkstemp(prefix=".directio_probe_", dir=directory)
        os.close(fd)
        fd = os.open(probe, os.O_WRONLY | os.O_DIRECT)
        try:
            buf = mmap.mmap(-1, ALIGN)
            try:
                os.pwrite(fd, memoryview(buf), 0)
                ok = True
            finally:
                buf.close()
        finally:
            os.close(fd)
    except OSError:
        ok = False
    finally:
        if probe is not None:
            try:
                os.unlink(probe)
            except OSError:
                pass
    with _support_lock:
        _support_cache[key] = ok
    return ok


class DirectAppender:
    """Append-only writer with O_DIRECT + aligned double buffering.

    ``append(data)`` returns the (logical offset, length) of the
    payload; ``finish()`` makes the file exactly ``size`` bytes long
    and closes the write descriptor.  Not thread-safe (one writer per
    file); the async flush runs on the shared ``executor``.
    """

    def __init__(self, path: str, use_direct: bool = True,
                 buf_bytes: int = 1 << 20,
                 executor: Optional[ThreadPoolExecutor] = None,
                 prealloc_bytes: int = 0):
        if buf_bytes % ALIGN:
            raise ValueError(f"buf_bytes must be {ALIGN}-aligned")
        self.path = path
        self.size = 0            # logical bytes appended
        self._file_off = 0       # aligned bytes already on disk
        self._executor = executor
        self._pending: Optional[Future] = None
        # extent preallocation: interleaved appends across many files
        # (one per partition) otherwise fragment each file into
        # bounce-buffer-sized extents, degrading the later sequential
        # read; fallocate in prealloc_bytes steps keeps extents large
        # (finish() ftruncates, returning the unused tail).  0 = off.
        self._prealloc = int(prealloc_bytes)
        self._allocated = 0
        flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
        self.direct = bool(use_direct) and hasattr(os, "O_DIRECT")
        if self.direct:
            try:
                self._fd = os.open(path, flags | os.O_DIRECT, 0o600)
            except OSError:
                self.direct = False
                self._fd = os.open(path, flags, 0o600)
        else:
            self._fd = os.open(path, flags, 0o600)
        # page-aligned bounce buffers (the O_DIRECT memory contract);
        # two so a fill can overlap the previous block's pwrite
        self._bufs = [mmap.mmap(-1, buf_bytes), mmap.mmap(-1, buf_bytes)]
        self._cur = 0
        self._fill = 0
        self._closed = False

    # -- write side ---------------------------------------------------------
    def append(self, data) -> Tuple[int, int]:
        if self._closed:
            raise ValueError(f"appender for {self.path} is finished")
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        off = self.size
        n = len(mv)
        buf = self._bufs[self._cur]
        cap = len(buf)
        pos = 0
        while pos < n:
            take = min(n - pos, cap - self._fill)
            buf[self._fill : self._fill + take] = mv[pos : pos + take]
            self._fill += take
            pos += take
            if self._fill == cap:
                self._flush_block(cap)
                buf = self._bufs[self._cur]
        self.size += n
        return off, n

    def _flush_block(self, nbytes: int) -> None:
        """Write the current buffer's first ``nbytes`` (ALIGN-multiple)
        at the current aligned file offset, then rotate buffers."""
        buf = self._bufs[self._cur]
        file_off = self._file_off
        fd = self._fd
        if self._prealloc and file_off + nbytes > self._allocated:
            grow = max(self._prealloc, nbytes)
            if _fallocate is not None and _fallocate(
                fd, 0, self._allocated, grow
            ) == 0:
                self._allocated += grow
            else:
                self._prealloc = 0  # fs/libc without fallocate(2)

        def _write(buf=buf, nbytes=nbytes, file_off=file_off, fd=fd):
            view = memoryview(buf)[:nbytes]
            pos = 0
            while pos < nbytes:
                pos += os.pwrite(fd, view[pos:], file_off + pos)

        self._file_off += nbytes
        if self._executor is not None:
            self._wait_pending()
            self._pending = self._executor.submit(_write)
        else:
            _write()
        # rotating is safe: the buffer rotated TO had its write waited
        # by the _wait_pending above (at most one write in flight)
        self._cur ^= 1
        self._fill = 0

    def _wait_pending(self) -> None:
        if self._pending is not None:
            f, self._pending = self._pending, None
            f.result()

    def finish(self) -> int:
        """Flush the tail, trim to the logical size, close the write
        descriptor.  Returns the logical size."""
        if self._closed:
            return self.size
        self._closed = True
        if self._fill:
            # pad to the alignment block; the ftruncate below trims it
            padded = (self._fill + ALIGN - 1) // ALIGN * ALIGN
            buf = self._bufs[self._cur]
            buf[self._fill : padded] = b"\x00" * (padded - self._fill)
            self._flush_block(padded)
        self._wait_pending()
        os.ftruncate(self._fd, self.size)
        self._release_fd_and_bufs()
        return self.size

    def abandon(self) -> None:
        """Failure path: close and unlink."""
        if not self._closed:
            self._closed = True
            try:
                self._wait_pending()
            except OSError:
                pass
            self._release_fd_and_bufs()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _release_fd_and_bufs(self) -> None:
        try:
            os.close(self._fd)
        finally:
            for b in self._bufs:
                try:
                    b.close()
                except BufferError:
                    pass
            self._bufs = []

    # -- read side ----------------------------------------------------------
    def open_read(self):
        """Buffered read descriptor (valid after finish())."""
        return open(self.path, "rb")
