"""Memory layer: native host staging pool + device (HBM) arena registry."""

from sparkrdma_tpu.memory.staging import StagingBuffer, StagingPool
from sparkrdma_tpu.memory.arena import ArenaManager, DeviceSegment

__all__ = ["StagingPool", "StagingBuffer", "ArenaManager", "DeviceSegment"]
