"""Tiered block store: hot pooled rows over cold mapped files.

The out-of-core residency manager (ROADMAP item 3, the *DMA Streaming
Framework* / *RDMAbox* direction).  The reference mmaps every shuffle
file and registers it whole, prefetching ODP pages ahead of RDMA reads
(RdmaMappedFile.java:95-171, the prefetch sweep at :158-168); here a
file-backed map output is adopted by a per-node :class:`TieredBlockStore`
that owns the residency state of every partition block:

- **cold tier** — the committed data file itself (write-through at
  commit: the bytes are on disk before the output publishes), read via
  O_DIRECT ``pread`` or a LAZILY created mmap (``defer_map``), so an
  output whose partitions are never read costs the file alone;
- **hot tier** — blocks promoted into pooled ``StagingPool.alloc_gc``
  rows under the ``tierHotBytes`` budget, served as zero-copy read-only
  views (release is GC-tied, so a demotion can never recycle memory
  under a live consumer view);
- **eviction** — promotion past the budget demotes the LRU *unpinned*
  blocks (a block with an in-flight serve holds pins — the
  ``Channel.in_flight()`` refcount precedent — and is skipped, counted
  as a refusal); demotion is free because the cold tier is the source
  of truth;
- **prefetch** — two promotion signals hide the disk reads: the serve
  path's own request stream (a read of block *i* schedules readahead of
  blocks *i+1..i+k* through the node's byte-credited serve pool) and
  reader-sent :class:`~sparkrdma_tpu.rpc.messages.PrefetchHintMsg`
  lists (the reader knows its full fetch plan), warming blocks before
  the read RPCs arrive.

Concurrency: the store lock guards residency metadata only — disk
reads and row copies ALWAYS run outside it (concheck's DISK_BLOCKING
gate pins this down), and concurrent readers of a block mid-promotion
wait on its loading event instead of issuing duplicate disk reads (the
striped sub-range serve shape: every lane's first touch races here).
"""

from __future__ import annotations

import logging
import threading
import weakref
from bisect import bisect_right
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.memory.staging import alloc_row_gc
from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.transport.channel import TransportError
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.statemachine import StateMachine

logger = logging.getLogger(__name__)

# cold-tier blocks at least this large read O_DIRECT (pread); smaller
# ones fault through the lazy mmap — same split as arena.DIRECT_READ_MIN
# (buffered faults are writeback-throttled on virtualized hosts, but a
# 4 KiB-aligned O_DIRECT round trip is pure overhead for tiny blocks)
TIER_DIRECT_READ_MIN = 1 << 20

# clustered cold reads skip gaps above this (the arena's
# READ_MANY_MAX_GAP policy: a sparse batch must not drag the whole gap
# off disk)
TIER_READ_MAX_GAP = 8 << 20


class _Block(StateMachine):
    """Residency state of one partition block of one map output:
    ``cold`` (disk only) → ``loading`` (one promotion in flight) →
    ``hot`` (pinned row installed), demoting back to ``cold`` on
    eviction or a failed/raced load."""

    __slots__ = ("index", "offset", "length", "row", "pins", "seq",
                 "loading", "prefetched", "touched", "hot_tkt", "_state")

    MACHINE = "tier.block"
    STATES = ("cold", "loading", "hot")
    INITIAL = "cold"
    TERMINAL = ()
    TRANSITIONS = {
        "cold": ("loading",),
        "loading": ("hot", "cold"),  # install, or rollback/raced release
        "hot": ("cold",),            # demote
    }

    def __init__(self, index: int, offset: int, length: int):
        self.index = index
        self.offset = offset
        self.length = length
        # all mutable state below guarded-by the owning store's _lock
        self._state = "cold"  # state: tier.block guarded-by: TieredBlockStore._lock
        self.row: Optional[np.ndarray] = None  # hot: exact-length view
        self.pins = 0  # resource: tier.pins (live consumer views)
        self.hot_tkt = NOOP_TICKET  # this block's hot-byte reservation
        self.seq = 0            # LRU clock at last touch
        self.loading: Optional[threading.Event] = None
        self.prefetched = False  # promoted by prefetch, not yet read
        self.touched = False     # ever served (never-read accounting)


class TierEntry:
    """One adopted map output: its data file + per-block residency.
    ``tenant`` (qos/) is the owning tenant resolved at adoption — the
    hot budget's weighted-share accounting keys on it."""

    __slots__ = ("mf", "nbytes", "shuffle_id", "blocks", "_ends",
                 "mkey", "tenant")

    def __init__(self, mf, spans: Sequence[Tuple[int, int]],
                 nbytes: int, shuffle_id: Optional[int]):
        self.mf = mf
        self.nbytes = nbytes
        self.shuffle_id = shuffle_id
        self.mkey = 0  # assigned at registration
        self.tenant = None  # resolved by the adopting store
        self.blocks: List[_Block] = [
            _Block(i, off, ln)
            for i, (off, ln) in enumerate(spans) if ln > 0
        ]
        # exclusive end offsets for bisect lookup
        self._ends = [b.offset + b.length for b in self.blocks]

    def block_covering(self, lo: int, hi: int) -> Optional[_Block]:
        """The single block containing [lo, hi), or None (a span
        crossing block boundaries serves cold — it cannot be one
        published location)."""
        i = bisect_right(self._ends, lo)
        if i < len(self.blocks):
            b = self.blocks[i]
            if b.offset <= lo and hi <= b.offset + b.length:
                return b
        return None

    def blocks_overlapping(self, lo: int, hi: int) -> List[_Block]:
        i = bisect_right(self._ends, lo)
        out = []
        while i < len(self.blocks) and self.blocks[i].offset < hi:
            out.append(self.blocks[i])
            i += 1
        return out


class TieredSegment:
    """Arena-registered face of one tier entry: duck-types
    DeviceSegment (``ArenaManager.register_external``) so every serve
    path — local short-circuit, TCP/loopback one-sided reads, the bulk
    plane's batched ``read_many`` — resolves through the store's
    residency state without knowing tiers exist."""

    __slots__ = ("mkey", "nbytes", "shuffle_id", "budgeted",
                 "zero_copy_ok", "keepalive", "store", "entry")

    def __init__(self, store: "TieredBlockStore", entry: TierEntry):
        self.mkey = 0  # assigned by ArenaManager.register_external
        self.nbytes = entry.nbytes
        self.shuffle_id = entry.shuffle_id
        self.budgeted = False   # bytes live on disk / in tier-budgeted rows
        self.zero_copy_ok = True  # hot rows are GC-tied, mmaps refcounted
        self.keepalive = None
        self.store = store
        self.entry = entry

    def _check(self, lo: int, hi: int) -> None:
        if lo < 0 or hi > self.nbytes:
            raise TransportError(
                f"read [{lo},{hi}) outside tiered segment "
                f"mkey={self.mkey} of {self.nbytes}B"
            )

    def read(self, offset: int, length: int):
        self._check(offset, offset + length)
        return self.store.read(self.entry, offset, length)

    def read_many(self, spans):
        if not spans:
            return []
        self._check(min(o for o, _l in spans),
                    max(o + _l for o, _l in spans))
        return self.store.read_many(self.entry, spans)

    def _release_keepalive(self) -> None:
        self.store.release_entry(self.entry)


class TieredBlockStore:
    """Per-node residency manager for file-backed map outputs."""

    def __init__(self, staging_pool=None, hot_bytes: int = 0,
                 prefetch_blocks: int = 2, submitter=None, qos=None):
        self.staging_pool = staging_pool
        self.hot_budget = max(int(hot_bytes), 0)  # 0 = unbounded
        self.prefetch_blocks = max(int(prefetch_blocks), 0)
        # multi-tenant QoS (qos/): when a tenant registry is attached,
        # the hot budget splits into weighted max-min shares — an
        # over-share tenant may only displace its own (or other
        # over-share) blocks, a DEGRADED tenant (admission quota) is
        # not promoted at all (its blocks serve cold), and idle shares
        # stay borrowable (work conservation)
        self._qos = qos
        self._hot_by_tenant: Dict[str, int] = {}  # guarded-by: _lock
        # async promotion executor: (fn, args, cost_bytes) — wired to
        # Node.submit_serve so warms ride the serve pool's byte
        # credits; None runs nothing (demand-only cache)
        self._submit = submitter
        # guards every _Block's mutable state + the maps/accounting
        # below; disk reads and row copies NEVER run under it.
        # Deliberately a PLAIN RLock, never a DebugLock: _unpin runs
        # as a weakref.finalize callback, and cyclic GC can fire it on
        # a thread that already holds this lock (or any other) — a
        # rank-checked non-reentrant wrapper would raise inside the
        # finalizer and leak the pin forever (the StagingPool._lock
        # precedent, memory/staging.py)
        self._lock = threading.RLock()  # lock-order: 76
        self._by_mkey: Dict[int, TierEntry] = {}  # guarded-by: _lock
        self._hot_bytes = 0  # resource: tier.hot_bytes  # guarded-by: _lock
        self._hot: Dict[_Block, TierEntry] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._m_hot = gauge("tier_hot_bytes")
        self._m_entries = gauge("tier_entries")
        self._m_hits = counter("tier_hits_total")
        self._m_misses = counter("tier_misses_total")
        self._m_promotes = counter("tier_promotes_total")
        self._m_promote_bytes = counter("tier_promote_bytes_total")
        self._m_demotes = counter("tier_demotes_total")
        self._m_demote_bytes = counter("tier_demote_bytes_total")
        self._m_evict_refusals = counter("tier_evict_refusals_total")
        self._m_cold_bytes = counter("tier_cold_read_bytes_total")
        self._m_prefetch_tasks = counter("tier_prefetch_tasks_total")
        self._m_prefetch_useful = counter("tier_prefetch_useful_total")
        self._m_never_read = counter("tier_bytes_never_read_total")
        self._m_commit_bytes = counter("tier_commit_bytes_total")

    # -- adoption / release --------------------------------------------------
    def adopt(self, mf, spans: Sequence[Tuple[int, int]], nbytes: int,
              shuffle_id: Optional[int], arena) -> TieredSegment:
        """Adopt one committed data file as a tiered segment: registers
        it in ``arena`` (mkey assignment + read dispatch) and indexes
        its partition blocks for residency tracking.  ``spans`` are the
        per-partition (offset, length) pairs; takes ownership of ``mf``
        (freed on segment release)."""
        entry = TierEntry(mf, spans, nbytes, shuffle_id)
        if self._qos is not None:
            entry.tenant = self._qos.tenant_of_shuffle(shuffle_id)
        seg = TieredSegment(self, entry)
        arena.register_external(seg)
        entry.mkey = seg.mkey
        with self._lock:
            self._by_mkey[seg.mkey] = entry
        self._m_entries.inc()
        self._m_commit_bytes.inc(sum(b.length for b in entry.blocks))
        return seg

    def release_entry(self, entry: TierEntry) -> None:
        """Segment released (shuffle unregistered / task retry):
        demote its hot blocks and free the data file.  Counts the
        bytes that were committed but NEVER served — the eager
        registration the lazy per-span path saves."""
        never_read = 0
        with self._lock:
            self._by_mkey.pop(entry.mkey, None)
            for blk in entry.blocks:
                if blk.row is not None:
                    self._demote_locked(blk)
                if not blk.touched:
                    never_read += blk.length
        self._m_entries.dec()
        if never_read:
            self._m_never_read.inc(never_read)
        entry.mf.free()

    def stop(self) -> None:
        """Defensive teardown (entries normally drain via segment
        release through the arena)."""
        with self._lock:
            entries = list(self._by_mkey.values())
        for entry in entries:
            self.release_entry(entry)

    # -- read path -----------------------------------------------------------
    def read(self, entry: TierEntry, offset: int, length: int):
        """Serve one span from whichever tier holds the bytes."""
        return self.read_many(entry, [(offset, length)])[0]

    def read_many(self, entry: TierEntry, spans):
        """Serve many (offset, length) spans: hot blocks hand back
        zero-copy pinned views; a sub-range of a cold block promotes
        the WHOLE block first (one disk read serves every stripe of
        it — concurrent lanes wait on the loading event instead of
        re-reading); whole-block cold reads serve straight from disk,
        clustered by proximity like the arena's batched reads.  Always
        completes — a full hot tier degrades to cold serving, never an
        error or a wait-forever."""
        out: list = [None] * len(spans)
        cold: List[int] = []
        last_block = None
        for i, (off, ln) in enumerate(spans):
            if ln == 0:
                out[i] = b""
                continue
            blk = entry.block_covering(off, off + ln)
            if blk is None:
                # crosses block boundaries: not a published location —
                # serve cold without residency tracking
                cold.append(i)
                continue
            if last_block is None or blk.index > last_block.index:
                last_block = blk
            if ln < blk.length:
                # stripe sub-range: siblings are coming — promote
                out[i] = self._serve_block(
                    entry, blk, off - blk.offset, ln, want_promote=True
                )
            else:
                served = self._try_serve_hot(entry, blk)
                if served is None:
                    cold.append(i)
                else:
                    out[i] = served
        if cold:
            self._serve_cold_clustered(entry, spans, cold, out)
        if last_block is not None:
            self._maybe_readahead(entry, last_block)
        return out

    def _try_serve_hot(self, entry: TierEntry, blk: _Block):
        """Hot hit (or a wait on an in-flight promotion) for a
        whole-block read; None → caller serves cold."""
        for _ in range(8):
            with self._lock:
                self._touch_locked(blk)
                if blk.row is not None:
                    self._m_hits.inc()
                    return self._pinned_view_locked(blk, 0, blk.length)
                ev = blk.loading
            if ev is None:
                return None
            # a promote is in flight (hint warm / stripe sibling):
            # waiting reuses its one disk read; a stuck loader times
            # out into a plain cold serve
            if not ev.wait(timeout=30.0):
                return None
        return None

    def _serve_block(self, entry: TierEntry, blk: _Block, rel: int,
                     length: int, want_promote: bool):
        """Serve one span INSIDE one block, promoting it when asked
        (and the budget allows after eviction)."""
        loaded = False
        for _ in range(64):
            with self._lock:
                self._touch_locked(blk)
                if blk.row is not None:
                    if not loaded:
                        self._m_hits.inc()
                    return self._pinned_view_locked(blk, rel, length)
                ev = blk.loading
                if ev is None and want_promote \
                        and self._reserve_locked(blk.length, entry=entry):
                    blk._transition("loading", frm="cold")
                    blk.loading = threading.Event()
                    blk.hot_tkt = ledger_acquire(
                        "tier.hot_bytes", blk.length
                    )
                    ev = None
                    load = True
                else:
                    load = False
            if load:
                self._m_misses.inc()
                loaded = True
                row = None
                try:
                    row = self._load_row(entry, blk)
                finally:
                    self._finish_load(entry, blk, row)
                # serve OUR loaded row directly: a concurrent demand
                # promote may already have evicted the block again
                # under budget contention, and looping back would
                # re-read the same bytes from disk (thrash, and after
                # enough rounds a spurious convergence error) even
                # though this thread holds them right here.  If the
                # row is still installed, the view pins it; if it was
                # demoted, the view alone keeps it alive (GC chain).
                with self._lock:
                    if blk.row is row:
                        return self._pinned_view_locked(blk, rel, length)
                v = row[rel : rel + length].view()
                v.flags.writeable = False
                return v
            if ev is not None:
                if ev.wait(timeout=30.0):
                    continue
            # cold serve: budget exhausted / oversized / stuck loader
            if not loaded:
                self._m_misses.inc()
            self._m_cold_bytes.inc(length)
            return self._disk_read(entry, blk.offset + rel, length)
        raise TransportError(
            f"tier: block {blk.index} of mkey={entry.mkey} did not "
            f"converge to a servable tier"
        )

    def _serve_cold_clustered(self, entry: TierEntry, spans,
                              idxs: List[int], out: list) -> None:
        """One proximity-clustered disk read per dense run of cold
        spans (the arena ``_read_spans_clustered`` policy against the
        cold tier); served blocks are chunk views of each cluster's
        landed buffer."""
        for i in idxs:
            blk = entry.block_covering(
                spans[i][0], spans[i][0] + spans[i][1]
            )
            if blk is not None:
                with self._lock:
                    self._touch_locked(blk)
            self._m_misses.inc()
            self._m_cold_bytes.inc(spans[i][1])
        order = sorted(idxs, key=lambda i: spans[i][0])
        cluster: List[int] = []
        cend = 0

        def flush() -> None:
            if not cluster:
                return
            clo = spans[cluster[0]][0]
            chi = max(spans[i][0] + spans[i][1] for i in cluster)
            buf = self._disk_read(entry, clo, chi - clo)
            for i in cluster:
                o, ln = spans[i]
                out[i] = buf[o - clo : o - clo + ln]
            cluster.clear()

        for i in order:
            o, ln = spans[i]
            if cluster and o - cend > TIER_READ_MAX_GAP:
                flush()
            cluster.append(i)
            cend = max(cend, o + ln)
        flush()

    # -- promotion / prefetch ------------------------------------------------
    def warm(self, mkey: int, offset: int, length: int) -> int:
        """Promote the blocks covering [offset, offset+length) ahead
        of their reads — the PrefetchHintMsg / readahead entry point.
        Unknown mkeys (released shuffle, non-tiered segment) are a
        no-op.  Returns blocks promoted."""
        with self._lock:
            entry = self._by_mkey.get(mkey)
        if entry is None:
            return 0
        n = 0
        for blk in entry.blocks_overlapping(offset, offset + length):
            n += self._warm_block(entry, blk)
        if n and RECORDER.enabled:
            fr_event("tier", "warm", mkey=mkey, blocks=n)
        return n

    def would_warm(self, mkey: int) -> bool:
        """Cheap guard for hint handlers: is this mkey tiered at all?"""
        with self._lock:
            return mkey in self._by_mkey

    def _warm_block(self, entry: TierEntry, blk: _Block) -> int:
        with self._lock:
            if blk.row is not None or blk.loading is not None:
                return 0
            # a prediction may only recycle CONSUMED budget (touched,
            # unpinned blocks): warming the tail of a long plan must
            # never demote its still-unread head — when the budget is
            # full of unread predictions, warming simply stops and the
            # blocks serve cold on demand
            if not self._reserve_locked(blk.length, prefetch=True,
                                        entry=entry):
                return 0
            self._seq += 1  # noqa: CK03 - held
            blk.seq = self._seq  # noqa: CK03 - held
            blk._transition("loading", frm="cold")
            blk.loading = threading.Event()
            blk.hot_tkt = ledger_acquire("tier.hot_bytes", blk.length)
            blk.prefetched = True
        self._m_prefetch_tasks.inc()
        row = None
        try:
            row = self._load_row(entry, blk)
        except BaseException:
            logger.warning(
                "tier: prefetch of block %d (mkey=%d) failed",
                blk.index, entry.mkey, exc_info=True,
            )
        finally:
            self._finish_load(entry, blk, row)
        return 1 if row is not None else 0

    def _maybe_readahead(self, entry: TierEntry, blk: _Block) -> None:
        """The request-stream signal: serving block i schedules async
        promotion of the next blocks of the same output through the
        serve pool (byte-credited — a prefetch storm cannot pin
        unbounded memory, it queues behind real serves)."""
        k = self.prefetch_blocks
        submit = self._submit
        if k <= 0 or submit is None:
            return
        for nb in entry.blocks[blk.index + 1 : blk.index + 1 + k]:
            with self._lock:
                if (nb.row is not None or nb.loading is not None
                        or entry.mkey not in self._by_mkey):
                    continue
            try:
                submit(self._warm_block, (entry, nb), nb.length)
            except Exception:
                return  # serve pool stopped / saturated: demand-only

    # -- internals (lock held where noted) -----------------------------------
    def _touch_locked(self, blk: _Block) -> None:
        self._seq += 1  # noqa: CK03 - caller holds _lock
        blk.seq = self._seq  # noqa: CK03 - caller holds _lock
        if not blk.touched:
            blk.touched = True
            if blk.prefetched:
                blk.prefetched = False
                # useful only if the prediction actually delivered:
                # the row is resident, or its load is in flight (the
                # reader reuses that disk read via the loading event);
                # a FAILED warm must not inflate the usefulness ratio
                if blk.row is not None or blk.loading is not None:
                    self._m_prefetch_useful.inc()
        elif blk.prefetched and blk.row is not None:
            blk.prefetched = False
            self._m_prefetch_useful.inc()

    def _pinned_view_locked(self, blk: _Block, rel: int, length: int):
        """Zero-copy read-only view of a hot row, pinned until the
        view is collected (the in-flight refcount eviction honors).
        Memory safety does NOT depend on the pin — the alloc_gc base
        chain keeps the row's pages alive under any surviving slice —
        the pin only stops eviction from demoting a block mid-serve."""
        blk.pins += 1  # acquires: tier.pins  # noqa: CK03 - caller holds _lock
        tkt = ledger_acquire("tier.pins")
        v = blk.row[rel : rel + length].view()
        v.flags.writeable = False
        weakref.finalize(v, self._unpin, blk, tkt)  # releases: tier.pins
        return v

    def _unpin(self, blk: _Block, tkt=NOOP_TICKET) -> None:
        with self._lock:
            blk.pins -= 1
        # settled OUTSIDE the store lock: a finalizer firing at
        # interpreter shutdown (after the ledger epoch closed) must be
        # a silent no-op, and a live one must never raise with the
        # store lock held
        tkt.release()

    def _tier_shares_locked(self, extra) -> Dict[str, float]:
        """The hot budget's weighted max-min shares over the tenants
        with hot bytes, plus ``extra`` (the requester) — the SAME
        formula every credit ledger uses (qos/broker.py)."""
        from sparkrdma_tpu.qos.broker import weighted_shares

        return weighted_shares(
            self.hot_budget, self._qos,
            self._hot_by_tenant,  # noqa: CK03 - caller holds _lock
            {extra.name: extra} if extra is not None else None,
        )

    def _drop_hot_tenant_locked(self, tenant, n: int) -> None:
        """Return ``n`` bytes of a tenant's hot usage (demotion or a
        failed/raced load) — caller holds ``_lock``."""
        if tenant is None:
            return
        left = self._hot_by_tenant.get(tenant.name, 0) - n  # noqa: CK03 - held
        if left > 0:
            self._hot_by_tenant[tenant.name] = left  # noqa: CK03 - held
        else:
            self._hot_by_tenant.pop(tenant.name, None)  # noqa: CK03 - held

    def _reserve_locked(self, n: int, prefetch: bool = False,
                        entry: Optional[TierEntry] = None) -> bool:
        """Make budget room for one promotion (evicting LRU unpinned
        hot blocks), reserving ``n`` bytes on success.  A block larger
        than the whole budget is never promoted (it serves cold) —
        the no-deadlock clamp.  ``prefetch`` restricts eviction to
        TOUCHED blocks (served at least once): a demand read may
        displace an unread prediction, a prediction may not — warming
        the tail of a plan must never cannibalize its unread head.
        With QoS on, a DEGRADED tenant never promotes (cold serves —
        the admission-control shed path) and eviction honors weighted
        shares (``_evict_locked``)."""
        tenant = entry.tenant if entry is not None else None
        if self._qos is not None and tenant is not None \
                and tenant.degraded:
            counter("qos_tier_denials_total",
                    tenant=tenant.name).inc()
            return False
        if self.hot_budget:
            if n > self.hot_budget:
                return False
            over = self._hot_bytes + n - self.hot_budget  # noqa: CK03 - held
            if over > 0:
                self._evict_locked(over, touched_only=prefetch,
                                   requester=tenant)
            if self._hot_bytes + n > self.hot_budget:  # noqa: CK03 - held
                return False
        # the reservation's release duty rides the block: installed
        # rows settle through demotion, failed/raced loads roll back
        # owns: tier.hot_bytes -> _demote_locked
        # owns: tier.hot_bytes -> _finish_load
        self._hot_bytes += n  # acquires: tier.hot_bytes  # noqa: CK03 - held
        if tenant is not None:
            self._hot_by_tenant[tenant.name] = (  # noqa: CK03 - held
                self._hot_by_tenant.get(tenant.name, 0) + n  # noqa: CK03 - held
            )
        self._m_hot.inc(n)
        return True

    def _evict_locked(self, need: int, touched_only: bool = False,
                      requester=None) -> None:
        protect_others = False
        shares: Dict[str, float] = {}
        if self._qos is not None and requester is not None:
            # a requester already at/over its weighted share may only
            # displace its OWN blocks (or another over-share tenant's)
            # — an under-share tenant's hot set is protected from it;
            # an under-share requester reclaims from anyone (that IS
            # the reclaim-on-demand of borrowed idle shares)
            shares = self._tier_shares_locked(requester)
            protect_others = (
                self._hot_by_tenant.get(requester.name, 0)  # noqa: CK03 - held
                >= shares.get(requester.name, float("inf"))
            )
        order = sorted(self._hot, key=lambda b: b.seq)  # noqa: CK03 - held
        freed = 0
        for blk in order:
            if freed >= need:
                break
            if touched_only and not blk.touched:
                continue
            if blk.pins > 0:
                # in-flight serve: never demote under a live reader
                self._m_evict_refusals.inc()
                continue
            if protect_others:
                owner = self._hot[blk].tenant  # noqa: CK03 - held
                if (owner is not None
                        and owner.name != requester.name
                        and self._hot_by_tenant.get(owner.name, 0)  # noqa: CK03 - held
                        <= shares.get(owner.name, 0)):
                    self._m_evict_refusals.inc()
                    continue
            freed += blk.length
            self._demote_locked(blk)

    def _demote_locked(self, blk: _Block) -> None:
        entry = self._hot.pop(blk, None)  # noqa: CK03 - caller holds _lock
        blk._transition("cold", frm="hot")
        blk.row = None  # cold tier is the source of truth: no write-back
        tkt, blk.hot_tkt = blk.hot_tkt, NOOP_TICKET
        tkt.release()
        self._hot_bytes -= blk.length  # releases: tier.hot_bytes  # noqa: CK03
        self._drop_hot_tenant_locked(
            entry.tenant if entry is not None else None, blk.length
        )
        self._m_hot.dec(blk.length)
        self._m_demotes.inc()
        self._m_demote_bytes.inc(blk.length)
        if RECORDER.enabled:
            fr_event("tier", "demote", bytes=blk.length)

    def _finish_load(self, entry: TierEntry, blk: _Block,
                     row: Optional[np.ndarray]) -> None:
        """Install a loaded row (or roll back the reservation) and
        wake waiters — exactly once per loading transition."""
        with self._lock:
            ev, blk.loading = blk.loading, None
            if row is not None and entry.mkey in self._by_mkey:
                blk._transition("hot", frm="loading")
                blk.row = row
                self._hot[blk] = entry
            else:
                blk._transition("cold", frm="loading")
                # failed load, or the entry was released mid-load
                tkt, blk.hot_tkt = blk.hot_tkt, NOOP_TICKET
                tkt.release()
                self._hot_bytes -= blk.length  # releases: tier.hot_bytes
                self._drop_hot_tenant_locked(entry.tenant, blk.length)
                self._m_hot.dec(blk.length)
        if ev is not None:
            ev.set()

    def _load_row(self, entry: TierEntry, blk: _Block) -> np.ndarray:
        """One whole-block disk read into a pooled row (NO lock held —
        this is the promotion's actual I/O)."""
        row = alloc_row_gc(
            self.staging_pool, blk.length,
            "tier_row_pool_fallbacks_total",
        )
        data = self._disk_read(entry, blk.offset, blk.length)
        row[: blk.length] = (
            data if isinstance(data, np.ndarray)
            else np.frombuffer(memoryview(data), np.uint8)
        )
        row.flags.writeable = False
        self._m_promotes.inc()
        self._m_promote_bytes.inc(blk.length)
        if RECORDER.enabled:
            fr_event(
                "tier", "promote",
                bytes=blk.length, prefetched=1 if blk.prefetched else 0,
            )
        return row

    def _disk_read(self, entry: TierEntry, offset: int, length: int):
        """Cold-tier read (NO lock held — concheck DISK_BLOCKING):
        O_DIRECT pread for large spans, the lazily created mmap view
        otherwise/fallback."""
        if RECORDER.enabled:
            fr_event("tier", "disk_read", bytes=length)
        if FAULTS.enabled:
            # models a failed/slow spill read: surfaces through the
            # same TransportError path as the freed-entry race below,
            # so the serve side converts it to a retryable failure
            FAULTS.check("disk_read")
        mf = entry.mf
        if length >= TIER_DIRECT_READ_MIN:
            got = mf.pread(offset, length)
            if got is not None:
                return got
        try:
            arr = mf.ensure_mapped()
        except (ValueError, OSError) as e:
            # entry freed under a racing read (task retry superseding
            # the segment): the _freed check raises ValueError, and a
            # free() landing between that check and the np.memmap open
            # surfaces as FileNotFoundError — either way surface the
            # transport-failure type the serve paths convert to a
            # retryable fetch failure
            raise TransportError(str(e)) from e
        view = arr[offset : offset + length].view()
        view.flags.writeable = False
        return view

    # -- stats ---------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._by_mkey),
                "hot_blocks": len(self._hot),
                "hot_bytes": self._hot_bytes,
                "hot_budget": self.hot_budget,
            }


__all__ = ["TieredBlockStore", "TieredSegment", "TierEntry"]
