"""File-backed registered segments: the RdmaMappedFile analog.

The reference commits each map task's shuffle file by mmapping it in
4 KiB-aligned chunks and registering every chunk as an ibverbs MR, with
``deleteOnExit`` + explicit dispose (RdmaMappedFile.java:76-199).  Here
a committed byte stream can be written to disk and served through a
read-only ``np.memmap`` registered in the arena: the OS page cache
plays the registered-memory role, reads go straight from the mapping,
and the file is unlinked when the segment is released (the
deleteOnExit/dispose pair).

This is the larger-than-memory commit path — HBM staging
(resolver default) serves the hot exchange; file-backed segments hold
shuffles whose working set exceeds the arena budget.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class MappedFile:
    """One shuffle data file: write once, then serve reads via mmap.

    ``chunks`` is any iterable of byte strings, written STREAMING so a
    spilled map output never needs to be resident in RAM at commit
    (each chunk is materialized alone).  Pass the instance as
    ``keepalive`` to ``ArenaManager.register`` — ``free()`` is called
    exactly once on segment release and unlinks the file."""

    def __init__(self, chunks, directory: Optional[str] = None,
                 prefix: str = "sparkrdma_tpu_shuffle_"):
        if isinstance(chunks, (bytes, bytearray, memoryview)):
            chunks = (chunks,)
        directory = directory or tempfile.gettempdir()
        os.makedirs(directory, exist_ok=True)
        fd, self.path = tempfile.mkstemp(prefix=prefix, dir=directory)
        try:
            total = 0
            with os.fdopen(fd, "wb") as f:
                for chunk in chunks:
                    f.write(chunk)
                    total += len(chunk)
                if total == 0:
                    # mmap of a zero-byte file is invalid: pad to one
                    # byte so an all-empty-partitions commit still maps
                    # (the segment serves only EMPTY locations anyway)
                    f.write(b"\x00")
            # read-only mapping: serves get_local_block / transport reads
            # without a resident copy (page cache backs it)
            self.array = np.memmap(self.path, dtype=np.uint8, mode="r",
                                   shape=(max(total, 1),))
        except BaseException:
            self._unlink()
            raise
        self._freed = False

    def _unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            logger.warning("could not unlink %s", self.path, exc_info=True)

    def free(self) -> None:
        """Dispose: drop the mapping and delete the file
        (RdmaMappedFile.java:189-199)."""
        if self._freed:
            return
        self._freed = True
        mm = getattr(self.array, "_mmap", None)
        self.array = None
        if mm is not None:
            try:
                mm.close()
            except (BufferError, OSError):
                pass  # outstanding views keep the mapping alive until GC
        self._unlink()
