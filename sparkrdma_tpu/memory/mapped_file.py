"""File-backed registered segments: the RdmaMappedFile analog.

The reference commits each map task's shuffle file by mmapping it in
4 KiB-aligned chunks and registering every chunk as an ibverbs MR, with
``deleteOnExit`` + explicit dispose (RdmaMappedFile.java:76-199).  Here
a committed byte stream can be written to disk and served through a
read-only ``np.memmap`` registered in the arena: the OS page cache
plays the registered-memory role, reads go straight from the mapping,
and the file is unlinked when the segment is released (the
deleteOnExit/dispose pair).

This is the larger-than-memory commit path — HBM staging
(resolver default) serves the hot exchange; file-backed segments hold
shuffles whose working set exceeds the arena budget.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


_COMMIT_IO = None
_COMMIT_IO_LOCK = threading.Lock()  # lock-order: 86


def _commit_io_executor():
    """Shared 1-thread flush executor for commit-time DirectAppenders:
    overlaps the chunk producer (often a spill read-back) with the
    O_DIRECT pwrites, like the writer's spill appenders do.  Module-
    level and never shut down, so commits issued during manager
    teardown can't hit 'cannot schedule new futures'.  Double-checked
    lock: two first-commit threads racing here must share ONE flush
    thread (the single-flush-thread property the writers rely on)."""
    global _COMMIT_IO
    if _COMMIT_IO is None:
        with _COMMIT_IO_LOCK:
            if _COMMIT_IO is None:
                from concurrent.futures import ThreadPoolExecutor

                _COMMIT_IO = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="commit-io"
                )
    return _COMMIT_IO


def _advise_sequential(arr) -> None:
    """MADV_SEQUENTIAL on the backing mmap: shuffle blocks are read
    front-to-back, and aggressive readahead is worth 2-4x over default
    page faulting on the O_DIRECT-written (cache-cold) files."""
    import mmap as _mmap

    mm = getattr(arr, "_mmap", None)
    if mm is not None and hasattr(mm, "madvise"):
        try:
            mm.madvise(_mmap.MADV_SEQUENTIAL)
        except (OSError, ValueError):
            pass


class MappedFile:
    """One shuffle data file: write once, then serve reads via mmap.

    ``chunks`` is any iterable of byte strings, written STREAMING so a
    spilled map output never needs to be resident in RAM at commit
    (each chunk is materialized alone).  Pass the instance as
    ``keepalive`` to ``ArenaManager.register`` — ``free()`` is called
    exactly once on segment release and unlinks the file."""

    def __init__(self, chunks, directory: Optional[str] = None,
                 prefix: str = "sparkrdma_tpu_shuffle_",
                 direct_write: bool = True, defer_map: bool = False):
        if isinstance(chunks, (bytes, bytearray, memoryview)):
            chunks = (chunks,)
        directory = directory or tempfile.gettempdir()
        os.makedirs(directory, exist_ok=True)
        fd, self.path = tempfile.mkstemp(prefix=prefix, dir=directory)
        try:
            total = self._write_chunks(fd, chunks, directory, direct_write)
            if defer_map:
                # tiered commits (memory/tier.py) defer the read-only
                # mapping until a span is actually resolved/prefetched:
                # an output whose partitions are never read costs the
                # data file alone, no VMA and no faulted pages
                self.array = None
                self._length = total
            else:
                self._map(total)
        except BaseException:
            self._unlink()
            raise
        self._freed = False

    def _write_chunks(self, fd: int, chunks, directory: str,
                      direct_write: bool) -> int:
        """Stream ``chunks`` to disk, O_DIRECT when the fs supports it:
        commits are exactly the writes the virtualized hosts' buffered
        writeback throttles to ~1/5 of device bandwidth (BASELINE.md
        round 4 — this was the assembled run's largest single cost),
        and the file is mmap'd/pread back cache-cold either way."""
        from sparkrdma_tpu.memory.direct_io import (
            DirectAppender,
            direct_supported,
        )

        total = 0
        if direct_write and direct_supported(directory):
            os.close(fd)  # DirectAppender reopens with its own flags
            app = DirectAppender(self.path, prealloc_bytes=32 << 20,
                                 executor=_commit_io_executor())
            try:
                for chunk in chunks:
                    _, n = app.append(chunk)
                    total += n
                if total == 0:
                    # mmap of a zero-byte file is invalid: pad to one
                    # byte so an all-empty-partitions commit still
                    # maps (the segment serves only EMPTY locations)
                    app.append(b"\x00")
            finally:
                app.finish()
            return total
        with os.fdopen(fd, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
                total += len(chunk)
            if total == 0:
                f.write(b"\x00")
        return total

    # set False (e.g. conf directIO=off) to force the mmap view path
    direct_read_enabled = True

    def pread(self, offset: int, length: int):
        """O_DIRECT read of ``[offset, offset+length)`` into a fresh
        page-aligned buffer, bypassing the buffered fault path that
        virtualized hosts throttle to a fraction of device bandwidth
        (measured 181 MB/s faulted vs 893 MB/s O_DIRECT on the same
        file — BASELINE.md round-4 notes).  Returns a read-only uint8
        array, or None when O_DIRECT is unavailable/disabled (caller
        falls back to the mmap view).

        The descriptor is opened PER CALL by path: a concurrent
        ``free()`` (segment superseded by a task retry) at worst makes
        the open fail — never an fd-reuse read of the wrong file — and
        the fallback mmap view keeps the old loud-failure semantics."""
        import mmap as _mmap

        from sparkrdma_tpu.memory.direct_io import ALIGN

        if (self._freed or not self.direct_read_enabled
                or not hasattr(os, "O_DIRECT")):
            return None
        try:
            fd = os.open(self.path, os.O_RDONLY | os.O_DIRECT)
        except OSError:
            return None
        lo = offset // ALIGN * ALIGN
        hi = (offset + length + ALIGN - 1) // ALIGN * ALIGN
        mm = _mmap.mmap(-1, hi - lo)
        pos = 0
        want = hi - lo
        need = (offset - lo) + length
        view = memoryview(mm)
        try:
            while pos < need:
                n = os.preadv(fd, [view[pos:want]], lo + pos)
                if n <= 0:
                    break  # EOF inside the final alignment block
                pos += n
        except OSError:
            pos = -1
        finally:
            view.release()
            try:
                os.close(fd)
            except OSError:
                pass
        if pos < need:
            mm.close()
            return None  # failed / short before the span ended
        arr = np.frombuffer(mm, np.uint8)[
            offset - lo : offset - lo + length
        ]
        arr.flags.writeable = False
        return arr

    @classmethod
    def from_path(cls, path: str, length: int,
                  defer_map: bool = False) -> "MappedFile":
        """Adopt an EXISTING data file (e.g. a per-partition spill file
        written through the O_DIRECT appender) as a registered mapped
        segment — the zero-copy commit: spilled bytes are never
        rewritten, the spill file IS the shuffle file.  Takes ownership
        (unlinked on free)."""
        mf = cls.__new__(cls)
        mf.path = path
        try:
            if defer_map:
                mf.array = None
                mf._length = length
            else:
                mf._map(length)
        except BaseException:
            mf._unlink()
            raise
        mf._freed = False
        return mf

    def ensure_mapped(self) -> np.ndarray:
        """Create the deferred read-only mapping on first use (the
        per-span registration step of the tiered store's cold reads
        when O_DIRECT preads are unavailable).  Racy-create is benign:
        two mappers of the same file both get valid views; one VMA
        wins the attribute slot.  Returns the mapped uint8 array."""
        arr = self.array
        if arr is None:
            if self._freed:
                raise ValueError(f"mapped file {self.path} already freed")
            self._map(self._length)
            arr = self.array
        return arr

    def _map(self, length: int) -> None:
        """Shared read-only mapping setup (serves get_local_block /
        transport reads without a resident copy; page cache backs it)."""
        self.array = np.memmap(
            self.path, dtype=np.uint8, mode="r", shape=(max(length, 1),)
        )
        _advise_sequential(self.array)

    def _unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            logger.warning("could not unlink %s", self.path, exc_info=True)

    def free(self) -> None:
        """Dispose: drop the mapping and delete the file
        (RdmaMappedFile.java:189-199)."""
        if self._freed:
            return
        self._freed = True
        mm = getattr(self.array, "_mmap", None)
        self.array = None
        if mm is not None:
            try:
                mm.close()
            except (BufferError, OSError):
                pass  # outstanding views keep the mapping alive until GC
        self._unlink()
