"""Host staging pool: ctypes binding over the native allocator.

The write path serializes shuffle partitions into these page-aligned,
size-class-pooled host buffers before staging them into HBM arenas —
the role the reference's registered off-heap buffers play for the NIC
(RdmaBufferManager.java:35-209, RdmaBuffer.java:32-107).  Backed by
``native/staging_allocator.cpp`` (built to ``_staging.so``); a
pure-Python pool with the same policy serves as fallback when the
native library is absent.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
import weakref
from typing import Dict

import numpy as np

from sparkrdma_tpu.metrics import counter

logger = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "_staging.so")

MIN_BLOCK_SIZE = 16 * 1024

STAT_FIELDS = ("owned", "in_use", "idle", "num_classes", "failed_allocs",
               "total_allocs")


def _load_native():
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    lib.staging_pool_create.restype = ctypes.c_void_p
    lib.staging_pool_create.argtypes = [ctypes.c_uint64]
    lib.staging_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.staging_alloc.restype = ctypes.c_void_p
    lib.staging_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.staging_free.restype = ctypes.c_int
    lib.staging_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.staging_block_size.restype = ctypes.c_uint64
    lib.staging_block_size.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.staging_pool_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)
    ]
    lib.staging_pool_trim.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    if hasattr(lib, "row_gather"):
        lib.row_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
    if hasattr(lib, "radix_argsort_i64"):
        lib.radix_argsort_i64.restype = ctypes.c_int
        lib.radix_argsort_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
        ]
    if hasattr(lib, "hash_partition_order"):
        lib.hash_partition_order.restype = ctypes.c_int
        lib.hash_partition_order.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
    if hasattr(lib, "radix_scratch_trim"):
        lib.radix_scratch_trim.restype = None
        lib.radix_scratch_trim.argtypes = []
    if hasattr(lib, "kway_merge_i64"):
        lib.kway_merge_i64.restype = ctypes.c_int
        lib.kway_merge_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
    if hasattr(lib, "rank_compress_i64"):
        lib.rank_compress_i64.restype = ctypes.c_int64
        lib.rank_compress_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
        ]
    if hasattr(lib, "merge_runs_groups_i64"):
        lib.merge_runs_groups_i64.restype = ctypes.c_int64
        lib.merge_runs_groups_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
    if hasattr(lib, "frame_spans_lp"):
        lib.frame_spans_lp.restype = ctypes.c_int64
        lib.frame_spans_lp.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
    if hasattr(lib, "columnar_frame_spans"):
        lib.columnar_frame_spans.restype = ctypes.c_int64
        lib.columnar_frame_spans.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
    if hasattr(lib, "crc32_spans"):
        lib.crc32_spans.restype = None
        lib.crc32_spans.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
    if hasattr(lib, "gather_blocks"):
        lib.gather_blocks.restype = ctypes.c_int64
        lib.gather_blocks.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
    return lib


_NATIVE = _load_native()


def native_row_gather(src: np.ndarray, idx: np.ndarray,
                      out: np.ndarray) -> bool:
    """``out[i] = src[idx[i]]`` via the prefetching C gather.  Returns
    False (caller falls back to ``np.take``) when the native lib is
    absent or the arrays don't qualify: src/out must be 1-D, same
    dtype, contiguous (an unaligned uint8-view is fine — only the
    stride matters); idx must be contiguous int64 within range."""
    if _NATIVE is None or not hasattr(_NATIVE, "row_gather"):
        return False
    if (
        src.ndim != 1 or out.ndim != 1 or idx.ndim != 1
        or src.dtype != out.dtype
        or idx.dtype != np.int64
        or out.shape[0] != idx.shape[0]
        or src.strides[0] != src.dtype.itemsize
        or out.strides[0] != out.dtype.itemsize
        or idx.strides[0] != 8
    ):
        return False
    _NATIVE.row_gather(
        src.ctypes.data, out.ctypes.data, idx.ctypes.data,
        idx.shape[0], src.dtype.itemsize,
    )
    return True


def native_radix_argsort(keys: np.ndarray):
    """Stable argsort of an int64 column via the native LSD radix
    (4 x 16-bit passes, constant digits skipped) — ~2.5x numpy's
    timsort path for wide-range int64 keys.  Returns the int64 order
    or None when unavailable/ineligible (caller falls back)."""
    if _NATIVE is None or not hasattr(_NATIVE, "radix_argsort_i64"):
        return None
    if keys.ndim != 1 or keys.dtype != np.int64 or (
        len(keys) and keys.strides[0] != 8
    ):
        return None
    order = np.empty(keys.shape[0], np.int64)
    rc = _NATIVE.radix_argsort_i64(
        keys.ctypes.data, keys.shape[0], order.ctypes.data
    )
    if rc != 0:
        return None
    return order


def native_kway_merge(keys: np.ndarray, run_offsets: np.ndarray):
    """Stable merge order over concatenated PRE-SORTED int64 runs (the
    loser tree in staging_allocator.cpp) — bit-exact with numpy's
    stable argsort of the concatenation, ~2.8x the radix argsort on
    the sorted-runs shape.  Returns the int64 gather order or None
    when unavailable/ineligible (caller falls back)."""
    if _NATIVE is None or not hasattr(_NATIVE, "kway_merge_i64"):
        return None
    if (
        keys.ndim != 1 or keys.dtype != np.int64
        or (len(keys) and keys.strides[0] != 8)
        or run_offsets.ndim != 1 or run_offsets.dtype != np.int64
        or run_offsets.strides[0] != 8
        or len(run_offsets) < 1
        or run_offsets[0] != 0 or run_offsets[-1] != len(keys)
        or (np.diff(run_offsets) < 0).any()
    ):
        return None
    order = np.empty(len(keys), np.int64)
    rc = _NATIVE.kway_merge_i64(
        keys.ctypes.data, run_offsets.ctypes.data,
        len(run_offsets) - 1, order.ctypes.data,
    )
    if rc != 0:
        return None
    return order


def native_rank_compress(keys: np.ndarray):
    """Dense sorted-rank compression of a wide-range, low-cardinality
    int64 column (staging_allocator.cpp rank_compress_i64): returns
    ``(ranks, n_distinct)`` — a uint16 rank array whose stable argsort
    equals the keys' stable argsort, plus the exact distinct count the
    kernel already knows (so callers never rescan for it) — or None
    when unavailable/ineligible/cardinality > 65536 (the kernel aborts
    its scan at the 65537th distinct, so the failed probe costs well
    under a millisecond on high-cardinality data)."""
    if _NATIVE is None or not hasattr(_NATIVE, "rank_compress_i64"):
        return None
    if (
        keys.ndim != 1 or keys.dtype != np.int64
        or (len(keys) and keys.strides[0] != 8)
    ):
        return None
    ranks = np.empty(len(keys), np.uint16)
    g = _NATIVE.rank_compress_i64(
        keys.ctypes.data, len(keys), ranks.ctypes.data
    )
    if g < 0:
        return None
    return ranks, int(g)


def native_merge_runs_groups(key_runs, val_runs):
    """Fused group-by-key merge over key-sorted runs (one streaming C
    pass; staging_allocator.cpp merge_runs_groups_i64).  ``key_runs``
    are contiguous int64 key columns, ``val_runs`` the matching
    contiguous fixed-itemsize value columns.  Returns ``(uniq_keys,
    merged_vals, group_offs)`` — group ``i``'s values are the VIEW
    ``merged_vals[group_offs[i]:group_offs[i+1]]``, ordered run-0's
    rows first (bit-exact with the per-key Python merge's batch
    order) — or None when unavailable/ineligible."""
    if _NATIVE is None or not hasattr(_NATIVE, "merge_runs_groups_i64"):
        return None
    if len(key_runs) != len(val_runs) or not key_runs:
        return None
    vdt = val_runs[0].dtype
    if vdt.hasobject:
        # memcpy'ing PyObject* rows would duplicate references
        # without INCREF — double-free on collection
        return None
    for k, v in zip(key_runs, val_runs):
        if (
            k.ndim != 1 or k.dtype != np.int64
            or (len(k) and k.strides[0] != 8)
            or v.ndim != 1 or v.dtype != vdt
            or (len(v) and v.strides[0] != vdt.itemsize)
            or len(k) != len(v)
        ):
            return None
    n = sum(len(k) for k in key_runs)
    out_vals = np.empty(n, vdt)
    out_keys = np.empty(n, np.int64)
    out_offs = np.empty(n + 1, np.int64)
    nruns = len(key_runs)
    kptrs = (ctypes.c_void_p * nruns)(*[k.ctypes.data for k in key_runs])
    vptrs = (ctypes.c_void_p * nruns)(*[v.ctypes.data for v in val_runs])
    lens = (ctypes.c_int64 * nruns)(*[len(k) for k in key_runs])
    g = _NATIVE.merge_runs_groups_i64(
        kptrs, vptrs, lens, nruns, vdt.itemsize,
        out_vals.ctypes.data, out_keys.ctypes.data, out_offs.ctypes.data,
    )
    if g < 0:
        return None
    # copy the (small) group-level slices so the full n-sized scratch
    # isn't pinned behind the views for the consumer's lifetime
    return out_keys[:g].copy(), out_vals, out_offs[: g + 1].copy()


def _flat_u8(data):
    """Flat uint8 view of any contiguous bytes-like (no copy), or None
    when the buffer protocol won't yield one."""
    try:
        return np.frombuffer(data, np.uint8)
    except (TypeError, ValueError, BufferError):
        return None


def native_frame_spans(data, prefix: int):
    """(start, end) spans of length-prefixed frames (``prefix`` opaque
    bytes + 4B LE length + body) in one C walk — the serde
    frame-walking loops (PickleSerializer prefix=0, CompressedSerializer
    prefix=1) pay one interpreted iteration PER FRAME otherwise.
    Returns an int64 [n, 2] span array, or None when the native lib is
    absent, the buffer won't view flat, or the stream is truncated —
    the caller re-walks in Python (raising its detailed error)."""
    if _NATIVE is None or not hasattr(_NATIVE, "frame_spans_lp"):
        return None
    arr = _flat_u8(data)
    if arr is None:
        return None
    total = arr.shape[0]
    if total == 0:
        return np.empty((0, 2), np.int64)
    # frames are typically >= hundreds of bytes; grow on the rare -2
    cap = max(64, total // 256)
    while True:
        spans = np.empty((cap, 2), np.int64)
        n = _NATIVE.frame_spans_lp(
            arr.ctypes.data, total, prefix, spans.ctypes.data, cap
        )
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            return None
        return spans[:n]


def native_columnar_frame_spans(data):
    """(start, end) spans of columnar frames (serde.ColumnarSerializer
    0xC2/0xC3 framing) in one C walk, parsing the fixed-width dtype
    headers natively.  Returns an int64 [n, 2] span array, or None on
    lib-absent / truncation / exotic dtype strings / bad magic — the
    Python walker is the authority for every error path."""
    if _NATIVE is None or not hasattr(_NATIVE, "columnar_frame_spans"):
        return None
    arr = _flat_u8(data)
    if arr is None:
        return None
    total = arr.shape[0]
    if total == 0:
        return np.empty((0, 2), np.int64)
    cap = max(64, total // 256)
    while True:
        spans = np.empty((cap, 2), np.int64)
        n = _NATIVE.columnar_frame_spans(
            arr.ctypes.data, total, spans.ctypes.data, cap
        )
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            return None
        return spans[:n]


def native_crc32_spans(data, spans):
    """``out[i] = zlib.crc32(data[a_i:b_i])`` batched into ONE call —
    per-span zlib.crc32 pays Python call + buffer-protocol overhead
    per frame, which dominates for the small-frame shapes the decode
    plane checksums.  ``spans`` is any [n, 2] int-convertible array.
    Returns a uint32 array (bit-exact with zlib.crc32) or None when
    unavailable/ineligible (caller falls back to the zlib loop)."""
    if _NATIVE is None or not hasattr(_NATIVE, "crc32_spans"):
        return None
    arr = _flat_u8(data)
    if arr is None:
        return None
    sp = np.ascontiguousarray(spans, np.int64)
    if sp.ndim != 2 or sp.shape[1] != 2:
        return None
    n = sp.shape[0]
    if n == 0:
        return np.empty(0, np.uint32)
    # the kernel trusts the spans: bounds-check them here
    if (
        bool((sp[:, 0] < 0).any())
        or bool((sp[:, 1] < sp[:, 0]).any())
        or int(sp[:, 1].max()) > arr.shape[0]
    ):
        return None
    out = np.empty(n, np.uint32)
    _NATIVE.crc32_spans(arr.ctypes.data, sp.ctypes.data, n, out.ctypes.data)
    return out


def native_gather_blocks(dst: np.ndarray, src_addrs, lens, dst_offs) -> bool:
    """Batched ``dst[off:off+n] = block`` memcpy: ONE call assembles a
    whole exchange source row instead of one numpy slice assignment
    per map-output block (bulk._assemble).  ``src_addrs`` are raw
    buffer addresses — the CALLER keeps the owning arrays alive across
    the call.  Returns False (caller runs the slice-assignment loop)
    when unavailable or ineligible; every span is re-checked against
    ``dst`` before the memcpys run."""
    if _NATIVE is None or not hasattr(_NATIVE, "gather_blocks"):
        return False
    if dst.ndim != 1 or dst.dtype != np.uint8 or (
        dst.shape[0] and dst.strides[0] != 1
    ):
        return False
    a = np.ascontiguousarray(src_addrs, np.uint64)
    ln = np.ascontiguousarray(lens, np.int64)
    off = np.ascontiguousarray(dst_offs, np.int64)
    n = a.shape[0]
    if ln.shape[0] != n or off.shape[0] != n:
        return False
    if n == 0:
        return True
    if (
        bool((ln < 0).any()) or bool((off < 0).any())
        or int((off + ln).max()) > dst.shape[0]
    ):
        return False
    _NATIVE.gather_blocks(
        a.ctypes.data, ln.ctypes.data, dst.ctypes.data, off.ctypes.data, n
    )
    return True


def native_radix_scratch_trim() -> None:
    """Release the CALLING thread's radix-sort scratch (scratch above
    64 MiB is auto-freed after each sort; this hook drops the warm
    sub-threshold pages too — call it when a writer thread retires)."""
    if _NATIVE is not None and hasattr(_NATIVE, "radix_scratch_trim"):
        _NATIVE.radix_scratch_trim()


def native_hash_partition_order(keys: np.ndarray, num_partitions: int,
                                kmin: int, krange: int):
    """Fused splitmix64 %P + stable pid-major key-asc counting-sort
    order for int64 key columns (requires ``num_partitions * krange <=
    65536``).  Returns ``(order int64[n], counts int64[P])`` or None
    when the native lib is absent / the column doesn't qualify —
    callers fall back to the numpy two-sort path.  Bit-exact with
    HashPartitioner.partition_array + the composite radix argsort."""
    if _NATIVE is None or not hasattr(_NATIVE, "hash_partition_order"):
        return None
    if (
        keys.ndim != 1 or keys.dtype != np.int64
        or keys.strides[0] != 8
        or num_partitions * krange > (1 << 16)
    ):
        return None
    n = keys.shape[0]
    order = np.empty(n, np.int64)
    counts = np.empty(num_partitions, np.int64)
    rc = _NATIVE.hash_partition_order(
        keys.ctypes.data, n, num_partitions, kmin, krange,
        counts.ctypes.data, order.ctypes.data,
    )
    if rc != 0:
        return None
    return order, counts


def alloc_row_gc(pool, nbytes: int, fallback_counter: str) -> np.ndarray:
    """One pooled contiguous row sized exactly ``nbytes`` whose release
    is tied to GC of the returned view (``StagingPool.alloc_gc``) —
    shared by the bulk-exchange source rows and the striped-transport
    destination rows.  Falls back to a plain numpy buffer (counting the
    fallback under ``fallback_counter``) when no pool is wired or its
    budget is exhausted."""
    if nbytes <= 0:
        return np.empty(0, np.uint8)
    if pool is not None:
        try:
            return pool.alloc_gc(nbytes)[:nbytes]
        except MemoryError:
            counter(fallback_counter).inc()
    return np.empty(nbytes, np.uint8)


class StagingBuffer:
    """One pooled, page-aligned host buffer exposed as a numpy view."""

    def __init__(self, pool: "StagingPool", address: int, capacity: int,
                 view: np.ndarray):
        self._pool = pool
        self.address = address
        self.capacity = capacity
        self.view = view  # uint8[capacity], zero-copy over the native block
        self._freed = False

    def free(self) -> None:
        if not self._freed:
            self._freed = True
            self._pool._free(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.free()


class StagingPool:
    """Size-class pooled host buffers with a byte budget and LRU trim.

    Native-backed when ``_staging.so`` is present (``is_native``), else a
    Python pool with identical policy: power-of-two classes (min 16 KiB),
    trim idle blocks when idle bytes exceed 90% of the budget, down to
    65% (reference policy, RdmaBufferManager.java:150-188).
    """

    def __init__(self, max_bytes: int = 0, force_python: bool = False):
        self.max_bytes = max_bytes
        self.is_native = _NATIVE is not None and not force_python
        kind = "native" if self.is_native else "python"
        self._m_allocs = counter("staging_allocs_total", pool=kind)
        self._m_alloc_bytes = counter("staging_alloc_bytes_total", pool=kind)
        self._m_failed = counter("staging_failed_allocs_total", pool=kind)
        # hit = pooled block reused, miss = fresh memory; only the
        # python pool sees its free lists (the native pool recycles
        # internally), so hits/misses stay zero under the native pool
        self._m_hits = counter("staging_hits_total", pool=kind)
        self._m_misses = counter("staging_misses_total", pool=kind)
        # RLock: a cyclic-GC pass triggered INSIDE a locked region can
        # run an alloc_gc finalizer on the same thread, which takes
        # this lock again — re-entrant entry is safe (counter updates;
        # destroy needs _closed, impossible mid-alloc).  Deliberately a
        # PLAIN RLock, never a DebugLock: GC can fire the finalizer
        # while the triggering thread holds ANY lock, so rank checks
        # here would flag inversions that are not real lock-ordering
        # commitments.
        self._lock = threading.RLock()  # lock-order: 84
        self._closed = False
        # outstanding alloc_gc buffers: close() must DEFER destroying
        # the native pool until the last one is collected (destroying
        # frees the pages a live consumer view still reads)
        self._gc_live = 0
        if self.is_native:
            self._handle = _NATIVE.staging_pool_create(
                ctypes.c_uint64(max_bytes)
            )
            if not self._handle:
                raise MemoryError("staging_pool_create failed")
        else:
            # python fallback pool
            self._free_lists: Dict[int, list] = {}
            self._owned = 0
            self._in_use = 0
            self._tick = 0
            self._last_use: Dict[int, int] = {}
            self._failed = 0
            self._total_allocs = 0

    # -- public API ---------------------------------------------------------
    def alloc(self, size: int) -> StagingBuffer:
        if size <= 0:
            raise ValueError(f"alloc size must be > 0: {size}")
        if self._closed:
            raise MemoryError("pool closed")
        self._m_allocs.inc()
        self._m_alloc_bytes.inc(size)
        if self.is_native:
            ptr = _NATIVE.staging_alloc(self._handle, ctypes.c_uint64(size))
            if not ptr:
                self._m_failed.inc()
                raise MemoryError(
                    f"staging pool budget exhausted allocating {size}B "
                    f"(budget {self.max_bytes}B)"
                )
            cap = _NATIVE.staging_block_size(self._handle, ctypes.c_void_p(ptr))
            raw = (ctypes.c_uint8 * cap).from_address(ptr)
            view = np.frombuffer(raw, dtype=np.uint8)
            return StagingBuffer(self, ptr, cap, view)
        return self._py_alloc(size)

    def alloc_gc(self, size: int) -> np.ndarray:
        """Pooled buffer whose RELEASE is tied to garbage collection of
        the returned uint8 view and every numpy slice of it — the
        BufferReleasingInputStream analog
        (RdmaShuffleFetcherIterator.scala:377-406): consumers receive
        zero-copy slices of one pooled buffer and the buffer returns to
        the pool only when the last slice dies, so no explicit release
        call can free memory under a live view.

        Native pool: the block physically returns for reuse.  Python
        fallback: the memory goes back to the OS and only the
        accounting is adjusted (numpy owns the pages)."""
        if size <= 0:
            raise ValueError(f"alloc size must be > 0: {size}")
        self._m_allocs.inc()
        self._m_alloc_bytes.inc(size)
        if self.is_native:
            # closed-check, alloc, and the live-count publication happen
            # under ONE lock hold: close() destroys the native pool when
            # _gc_live == 0, so a gap here would let it free the handle
            # mid-allocation
            with self._lock:
                if self._closed or self._handle is None:
                    raise MemoryError("pool closed")
                ptr = _NATIVE.staging_alloc(
                    self._handle, ctypes.c_uint64(size)
                )
                if not ptr:
                    self._m_failed.inc()
                    raise MemoryError(
                        f"staging pool budget exhausted allocating {size}B "
                        f"(budget {self.max_bytes}B)"
                    )
                cap = _NATIVE.staging_block_size(
                    self._handle, ctypes.c_void_p(ptr)
                )
                self._gc_live += 1
            raw = (ctypes.c_uint8 * cap).from_address(ptr)

            def _ret(pool=self, address=ptr):
                # runs when raw (kept alive by every slice's base chain)
                # is collected; the handle stays valid after close()
                # because destroy is deferred to the LAST of us.  Free
                # and the destroy decision happen under ONE lock hold —
                # two finalizers racing could otherwise free into a
                # just-destroyed pool.
                with pool._lock:
                    handle = pool._handle
                    if handle is not None:
                        _NATIVE.staging_free(
                            handle, ctypes.c_void_p(address)
                        )
                    pool._gc_live -= 1
                    destroy = (
                        pool._closed and pool._gc_live == 0
                        and handle is not None
                    )
                    if destroy:
                        pool._handle = None
                if destroy:
                    _NATIVE.staging_pool_destroy(handle)

            weakref.finalize(raw, _ret)
            return np.frombuffer(raw, dtype=np.uint8)
        # python fallback: fresh numpy memory, GC frees it to the OS
        if self._closed:
            raise MemoryError("pool closed")
        cls = self._round_class(size)
        self._m_misses.inc()
        with self._lock:
            self._py_reserve(size, cls)
            self._owned += cls
            self._in_use += cls
        view = np.empty(cls, dtype=np.uint8)

        def _reclaim(pool=self, cls=cls):
            with pool._lock:
                pool._owned -= cls
                pool._in_use -= cls

        weakref.finalize(view, _reclaim)
        return view

    def stats(self) -> Dict[str, int]:
        if self.is_native:
            arr = (ctypes.c_uint64 * 6)()
            # hold the lock across the native call: the deferred-destroy
            # finalizer must not tear the handle down mid-read
            with self._lock:
                if self._handle:
                    _NATIVE.staging_pool_stats(self._handle, arr)
            return dict(zip(STAT_FIELDS, (int(x) for x in arr)))
        with self._lock:
            idle = self._owned - self._in_use
            return {
                "owned": self._owned, "in_use": self._in_use, "idle": idle,
                "num_classes": len(self._free_lists),
                "failed_allocs": self._failed,
                "total_allocs": self._total_allocs,
            }

    def prealloc(self, total_bytes: int, block_size: int) -> int:
        """Warm the pool with ``total_bytes`` worth of ``block_size``
        blocks (reference: executor-side async preallocation of
        maxAggBlock buffers, RdmaBufferManager.java:112-120).  Returns
        the number of blocks preallocated."""
        if total_bytes <= 0 or block_size <= 0:
            return 0
        n = max(1, total_bytes // block_size)
        bufs = []
        try:
            for _ in range(n):
                bufs.append(self.alloc(block_size))
        except MemoryError:
            pass  # budget hit: keep what we got
        count = len(bufs)
        for b in bufs:
            b.free()
        return count

    def trim(self, target_idle_bytes: int = 0) -> None:
        if self.is_native:
            _NATIVE.staging_pool_trim(
                self._handle, ctypes.c_uint64(target_idle_bytes)
            )
        else:
            with self._lock:
                self._py_trim(target_idle_bytes)

    def close(self) -> None:
        if self._closed:
            return
        handle = None
        with self._lock:
            self._closed = True
            if self.is_native and self._gc_live == 0:
                handle, self._handle = self._handle, None
            # else (gc_live > 0): the LAST outstanding alloc_gc
            # buffer's finalizer destroys the pool — destroying now
            # would free pages a live consumer view still reads
        if handle:
            _NATIVE.staging_pool_destroy(handle)
        if not self.is_native:
            self._free_lists.clear()

    # -- internals ----------------------------------------------------------
    def _free(self, buf: StagingBuffer) -> None:
        if self._closed:
            return
        if self.is_native:
            rc = _NATIVE.staging_free(self._handle, ctypes.c_void_p(buf.address))
            if rc != 0:
                logger.warning("staging_free: unknown/double-freed buffer")
        else:
            self._py_free(buf)

    @staticmethod
    def _round_class(size: int) -> int:
        c = MIN_BLOCK_SIZE
        while c < size:
            c <<= 1
        return c

    def _py_reserve(self, size: int, cls: int) -> None:
        """Account one allocation and ensure budget headroom for a NEW
        ``cls``-sized block (lock held; shared by _py_alloc and the
        python alloc_gc path so the trim/budget policy lives once)."""
        self._tick += 1
        self._total_allocs += 1
        self._last_use[cls] = self._tick
        if self.max_bytes and self._owned + cls > self.max_bytes:
            self._py_trim(0)
            if self._owned + cls > self.max_bytes:
                self._failed += 1
                self._m_failed.inc()
                raise MemoryError(
                    f"staging pool budget exhausted allocating {size}B"
                )

    def _py_alloc(self, size: int) -> StagingBuffer:
        cls = self._round_class(size)
        with self._lock:
            lst = self._free_lists.setdefault(cls, [])
            if lst:
                self._tick += 1
                self._total_allocs += 1
                self._last_use[cls] = self._tick
                view = lst.pop()
                hit = True
            else:
                self._py_reserve(size, cls)
                view = np.zeros(cls, dtype=np.uint8)
                self._owned += cls
                hit = False
            self._in_use += cls
        (self._m_hits if hit else self._m_misses).inc()
        return StagingBuffer(self, view.ctypes.data, cls, view)

    def _py_free(self, buf: StagingBuffer) -> None:
        cls = buf.capacity
        with self._lock:
            self._tick += 1
            self._last_use[cls] = self._tick
            self._free_lists.setdefault(cls, []).append(buf.view)
            self._in_use -= cls
            if self.max_bytes:
                idle = self._owned - self._in_use
                if idle > 0.9 * self.max_bytes:
                    self._py_trim(int(0.65 * self.max_bytes))

    def _py_trim(self, target_idle: int) -> None:
        # assumes lock held
        idle = self._owned - self._in_use
        order = sorted(
            (s for s in self._free_lists if self._free_lists[s]),
            key=lambda s: self._last_use.get(s, 0),
        )
        for cls in order:
            if idle <= target_idle:
                break
            n = len(self._free_lists[cls])
            self._free_lists[cls] = []
            self._owned -= n * cls
            idle -= n * cls

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
