"""HBM arena registry: registered device segments serving block reads.

The device-side half of the memory layer.  Where the reference mmaps a
shuffle data file in ≥write-block-size chunks and registers each chunk as
an ibverbs MR (RdmaMappedFile.java:95-171), here a map task's serialized
output is staged into one or more ``DeviceSegment``s — uint8 JAX arrays
resident in HBM — each tagged with an ``mkey``.  A ``BlockLocation``
then addresses (mkey, byte offset, length) exactly like the reference's
(mkey, address, length) triple.

``ArenaManager`` is the per-process registry: it assigns mkeys, accounts
bytes against ``max_buffer_allocation_size``, serves one-sided reads
(``BlockStore``), and releases segments when a shuffle is unregistered
(dispose path, RdmaMappedFile.java:189-199).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.transport.channel import BlockStore, TransportError
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.types import BlockLocation


class DeviceSegment:
    """One registered HBM segment (a uint8 device array).

    ``keepalive`` pins an underlying host buffer (e.g. a pooled staging
    buffer PJRT may have zero-copy aliased) until the segment is
    released; its ``free()`` is called exactly once on release."""

    def __init__(self, mkey: int, array, shuffle_id: Optional[int] = None,
                 keepalive=None, budgeted: bool = True,
                 zero_copy_ok: bool = False):
        self.mkey = mkey
        self.array = array  # jax.Array uint8[nbytes] (or np.ndarray on host)
        self.nbytes = int(array.shape[0])
        self.shuffle_id = shuffle_id
        self.keepalive = keepalive
        self.budgeted = budgeted
        self.zero_copy_ok = zero_copy_ok
        self.created_at = time.monotonic()

    def _release_keepalive(self) -> None:
        ka, self.keepalive = self.keepalive, None
        if ka is not None:
            try:
                ka.free()
            except Exception:
                pass

    def read(self, offset: int, length: int):
        """Serve one block.  Host-resident segments (plain numpy or
        mmap) return a ZERO-COPY read-only view — safe because the view
        keeps the backing buffer alive by refcount after release (the
        reference's zero-copy DirectByteBuffer serving,
        RdmaMappedFile.java:225-229).  Device segments materialize a
        host copy (the device→host transfer is the copy).  Pool-backed
        host buffers must NOT be registered with ``zero_copy_ok`` —
        the pool reuses freed memory under live views."""
        end = offset + length
        if offset < 0 or end > self.nbytes:
            raise TransportError(
                f"read [{offset},{end}) outside segment mkey={self.mkey} "
                f"of {self.nbytes}B"
            )
        if self.zero_copy_ok:
            ka = self.keepalive
            if length >= DIRECT_READ_MIN and hasattr(ka, "pread"):
                # big file-backed blocks read O_DIRECT: buffered mmap
                # faults are writeback/readahead-throttled on
                # virtualized hosts (~5x slower — memory/direct_io.py)
                got = ka.pread(offset, length)
                if got is not None:
                    return got
            view = self.array[offset:end].view()
            view.flags.writeable = False
            return view
        return bytes(np.asarray(self.array[offset:end]))

    def read_many(self, spans):
        """Serve many ``(offset, length)`` blocks with batched
        device→host transfers (a per-block ``read`` costs a device
        slice dispatch + host round-trip EACH — through the real
        chip's tunnel that is milliseconds per block).  Spans cluster
        by proximity (:func:`_read_spans_clustered`) so one transfer
        covers each dense run while large gaps are skipped.  Host
        segments keep the per-span zero-copy views."""
        if not spans:
            return []
        lo = min(o for o, _l in spans)
        hi = max(o + _l for o, _l in spans)
        if lo < 0 or hi > self.nbytes:
            raise TransportError(
                f"read_many [{lo},{hi}) outside segment "
                f"mkey={self.mkey} of {self.nbytes}B"
            )
        if isinstance(self.array, np.ndarray):
            return [self.read(o, l) for o, l in spans]
        return _read_spans_clustered(
            spans, lambda a, b: np.asarray(self.array[a:b])
        )


# read_many clusters spans whose gaps exceed this: a sparse batch (two
# small blocks at opposite ends of a big segment) must not materialize
# the whole gap to host
READ_MANY_MAX_GAP = 8 << 20

# blocks at least this large take the O_DIRECT pread path on
# file-backed segments; smaller ones stay zero-copy mmap views
DIRECT_READ_MIN = 1 << 20


def _read_spans_clustered(spans, fetch):
    """Serve ``(offset, length)`` spans via ``fetch(lo, hi)`` range
    reads, one per proximity cluster (gaps above READ_MANY_MAX_GAP are
    skipped rather than transferred).  Returns blocks in input order —
    as zero-copy CHUNK VIEWS of each cluster's landed buffer (the view
    keeps the cluster alive by refcount; re-materializing every block
    as ``bytes`` doubled the serve path's copies)."""
    order = sorted(range(len(spans)), key=lambda i: spans[i][0])
    out: list = [b""] * len(spans)
    cluster: list = []
    cend = 0

    def flush():
        if not cluster:
            return
        clo = spans[cluster[0]][0]
        chi = max(spans[i][0] + spans[i][1] for i in cluster)
        buf = fetch(clo, chi)
        for i in cluster:
            o, ln = spans[i]
            out[i] = buf[o - clo : o - clo + ln]
        cluster.clear()

    for i in order:
        o, ln = spans[i]
        if cluster and o - cend > READ_MANY_MAX_GAP:
            flush()
        cluster.append(i)
        cend = max(cend, o + ln)
    flush()
    return out


class ArenaSpanSegment:
    """A registered span of the persistent per-device HBM arena
    (memory/device_arena.py) — the collective read plane's MR analog.
    Duck-types DeviceSegment for the ArenaManager bookkeeping; the
    coordinator recognizes it via its ``span`` attribute and resolves
    block locations to absolute arena offsets."""

    __slots__ = ("mkey", "span", "nbytes", "shuffle_id", "budgeted",
                 "zero_copy_ok", "keepalive")

    def __init__(self, mkey: int, span, shuffle_id: Optional[int] = None):
        self.mkey = mkey
        self.span = span
        self.nbytes = span.nbytes
        self.shuffle_id = shuffle_id
        self.budgeted = True
        self.zero_copy_ok = False
        self.keepalive = None

    def _release_keepalive(self) -> None:
        self.span.free()

    def read(self, offset: int, length: int) -> bytes:
        end = offset + length
        if offset < 0 or end > self.nbytes:
            raise TransportError(
                f"read [{offset},{end}) outside arena span mkey={self.mkey} "
                f"of {self.nbytes}B"
            )
        return self.span.arena.read(self.span.offset + offset, length)

    def read_many(self, spans):
        """Clustered arena reads, sliced per block (see
        DeviceSegment.read_many)."""
        if not spans:
            return []
        lo = min(o for o, _l in spans)
        hi = max(o + _l for o, _l in spans)
        if lo < 0 or hi > self.nbytes:
            raise TransportError(
                f"read_many [{lo},{hi}) outside arena span "
                f"mkey={self.mkey} of {self.nbytes}B"
            )
        base = self.span.offset
        return _read_spans_clustered(
            spans,
            lambda a, b: memoryview(
                self.span.arena.read(base + a, b - a)
            ),
        )


class ArenaManager(BlockStore):
    """Per-process registry of device segments, keyed by mkey."""

    def __init__(self, max_bytes: int = 0):
        self.max_bytes = max_bytes
        self._segments: Dict[int, DeviceSegment] = {}  # guarded-by: _lock
        self._lock = dbg_lock("arena.segments", 82)
        self._next_mkey = 1  # 0 is reserved for BlockLocation.EMPTY
        self._total_bytes = 0  # guarded-by: _lock
        # resource: arena.registered_bytes (device + file segment bytes)
        self._tickets: Dict[int, object] = {}  # guarded-by: _lock
        # unbudgeted (file-backed mmap) segment bytes
        self._file_bytes = 0  # guarded-by: _lock
        # stats
        self._registered_ever = 0
        self._released_ever = 0
        self._m_registered = counter("arena_segments_registered_total")
        self._m_released = counter("arena_segments_released_total")
        self._m_alloc_failed = counter("arena_alloc_failures_total")
        # process-wide gauge shared by every ArenaManager: mutate by
        # DELTA so in-process driver+executor arenas aggregate
        self._m_bytes = gauge("arena_registered_bytes")

    def register(self, array, shuffle_id: Optional[int] = None,
                 keepalive=None, budgeted: bool = True,
                 zero_copy_ok: bool = False) -> DeviceSegment:
        """Register a 1-D uint8 array as a readable segment.

        ``budgeted=False`` registers without debiting the byte budget —
        for file-backed (mmap) segments whose pages live in the OS
        cache, not the arena's memory (their bytes are tracked in the
        ``file_bytes`` stat instead).

        ``zero_copy_ok`` lets reads serve views into ``array`` — ONLY
        safe when the backing memory is never recycled while Python
        references exist (plain numpy buffers, read-only mmaps; NOT
        pooled staging buffers)."""
        if array.ndim != 1 or str(array.dtype) != "uint8":
            raise ValueError(
                f"segments must be 1-D uint8, got {array.shape} {array.dtype}"
            )
        nbytes = int(array.shape[0])
        with self._lock:
            if (budgeted and self.max_bytes
                    and self._total_bytes + nbytes > self.max_bytes):
                self._m_alloc_failed.inc()
                raise MemoryError(
                    f"arena budget exhausted: {self._total_bytes + nbytes}B > "
                    f"{self.max_bytes}B"
                )
            mkey = self._next_mkey
            self._next_mkey += 1
            seg = DeviceSegment(mkey, array, shuffle_id, keepalive=keepalive,
                                budgeted=budgeted, zero_copy_ok=zero_copy_ok)
            self._segments[mkey] = seg
            if budgeted:
                self._total_bytes += nbytes
            else:
                self._file_bytes += nbytes
            self._registered_ever += 1
            # the segment's byte reservation rides the registry until an
            # unregister path settles it
            # owns: arena.registered_bytes -> release
            # owns: arena.registered_bytes -> release_shuffle
            # owns: arena.registered_bytes -> stop
            # owns: arena.registered_bytes -> replace_with_span
            self._tickets[mkey] = ledger_acquire(
                "arena.registered_bytes", nbytes
            )  # acquires: arena.registered_bytes
        self._m_registered.inc()
        self._m_bytes.inc(nbytes)
        return seg

    def register_external(self, seg):
        """Register a segment whose storage this arena does NOT manage
        (the tiered block store's file-backed segments, memory/tier.py):
        assigns the mkey, tracks the bytes in the ``file_bytes`` stat
        (never the arena byte budget — the data lives on disk / in
        pooled hot rows the tier itself budgets), and dispatches reads
        to the segment like any other.  ``seg`` must duck-type
        DeviceSegment (nbytes / shuffle_id / budgeted=False /
        read / read_many / _release_keepalive)."""
        with self._lock:
            mkey = self._next_mkey
            self._next_mkey += 1
            seg.mkey = mkey
            self._segments[mkey] = seg
            self._file_bytes += seg.nbytes
            self._registered_ever += 1
            # owns: arena.registered_bytes -> release
            self._tickets[mkey] = ledger_acquire(
                "arena.registered_bytes", seg.nbytes
            )  # acquires: arena.registered_bytes
        self._m_registered.inc()
        self._m_bytes.inc(seg.nbytes)
        return seg

    def register_arena_span(self, span, shuffle_id: Optional[int] = None
                            ) -> ArenaSpanSegment:
        """Register an allocated device-arena span as a readable
        segment (its HBM is real, so it debits the byte budget; the
        span is freed back to its arena on release)."""
        with self._lock:
            if (self.max_bytes
                    and self._total_bytes + span.nbytes > self.max_bytes):
                self._m_alloc_failed.inc()
                raise MemoryError(
                    f"arena budget exhausted: "
                    f"{self._total_bytes + span.nbytes}B > {self.max_bytes}B"
                )
            mkey = self._next_mkey
            self._next_mkey += 1
            seg = ArenaSpanSegment(mkey, span, shuffle_id)
            self._segments[mkey] = seg
            self._total_bytes += seg.nbytes
            self._registered_ever += 1
            # owns: arena.registered_bytes -> release
            self._tickets[mkey] = ledger_acquire(
                "arena.registered_bytes", seg.nbytes
            )  # acquires: arena.registered_bytes
        self._m_registered.inc()
        self._m_bytes.inc(seg.nbytes)
        return seg

    def replace_with_span(self, mkey: int, span
                          ) -> Optional[ArenaSpanSegment]:
        """Swap a host-resident segment for a device-arena span under
        the SAME mkey — the on-demand registration step of the lazy
        staging (ODP) path: published BlockLocations keep working
        because the mkey never changes.  Returns the new segment, or
        None (freeing ``span``) when the mkey is gone."""
        with self._lock:
            old = self._segments.get(mkey)
            if old is None:
                released = None
            else:
                freed = old.nbytes if old.budgeted else 0
                if (self.max_bytes and self._total_bytes - freed
                        + span.nbytes > self.max_bytes):
                    self._m_alloc_failed.inc()
                    raise MemoryError(
                        f"arena budget exhausted staging mkey={mkey}: "
                        f"{self._total_bytes - freed + span.nbytes}B > "
                        f"{self.max_bytes}B"
                    )
                seg = ArenaSpanSegment(mkey, span, old.shuffle_id)
                self._segments[mkey] = seg
                if old.budgeted:
                    self._total_bytes -= old.nbytes
                else:
                    self._file_bytes -= old.nbytes
                self._total_bytes += seg.nbytes
                released = old
                old_tkt = self._tickets.pop(mkey, NOOP_TICKET)
                # owns: arena.registered_bytes -> release
                self._tickets[mkey] = ledger_acquire(
                    "arena.registered_bytes", seg.nbytes
                )  # acquires: arena.registered_bytes
        if released is None:
            span.free()
            return None
        self._m_bytes.inc(seg.nbytes - released.nbytes)
        old_tkt.release()  # releases: arena.registered_bytes
        released._release_keepalive()
        return seg

    def get(self, mkey: int) -> Optional[DeviceSegment]:
        with self._lock:
            return self._segments.get(mkey)

    def release(self, mkey: int) -> None:
        with self._lock:
            seg = self._segments.pop(mkey, None)
            if seg is not None:
                if seg.budgeted:
                    self._total_bytes -= seg.nbytes
                else:
                    self._file_bytes -= seg.nbytes
                self._released_ever += 1
            tkt = self._tickets.pop(mkey, NOOP_TICKET)
        if seg is not None:
            self._m_released.inc()
            self._m_bytes.dec(seg.nbytes)
            tkt.release()  # releases: arena.registered_bytes
            seg._release_keepalive()

    def release_shuffle(self, shuffle_id: int) -> int:
        """Release all segments belonging to one shuffle (unregister path,
        reference: RdmaShuffleManager.unregisterShuffle → dispose)."""
        with self._lock:
            doomed = [k for k, s in self._segments.items()
                      if s.shuffle_id == shuffle_id]
            segs = [self._segments.pop(k) for k in doomed]
            tkts = [self._tickets.pop(k, NOOP_TICKET) for k in doomed]
            for seg in segs:
                if seg.budgeted:
                    self._total_bytes -= seg.nbytes
                else:
                    self._file_bytes -= seg.nbytes
                self._released_ever += 1
        if segs:
            self._m_released.inc(len(segs))
            self._m_bytes.dec(sum(s.nbytes for s in segs))
        for tkt in tkts:
            tkt.release()  # releases: arena.registered_bytes
        for seg in segs:
            seg._release_keepalive()
        return len(segs)

    # -- BlockStore ---------------------------------------------------------
    def read_block(self, location: BlockLocation) -> bytes:
        seg = self.get(location.mkey)
        if seg is None:
            raise TransportError(f"no segment registered for mkey={location.mkey}")
        return seg.read(location.address, location.length)

    def read_blocks(self, locations) -> list:
        """Serve many blocks, batching per backing segment
        (``Segment.read_many``: one device→host transfer per segment
        instead of per block — the one-sided READ service groups
        fetches, and a grouped fetch usually hits one map segment)."""
        by_key: Dict[int, list] = {}
        for i, loc in enumerate(locations):
            by_key.setdefault(loc.mkey, []).append(i)
        out: list = [b""] * len(locations)
        for mkey, idxs in by_key.items():
            seg = self.get(mkey)
            if seg is None:
                raise TransportError(
                    f"no segment registered for mkey={mkey}"
                )
            blocks = seg.read_many(
                [(locations[i].address, locations[i].length)
                 for i in idxs]
            )
            for i, b in zip(idxs, blocks):
                out[i] = b
        return out

    # -- stats --------------------------------------------------------------
    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total_bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "segments": len(self._segments),
                "total_bytes": self._total_bytes,
                "file_bytes": self._file_bytes,
                "registered_ever": self._registered_ever,
                "released_ever": self._released_ever,
            }

    def stop(self) -> None:
        with self._lock:
            segs = list(self._segments.values())
            self._segments.clear()
            tkts = list(self._tickets.values())
            self._tickets.clear()
            self._total_bytes = 0
            self._file_bytes = 0
        if segs:
            self._m_released.inc(len(segs))
            self._m_bytes.dec(sum(s.nbytes for s in segs))
        for tkt in tkts:
            tkt.release()  # releases: arena.registered_bytes
        for seg in segs:
            seg._release_keepalive()
