"""Persistent per-device HBM arenas: the registered-MR pool the
collective data plane reads from.

The reference registers each shuffle file's chunks as ibverbs MRs and
reducers pull byte ranges with one-sided READs against (addr, len, key)
(RdmaMappedFile.java:95-171, RdmaChannel.java:441-474).  The TPU analog
(SURVEY.md §7 mapping): ONE persistent uint8 HBM array per executor
device — commits sub-allocate spans and write their bytes in with a
donated ``dynamic_update_slice`` — so every committed block on a device
is addressable as (arena, offset, length), and one mesh-wide gather can
pack ANY set of blocks for an ``all_to_all`` round without per-segment
program shapes (the arena's shape is fixed, so the pack program
compiles once).

Allocation is a first-fit free list with coalescing (the
RdmaBufferManager role for device memory); writes are padded to
``WRITE_ALIGN`` so the update-slice programs compile per size class,
not per commit.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import numpy as np

from sparkrdma_tpu.utils.dbglock import dbg_lock

WRITE_ALIGN = 4096  # commit padding granularity (4 KiB, the mmap analog)


def _size_class(nbytes: int) -> int:
    """Span size class ≥ WRITE_ALIGN: the next {2^k, 1.5·2^k} value
    (shared by alloc and write; write's reshape to ROW_BYTES rows
    relies on spans being classed this way).  Two classes per octave
    keep the donated-write program count logarithmic while capping
    allocation waste at ~33% (pure pow2 classes wasted up to 2x of the
    arena on large commits)."""
    n = int(nbytes)
    if n <= WRITE_ALIGN:
        return WRITE_ALIGN
    p = 1 << (n - 1).bit_length()  # next pow2
    threeq = (p >> 1) + (p >> 2)   # 1.5·(p/2) = 0.75·p
    if n <= threeq and threeq % WRITE_ALIGN == 0:
        return threeq
    return p

# gather granularity of the collective read plane: block offsets within
# an arena must be multiples of this (byte-granular device gathers are
# ~100x slower than row gathers); WRITE_ALIGN is a multiple, so span
# starts are always row-aligned
ROW_BYTES = 128


@functools.lru_cache(maxsize=None)
def _write_fn(arena_rows: int, chunk_rows: int):
    """Jitted in-place arena write (donated: XLA reuses the arena
    buffer instead of copying the whole arena)."""
    import jax

    def body(arena, chunk, row_offset):
        return jax.lax.dynamic_update_slice(
            arena, chunk, (row_offset, 0)
        )

    return jax.jit(body, donate_argnums=(0,))


class ArenaSpan:
    """One allocated byte range of a device arena."""

    __slots__ = ("arena", "offset", "nbytes", "freed")

    def __init__(self, arena: "DeviceArena", offset: int, nbytes: int):
        self.arena = arena
        self.offset = offset
        self.nbytes = nbytes
        self.freed = False

    def free(self) -> None:
        self.arena.free(self)


class DeviceArena:
    """One persistent uint8 HBM array on a single device.

    The array is natively 2-D ``[rows, ROW_BYTES]`` — the exact shape
    the collective pack program consumes, so a flush hands XLA each
    device's arena buffer as-is (a 1-D array reshaped at flush time
    carries a non-default layout and forces a full arena relayout copy
    inside EVERY exchange round — measured 20x slower)."""

    def __init__(self, capacity: int, device=None):
        import jax
        import jax.numpy as jnp

        capacity = (capacity + WRITE_ALIGN - 1) // WRITE_ALIGN * WRITE_ALIGN
        self.capacity = capacity
        self.rows = capacity // ROW_BYTES
        self.device = device if device is not None else jax.devices()[0]
        with jax.default_device(self.device):
            self.array = jnp.zeros((self.rows, ROW_BYTES), jnp.uint8)
        self._lock = dbg_lock("device_arena.free_list", 80)
        # first-fit free list: sorted non-adjacent (offset, nbytes)
        self._free: List[Tuple[int, int]] = [(0, capacity)]  # guarded-by: _lock
        self.allocated_bytes = 0
        self.peak_bytes = 0
        self.writes = 0

    # -- allocation ---------------------------------------------------------
    def alloc(self, nbytes: int) -> ArenaSpan:
        """First-fit allocate a size-classed span (the buffer-manager
        size classes, RdmaBufferManager.java:88,135-147 — here the
        classes also bound how many distinct donated-write programs XLA
        compiles: one per class, not one per commit size)."""
        need = _size_class(nbytes)
        with self._lock:
            for i, (off, size) in enumerate(self._free):
                if size >= need:
                    if size == need:
                        self._free.pop(i)
                    else:
                        self._free[i] = (off + need, size - need)
                    self.allocated_bytes += need
                    self.peak_bytes = max(self.peak_bytes, self.allocated_bytes)
                    return ArenaSpan(self, off, need)
        raise MemoryError(
            f"device arena exhausted: need {need}B, "
            f"{self.capacity - self.allocated_bytes}B free (fragmented)"
        )

    def free(self, span: ArenaSpan) -> None:
        with self._lock:
            if span.freed:
                return
            span.freed = True
            self.allocated_bytes -= span.nbytes
            # insert sorted + coalesce with neighbors
            entry = (span.offset, span.nbytes)
            lo, hi = 0, len(self._free)
            while lo < hi:
                mid = (lo + hi) // 2
                if self._free[mid][0] < entry[0]:
                    lo = mid + 1
                else:
                    hi = mid
            self._free.insert(lo, entry)
            i = max(0, lo - 1)
            while i < len(self._free) - 1:
                off, size = self._free[i]
                noff, nsize = self._free[i + 1]
                if off + size == noff:
                    self._free[i] = (off, size + nsize)
                    self._free.pop(i + 1)
                else:
                    if i >= lo:
                        break
                    i += 1

    # -- data movement ------------------------------------------------------
    def write(self, span: ArenaSpan, data: np.ndarray) -> None:
        """Write host bytes into the span (donated in-place update on
        device; data is padded to the span's aligned size so the
        programs compile per size class)."""
        import jax
        import jax.numpy as jnp

        n = int(data.shape[0])
        if n > span.nbytes:
            raise ValueError(f"write of {n}B exceeds span of {span.nbytes}B")
        # pad to the next size class ≤ span (spans are pow2-classed), so
        # the donated-update program count stays logarithmic while the
        # host copy stays near the payload size
        chunk_n = min(span.nbytes, _size_class(n))
        if n < chunk_n:
            padded = np.zeros(chunk_n, np.uint8)
            padded[:n] = data
            data = padded
        with self._lock:
            self.writes += 1
            with jax.default_device(self.device):
                chunk = jnp.asarray(data.reshape(-1, ROW_BYTES))
                fn = _write_fn(self.rows, chunk_n // ROW_BYTES)
                self.array = fn(
                    self.array, chunk, np.int32(span.offset // ROW_BYTES)
                )

    def read(self, offset: int, length: int) -> bytes:
        """Host read (transport fallback / local short-circuit): one
        device→host copy of just the covering row range.  Materializes
        under the arena lock — a concurrent donated write invalidates
        the previous buffer, so an unlocked slice could observe a
        deleted array mid-copy."""
        end = offset + length
        if offset < 0 or end > self.capacity:
            raise ValueError(
                f"read [{offset},{end}) outside arena of {self.capacity}B"
            )
        r0 = offset // ROW_BYTES
        r1 = (end + ROW_BYTES - 1) // ROW_BYTES
        with self._lock:
            rows = np.asarray(self.array[r0:r1]).reshape(-1)
        lo = offset - r0 * ROW_BYTES
        return bytes(rows[lo : lo + length])

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "allocated_bytes": self.allocated_bytes,
                "peak_bytes": self.peak_bytes,
                "free_extents": len(self._free),
                "writes": self.writes,
            }


class DeviceStagingBridge:
    """Registered staging rows → reusable device donor buffers: the H2D
    seam of the device-native exchange.

    The reference stages shuffle bytes through registered MRs so the
    NIC can DMA them without a bounce copy (RdmaBuffer/
    RdmaBufferManager); the TPU analog stages each source's exchange
    payload ONCE into a POOLED host row (memory/staging.py — recycled
    across windows exactly like the RdmaBuffer pool) laid out in the
    exchange's padded device framing, and hands it to XLA as a device
    array via one ``jax.device_put`` per source row.  The jitted
    consumer donates the device buffer back to XLA after the collective
    (``donate_argnums``), so steady state is: pooled host row reused
    window over window, device buffer reused round over round, and ZERO
    intermediate ``bytes`` objects or per-round host staging matrices
    in between (counter ``device_exchange_h2d_bytes_avoided_total``
    tracks the host fill traffic the bridge eliminated).

    Framing helpers (``padded_cols``, ``as_words``) keep the layout
    rules in ONE place: rows are uint8, lane-aligned to the exchange's
    ``TILE_ALIGN``, and reinterpreted as uint32 words for the
    collective (4x fewer elements through the permutation at identical
    bytes; views require the 4-byte alignment the pools guarantee).
    """

    WORD = 4  # collective element width: uint32 words over uint8 lanes

    def __init__(self, pool=None):
        # optional StagingPool; None falls back to plain numpy rows
        # (the alloc_row_gc contract)
        self.pool = pool

    # -- framing ------------------------------------------------------------
    @staticmethod
    def as_words(row: np.ndarray):
        """Reinterpret a lane-aligned uint8 row as uint32 words for the
        collective, or None when the buffer's base address defeats the
        4-byte view (an exotic allocator) — callers then ship uint8."""
        if row.nbytes % DeviceStagingBridge.WORD:
            return None
        if row.ctypes.data % DeviceStagingBridge.WORD:
            return None
        try:
            return row.view(np.uint32)
        except ValueError:
            return None

    # -- pooled padded rows -------------------------------------------------
    def alloc_row(self, nbytes: int) -> np.ndarray:
        """One pooled uint8 staging row (recycled when the last view of
        it dies — the two-buffer steady state of the windowed plane)."""
        from sparkrdma_tpu.memory.staging import alloc_row_gc

        return alloc_row_gc(
            self.pool, nbytes, "exchange_row_pool_fallbacks_total"
        )

    # -- H2D ---------------------------------------------------------------
    def to_device(self, row: np.ndarray, device, avoided_bytes: int = 0):
        """Put one source row onto its mesh device; returns the device
        array.  ``avoided_bytes`` reports how many bytes of host
        staging-matrix fill the padded layout made unnecessary for this
        row (the per-round [D, D, tile] copies of the host-staged
        path) — the bridge's whole reason to exist, so it is counted
        here at the seam."""
        import jax

        from sparkrdma_tpu.metrics import counter

        if avoided_bytes > 0:
            counter("device_exchange_h2d_bytes_avoided_total").inc(
                avoided_bytes
            )
        return jax.device_put(row, device)
