"""Grouped top-k on the mesh: the rank/LIMIT-per-group SQL shape.

TPC-DS q67-style plans rank rows within each group and keep the top k
(``row_number() over (partition by key order by value desc) <= k``).
Device-native here as one SPMD pass over the existing primitives:

  hash exchange (co-locate each key) → ONE sort keyed (key, value
  descending via bitwise complement) → per-run rank from a run-head
  forward fill (no gathers) → rank < k mask.

The run-head fill rides the same machinery as the keyed reductions
(ops/segment.py; one-pass Pallas on TPU backends), so the step's cost
is the sort — identical shape to wordcount/aggregate.

Reference analog: none in-repo (the reference left SQL to Spark); this
is BASELINE config-5 surface like the joins (SURVEY.md §6).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.ops.segment import _ff_run_carry
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def _rank_in_runs(ks, valid_s):
    """Rank of each slot within its (key, validity) run in an already
    sorted layout: iota minus the run's start index, via the run-END
    fill of the PREVIOUS run's end position (the _prev_end idea with
    positions as the carried column)."""
    n = int(ks.shape[0])
    iota = jnp.arange(n, dtype=jnp.int32)
    bound = (ks[1:] != ks[:-1]) | (valid_s[1:] != valid_s[:-1])
    is_last = jnp.concatenate([bound, jnp.ones(1, bool)])
    # fill of run-end POSITIONS; shifted right one slot = the previous
    # run's end + 1 = my run's start (0 for the first run)
    flag, (fpos,) = _ff_run_carry(is_last, (iota + 1,))
    fpos = jnp.where(flag, fpos, 0)
    run_start = jnp.concatenate([jnp.zeros(1, jnp.int32), fpos[:-1]])
    return iota - run_start


@functools.lru_cache(maxsize=16)
def make_topk_step(mesh: Mesh, n_local: int, capacity: int, k: int):
    """Jitted grouped top-k over global [D*n_local] columns sharded on
    the mesh axis: returns (keys', vals', keep) where keep = 1 on the
    top-k rows of each key (value descending, ties broken
    arbitrarily — unstable sort, Spark shuffle parity)."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(keys, vals, valid):  # local [n_local]
        flat_k, flat_v, flat_m, max_fill = hash_exchange(
            keys, vals, valid, D, capacity
        )
        sentinel = jnp.array(jnp.iinfo(flat_k.dtype).max, flat_k.dtype)
        flat_k = jnp.where(flat_m > 0, flat_k, sentinel)
        # descending by value inside a run: sort on the bitwise
        # complement (order-reversing bijection on signed ints)
        desc = ~flat_v
        inv = jnp.int32(1) - flat_m.astype(jnp.int32)
        ks, inv_s, ds = jax.lax.sort(
            (flat_k, inv, desc), num_keys=3, is_stable=False
        )
        vs = ~ds  # complement is an involution: one fewer sort operand
        ms = jnp.int32(1) - inv_s
        rank = _rank_in_runs(ks, inv_s)
        keep = ((rank < k) & (ms > 0)).astype(jnp.int32)
        n_keep = jnp.sum(keep)
        # (*rows, n_unique, max_fill): the shared keyed-driver contract
        return ks, vs, keep, n_keep[None], max_fill[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec,) * 5
    )
    return jax.jit(mapped)


class GroupedTopK(ExchangeModel):
    """Host-facing grouped top-k: ``{key: [k largest values desc]}``."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 capacity_factor: float = 2.0):
        super().__init__(mesh, capacity_factor)

    def top_k(self, keys, vals, k: int) -> Dict[int, List[int]]:
        if k <= 0:
            raise ValueError(f"k must be positive: {k}")
        step_maker = functools.partial(_make_step_with_k, k=k)
        rows, _nu = self._run_padded_keyed(keys, vals, step_maker)
        if rows is None:
            return {}
        ks_h, vs_h, keep_h = rows
        out: Dict[int, List[Tuple[int, int]]] = {}
        D = self.n_devices
        for d in range(D):
            mask = keep_h[d] > 0
            for kk, vv in zip(ks_h[d][mask], vs_h[d][mask]):
                out.setdefault(int(kk), []).append(int(vv))
        # rows arrive key-grouped and value-descending per device; a
        # key lives on exactly one device post-exchange, so each list
        # is already the final descending top-k
        return out


def _make_step_with_k(mesh, n_local, capacity, k, with_validity=True):
    """Adapter matching the shared keyed-driver's maker signature; the
    validity-free fast path reuses the general body (the rank fill
    needs the validity run delimiter anyway)."""
    if not with_validity:
        step = make_topk_step(mesh, n_local, capacity, k)

        def run(keys, vals):
            valid = jnp.ones(keys.shape[0], jnp.int32)
            return step(keys, vals, valid)

        return run
    return make_topk_step(mesh, n_local, capacity, k)
