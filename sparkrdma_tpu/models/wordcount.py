"""WordCount / reduceByKey on the mesh.

The reference's hash-partitioned shuffle benchmarks (HiBench Sort +
WordCount, README.md:17) as one SPMD program: hash-partition keys,
all_to_all, then a device-side segment reduction
(sparkrdma_tpu.ops.segment) — every key's total ends up on exactly one
device, the contract a reduceByKey shuffle provides.

Validity is an explicit 0/1 column (not a key sentinel), so real keys
equal to the dtype max are counted correctly.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.ops.segment import reduce_by_key_local
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


@functools.lru_cache(maxsize=16)
def make_count_step(mesh: Mesh, n_local: int, capacity: int,
                    with_validity: bool = True):
    """Jitted reduceByKey(+) step over global [D*n_local] key/value
    (/valid) arrays sharded on the mesh axis.  ``with_validity=False``
    is the D == 1 unpadded fast path: every slot is real, so the
    validity operand drops out of the reduction sort entirely."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    if not with_validity:
        if D != 1:
            raise ValueError(
                "with_validity=False requires D == 1 (bucket fills on "
                "a real exchange need the validity column)"
            )

        def body_nv(k, v):  # local [n_local], all slots real
            uniq, sums, cnts, n_unique = reduce_by_key_local(k, v, None)
            return uniq, sums, cnts, n_unique[None], jnp.zeros(1, jnp.int32)

        mapped = jax.shard_map(
            body_nv, mesh=mesh, in_specs=(spec, spec),
            out_specs=(spec,) * 5,
        )
        return jax.jit(mapped)

    def body(k, v, valid):  # local [n_local]
        # (hash_exchange is the identity for D == 1 — no padded sorts)
        flat_k, flat_v, flat_m, max_fill = hash_exchange(
            k, v, valid, D, capacity
        )
        # pre-mask for the reduction contract: invalid slots (bucket pads
        # and input padding) get the grouping key + zero value
        sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
        flat_k = jnp.where(flat_m > 0, flat_k, sentinel)
        flat_v = jnp.where(flat_m > 0, flat_v, jnp.zeros((), v.dtype))
        uniq, sums, cnts, n_unique = reduce_by_key_local(
            flat_k, flat_v, flat_m
        )
        return uniq, sums, cnts, n_unique[None], max_fill[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec,) * 5,
    )
    return jax.jit(mapped)


class WordCounter(ExchangeModel):
    """Host-facing reduceByKey(+): returns {key: total}."""

    def __init__(self, mesh: Optional[Mesh] = None, capacity_factor: float = 2.0):
        super().__init__(mesh, capacity_factor)

    def count_device(self, keys: jax.Array, vals: jax.Array,
                     valid: Optional[jax.Array] = None,
                     capacity: Optional[int] = None):
        n = keys.shape[0]
        if n % self.n_devices:
            raise ValueError(f"length {n} not divisible by D={self.n_devices}")
        n_local = n // self.n_devices
        cap = capacity or self._capacity(n_local)
        keys = jax.device_put(keys, self.sharding)
        vals = jax.device_put(vals, self.sharding)
        if valid is None and self.n_devices == 1:
            # every slot real on one device: validity-free sort
            step = make_count_step(
                self.mesh, n_local, cap, with_validity=False
            )
            return step(keys, vals), cap
        step = make_count_step(self.mesh, n_local, cap)
        if valid is None:
            valid = jnp.ones(n, jnp.int32)
        valid = jax.device_put(valid, self.sharding)
        return step(keys, vals, valid), cap

    def count(self, keys, vals=None) -> Dict[int, int]:
        """Totals wrap in the value dtype on overflow (JVM Int/Long
        parity — Spark's reduceByKey(_+_) over Int wraps identically)."""
        keys = np.asarray(keys)
        vals = np.ones_like(keys) if vals is None else np.asarray(vals)
        rows, nu = self._run_padded_keyed(keys, vals, make_count_step)
        if rows is None:
            return {}
        uniq_h, sums_h, counts_h = rows
        out: Dict[int, int] = {}
        for d in range(self.n_devices):
            # results live at run-end positions: extract by counts > 0
            mask = counts_h[d] > 0
            for k, s in zip(uniq_h[d][mask], sums_h[d][mask]):
                out[int(k)] = int(s)
        return out
