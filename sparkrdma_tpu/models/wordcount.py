"""WordCount / reduceByKey on the mesh.

The reference's hash-partitioned shuffle benchmarks (HiBench Sort +
WordCount, README.md:17) as one SPMD program: hash-partition keys,
all_to_all, then a device-side segment reduction
(sparkrdma_tpu.ops.segment) — every key's total ends up on exactly one
device, the contract a reduceByKey shuffle provides.
"""

from __future__ import annotations

import functools
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.partition import hash_partition_ids, partition_to_buckets
from sparkrdma_tpu.ops.segment import reduce_by_key_local
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh


@functools.lru_cache(maxsize=16)
def make_count_step(mesh: Mesh, n_local: int, capacity: int):
    """Jitted reduceByKey(+) step over global [D*n_local] key/value
    arrays sharded on the mesh axis."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(k, v):  # local [n_local]
        ids = hash_partition_ids(k, D)
        (bk, bv), counts = partition_to_buckets(ids, (k, v), D, capacity)
        rk = jax.lax.all_to_all(bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
        rv = jax.lax.all_to_all(bv, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
        sent = jnp.minimum(counts, capacity)
        rcounts = jax.lax.all_to_all(
            sent.reshape(D, 1), EXCHANGE_AXIS, split_axis=0, concat_axis=0
        ).reshape(D)
        # compact received buckets: sort valid-first, then reduce
        flat_k = rk.reshape(-1)
        flat_v = rv.reshape(-1)
        slot = jnp.arange(capacity)
        valid_mask = (slot[None, :] < rcounts[:, None]).reshape(-1)
        sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
        flat_k = jnp.where(valid_mask, flat_k, sentinel)
        flat_v = jnp.where(valid_mask, flat_v, jnp.zeros((), v.dtype))
        uniq, sums, n_unique = reduce_by_key_local(flat_k, flat_v)
        overflow = jnp.max(counts).astype(jnp.int32)
        return uniq, sums, n_unique[None], overflow[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec),
        out_specs=(spec, spec, spec, spec),
    )
    return jax.jit(mapped)


class WordCounter:
    """Host-facing reduceByKey(+): returns {key: total}."""

    def __init__(self, mesh: Optional[Mesh] = None, capacity_factor: float = 2.0):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = len(list(self.mesh.devices.flat))
        self.capacity_factor = capacity_factor
        self.sharding = NamedSharding(self.mesh, P(EXCHANGE_AXIS))

    def _capacity(self, n_local: int, factor: float) -> int:
        cap = int(math.ceil(n_local / self.n_devices * factor))
        return max(8, (cap + 7) // 8 * 8)

    def count_device(self, keys: jax.Array, vals: jax.Array,
                     capacity: Optional[int] = None):
        n = keys.shape[0]
        if n % self.n_devices:
            raise ValueError(f"length {n} not divisible by D={self.n_devices}")
        n_local = n // self.n_devices
        cap = capacity or self._capacity(n_local, self.capacity_factor)
        step = make_count_step(self.mesh, n_local, cap)
        keys = jax.device_put(keys, self.sharding)
        vals = jax.device_put(vals, self.sharding)
        return step(keys, vals), cap

    def count(self, keys, vals=None) -> Dict[int, int]:
        keys = np.asarray(keys)
        vals = (
            np.ones_like(keys) if vals is None else np.asarray(vals)
        )
        n = keys.shape[0]
        if n == 0:
            return {}
        D = self.n_devices
        sentinel = np.array(np.iinfo(keys.dtype).max, keys.dtype)
        n_pad = (-n) % D
        if n_pad:
            # pad with sentinel keys + zero values: they reduce into the
            # sentinel slot, which we drop below
            keys = np.concatenate([keys, np.full(n_pad, sentinel, keys.dtype)])
            vals = np.concatenate([vals, np.zeros(n_pad, vals.dtype)])
        factor = self.capacity_factor
        for _attempt in range(6):
            (uniq, sums, n_unique, max_fill), cap = self.count_device(
                jnp.asarray(keys), jnp.asarray(vals),
                capacity=self._capacity(keys.shape[0] // D, factor),
            )
            if int(jnp.max(max_fill)) <= cap:
                break
            factor *= 2
        else:
            raise RuntimeError("bucket overflow persisted after 6 retries")
        uniq_h = np.asarray(uniq).reshape(D, -1)
        sums_h = np.asarray(sums).reshape(D, -1)
        nu = np.asarray(n_unique).reshape(-1)
        out: Dict[int, int] = {}
        for d in range(D):
            for k, s in zip(uniq_h[d, : nu[d]], sums_h[d, : nu[d]]):
                if k != sentinel:
                    out[int(k)] = int(s)
        return out
