"""Device-native equi-joins on the mesh: the SQL-exchange workloads.

The reference's benchmark list ends with Spark SQL TPC-DS q64/q72 —
"broadcast + exchange shuffle" joins (BASELINE.md configs).  These are
the corresponding device-native models, for the star-schema shape those
queries have: a large FACT table joined to a DIMENSION table whose join
keys are unique.

- :class:`HashJoiner` — the exchange-shuffle join: both sides are
  hash-partitioned by key and moved with one ``all_to_all`` each, then
  every device probes its co-partitioned pair locally (sort the
  dimension side, ``searchsorted`` probe — no scatters).
- :class:`BroadcastJoiner` — the broadcast join: the dimension side is
  small, so it is replicated to every device (``in_specs=P(None)``, the
  all-gather XLA inserts for a replicated operand) and only the fact
  side is sharded; no exchange at all.

Output is the matched triple per fact row plus a found mask; unmatched
fact rows are dropped host-side (inner join).  Unique-key dimension
sides make the output size statically equal to the fact side — the
property that keeps the SPMD program shape-static (SURVEY.md §7
"variable-length blocks" hard part does not arise).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def _probe(lk, l_valid, rk, rv, r_valid):
    """Local probe: for each left key, find its (unique) right match.
    Returns (rv_matched, found) aligned with lk.

    Validity of the HIT slot is checked explicitly: invalid right slots
    (bucket fill / padding) are forced onto the sentinel key and sorted
    AFTER valid slots of the same key, so a real right key equal to the
    dtype max still wins the side="left" probe, and a fact key equal to
    the dtype max cannot match a padding slot."""
    n = rk.shape[0]
    if n == 0:
        # empty dimension side: no fact row can match
        return jnp.zeros(lk.shape[0], rv.dtype), jnp.zeros(lk.shape[0], jnp.int32)
    sentinel = jnp.array(jnp.iinfo(rk.dtype).max, rk.dtype)
    rk_m = jnp.where(r_valid > 0, rk, sentinel)
    r_inv = jnp.int32(1) - (r_valid > 0).astype(jnp.int32)
    srk, sinv, srv = jax.lax.sort(
        (rk_m, r_inv, rv), num_keys=2, is_stable=False
    )
    idx = jnp.clip(
        jnp.searchsorted(srk, lk, side="left").astype(jnp.int32), 0, n - 1
    )
    hit_valid = sinv[idx] == 0
    found = ((srk[idx] == lk) & hit_valid & (l_valid > 0)).astype(jnp.int32)
    return srv[idx], found


@functools.lru_cache(maxsize=16)
def make_hash_join_step(mesh: Mesh, n_left: int, n_right: int,
                        cap_l: int, cap_r: int):
    """Jitted exchange join step over global [D*n_left] fact and
    [D*n_right] dimension columns sharded on the mesh axis."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # local shards
        elk, elv, elm, fill_l = hash_exchange(lk, lv, l_valid, D, cap_l)
        erk, erv, erm, fill_r = hash_exchange(rk, rv, r_valid, D, cap_r)
        rv_m, found = _probe(elk, elm, erk, erv, erm)
        return elk, elv, rv_m, found, fill_l[None], fill_r[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 6, out_specs=(spec,) * 6
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=16)
def make_broadcast_join_step(mesh: Mesh, n_left: int, n_right_total: int):
    """Jitted broadcast join: fact sharded, dimension replicated."""
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # rk/rv/r_valid: FULL table
        rv_m, found = _probe(lk, l_valid, rk, rv, r_valid)
        return lk, lv, rv_m, found

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(None), P(None), P(None)),
        out_specs=(spec,) * 4,
    )
    return jax.jit(mapped)


class HashJoiner(ExchangeModel):
    """Exchange-shuffle inner join of (fact_keys, fact_vals) with a
    unique-keyed (dim_keys, dim_vals)."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 capacity_factor: float = 1.6):
        super().__init__(mesh, capacity_factor)

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (keys, fact_vals, dim_vals) for every matching fact
        row (input order not preserved)."""
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D)
        rk, rv, r_valid, nr = _pad_to(rk, rv, D)

        # place inputs once: only the capacities change between retries
        placed = tuple(
            jax.device_put(x, self.sharding)
            for x in (lk, lv, l_valid, rk, rv, r_valid)
        )

        def attempt(factor: float):
            cap_l = self._capacity(nl // D, factor)
            cap_r = self._capacity(nr // D, factor)
            step = make_hash_join_step(self.mesh, nl // D, nr // D,
                                       cap_l, cap_r)
            elk, elv, rv_m, found, fill_l, fill_r = step(*placed)
            overflowed = (
                int(np.max(np.asarray(fill_l))) > cap_l
                or int(np.max(np.asarray(fill_r))) > cap_r
            )
            return (elk, elv, rv_m, found), overflowed

        elk, elv, rv_m, found = self._retry_with_factor(attempt)
        mask = np.asarray(found) > 0
        return (
            np.asarray(elk)[mask],
            np.asarray(elv)[mask],
            np.asarray(rv_m)[mask],
        )


class BroadcastJoiner(ExchangeModel):
    """Broadcast inner join: dimension side replicated to every device."""

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D)
        r_valid = jnp.ones(rk.shape[0], jnp.int32)
        step = make_broadcast_join_step(self.mesh, nl // D, rk.shape[0])
        rep = NamedSharding(self.mesh, P(None))
        elk, elv, rv_m, found = step(
            jax.device_put(lk, self.sharding),
            jax.device_put(lv, self.sharding),
            jax.device_put(l_valid, self.sharding),
            jax.device_put(jnp.asarray(rk), rep),
            jax.device_put(jnp.asarray(rv), rep),
            jax.device_put(r_valid, rep),
        )
        mask = np.asarray(found) > 0
        return (
            np.asarray(elk)[mask], np.asarray(elv)[mask],
            np.asarray(rv_m)[mask],
        )


def _as_columns(keys, vals):
    k = jnp.asarray(np.asarray(keys))
    v = jnp.asarray(np.asarray(vals))
    if k.shape != v.shape or k.ndim != 1:
        raise ValueError("keys/vals must be equal-length 1-D arrays")
    return k, v


def _pad_to(k, v, d):
    n = k.shape[0]
    n_pad = (-n) % d
    valid = np.ones(n + n_pad, np.int32)
    if n_pad:
        valid[n:] = 0
        k = jnp.concatenate([k, jnp.zeros(n_pad, k.dtype)])
        v = jnp.concatenate([v, jnp.zeros(n_pad, v.dtype)])
    return k, v, jnp.asarray(valid), n + n_pad
