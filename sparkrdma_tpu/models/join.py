"""Device-native equi-joins on the mesh: the SQL-exchange workloads.

The reference's benchmark list ends with Spark SQL TPC-DS q64/q72 —
"broadcast + exchange shuffle" joins (BASELINE.md configs).  These are
the corresponding device-native models, for the star-schema shape those
queries have: a large FACT table joined to a DIMENSION table whose join
keys are unique.

- :class:`HashJoiner` — the exchange-shuffle join: both sides are
  hash-partitioned by key and moved with one ``all_to_all`` each, then
  every device probes its co-partitioned pair locally.
- :class:`BroadcastJoiner` — the broadcast join: the dimension side is
  small, so it is replicated to every device (``in_specs=P(None)``, the
  all-gather XLA inserts for a replicated operand) and only the fact
  side is sharded; no exchange at all.

The local probe is a SORT-MERGE: both sides concatenate into one
multi-operand sort (dimension rows ordered before fact rows of the same
key); match detection is pure ``cummax``/``cumsum`` prefix scans
(native TPU primitives, ~15 ms per 8M elements measured), and the value
fill is ONE gather from the compact sorted dimension table.  The
obvious alternatives measured far worse on real hardware:
``jnp.searchsorted`` lowers to a gather per binary-search step and a
general ``associative_scan`` fill compiles pathologically at
multi-million element sizes.

Output rows are the concatenated probe layout with a found mask (1 only
on matched fact rows); unmatched/dimension rows are dropped host-side
(inner join).  Static shapes throughout (SURVEY.md §7 "variable-length
blocks" hard part does not arise).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.models._base import (
    ExchangeModel,
    check_no_silent_truncation,
)
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def _probe(lk, lv, l_valid, rk, rv, r_valid):
    """Sort-merge probe: join fact rows against the (unique-keyed)
    dimension rows.  Returns ``(keys, fact_vals, dim_vals, found)``, all
    of length ``n_left + n_right`` — ``found`` is 1 exactly on matched
    FACT rows (dimension and invalid rows carry 0); callers filter.

    Mechanics: one multi-operand sort of the concatenated sides, keyed
    (key, side) with dimension rows (side 0) before fact rows (side 1)
    of the same key.  A fact row matches iff the latest valid dimension
    row at or before it falls inside its own key-run — detected with
    two ``cummax`` scans (latest-dim position vs run-head position),
    gather-free.  Its dimension value is then the ``cumsum``-ranked
    entry of the separately key-sorted dimension table: ONE gather from
    the compact table (unique keys make both key-orders agree row for
    row).  Invalid slots (padding / bucket fill) are masked onto the
    sentinel key and excluded from the fill, so a real key equal to the
    dtype max still matches correctly and padding never matches."""
    nl, nr = lk.shape[0], rk.shape[0]
    sentinel = jnp.array(jnp.iinfo(lk.dtype).max, lk.dtype)
    if nr == 0:
        # empty dimension side: no fact row can match
        return (
            jnp.where(l_valid > 0, lk, sentinel), lv,
            jnp.zeros(nl, rv.dtype), jnp.zeros(nl, jnp.int32),
        )
    rk_m = jnp.where(r_valid > 0, rk, sentinel)
    r_inv = jnp.int32(1) - (r_valid > 0).astype(jnp.int32)
    # compact dimension table in key order, valid rows first
    _, _, srv = jax.lax.sort((rk_m, r_inv, rv), num_keys=2, is_stable=False)
    keys = jnp.concatenate([jnp.where(l_valid > 0, lk, sentinel), rk_m])
    side = jnp.concatenate([
        jnp.ones(nl, jnp.int32), jnp.zeros(nr, jnp.int32)
    ])
    # only FACT rows' own values are read from the sorted payload (dim
    # values come from the compact table below), so the dim slots carry
    # zeros OF lv's DTYPE — concatenating lv with rv would silently
    # promote mixed-dtype columns and corrupt fact values
    payload = jnp.concatenate([lv, jnp.zeros(nr, lv.dtype)])
    valid = jnp.concatenate([
        (l_valid > 0).astype(jnp.int32), (r_valid > 0).astype(jnp.int32)
    ])
    sk, sside, spay, svalid = jax.lax.sort(
        (keys, side, payload, valid), num_keys=2, is_stable=False
    )
    m = nl + nr
    iota = jnp.arange(m, dtype=jnp.int32)
    has = ((sside == 0) & (svalid > 0)).astype(jnp.int32)
    # latest valid-dim position vs my run head: inside my run <=> match
    # (the valid dim row of a key-run is always the run's FIRST row)
    latest_dim = jax.lax.cummax(jnp.where(has > 0, iota, jnp.int32(-1)))
    is_head = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    run_head = jax.lax.cummax(jnp.where(is_head, iota, jnp.int32(-1)))
    found = (
        (sside == 1) & (svalid > 0)
        & (latest_dim >= 0) & (latest_dim >= run_head)
    ).astype(jnp.int32)
    # value fill: has-rank in the combined order == row index in the
    # key-sorted dim table (keys unique among valid dim rows)
    rank = jnp.cumsum(has) - 1
    fv = srv[jnp.clip(rank, 0, nr - 1)]
    fv = jnp.where(found > 0, fv, jnp.zeros((), rv.dtype))
    return sk, spay, fv, found


@functools.lru_cache(maxsize=16)
def make_hash_join_step(mesh: Mesh, n_left: int, n_right: int,
                        cap_l: int, cap_r: int):
    """Jitted exchange join step over global [D*n_left] fact and
    [D*n_right] dimension columns sharded on the mesh axis."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # local shards
        # (hash_exchange is the identity for D == 1 — no padded sorts)
        elk, elv, elm, fill_l = hash_exchange(lk, lv, l_valid, D, cap_l)
        erk, erv, erm, fill_r = hash_exchange(rk, rv, r_valid, D, cap_r)
        jk, jlv, jrv, found = _probe(elk, elv, elm, erk, erv, erm)
        return jk, jlv, jrv, found, fill_l[None], fill_r[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 6, out_specs=(spec,) * 6
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=16)
def make_broadcast_join_step(mesh: Mesh, n_left: int, n_right_total: int):
    """Jitted broadcast join: fact sharded, dimension replicated."""
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # rk/rv/r_valid: FULL table
        return _probe(lk, lv, l_valid, rk, rv, r_valid)

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(None), P(None), P(None)),
        out_specs=(spec,) * 4,
    )
    return jax.jit(mapped)


class HashJoiner(ExchangeModel):
    """Exchange-shuffle inner join of (fact_keys, fact_vals) with a
    unique-keyed (dim_keys, dim_vals)."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 capacity_factor: float = 1.6):
        super().__init__(mesh, capacity_factor)

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (keys, fact_vals, dim_vals) for every matching fact
        row (input order not preserved)."""
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D)
        rk, rv, r_valid, nr = _pad_to(rk, rv, D)

        # place inputs once: only the capacities change between retries
        placed = tuple(
            jax.device_put(x, self.sharding)
            for x in (lk, lv, l_valid, rk, rv, r_valid)
        )

        def attempt(factor: float):
            cap_l = self._capacity(nl // D, factor)
            cap_r = self._capacity(nr // D, factor)
            step = make_hash_join_step(self.mesh, nl // D, nr // D,
                                       cap_l, cap_r)
            elk, elv, rv_m, found, fill_l, fill_r = step(*placed)
            overflowed = (
                int(np.max(np.asarray(fill_l))) > cap_l
                or int(np.max(np.asarray(fill_r))) > cap_r
            )
            return (elk, elv, rv_m, found), overflowed

        elk, elv, rv_m, found = self._retry_with_factor(attempt)
        mask = np.asarray(found) > 0
        return (
            np.asarray(elk)[mask],
            np.asarray(elv)[mask],
            np.asarray(rv_m)[mask],
        )


class BroadcastJoiner(ExchangeModel):
    """Broadcast inner join: dimension side replicated to every device."""

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D)
        r_valid = jnp.ones(rk.shape[0], jnp.int32)
        step = make_broadcast_join_step(self.mesh, nl // D, rk.shape[0])
        rep = NamedSharding(self.mesh, P(None))
        elk, elv, rv_m, found = step(
            jax.device_put(lk, self.sharding),
            jax.device_put(lv, self.sharding),
            jax.device_put(l_valid, self.sharding),
            jax.device_put(jnp.asarray(rk), rep),
            jax.device_put(jnp.asarray(rv), rep),
            jax.device_put(r_valid, rep),
        )
        mask = np.asarray(found) > 0
        return (
            np.asarray(elk)[mask], np.asarray(elv)[mask],
            np.asarray(rv_m)[mask],
        )


def _as_columns(keys, vals):
    check_no_silent_truncation(keys=keys, vals=vals)
    k = jnp.asarray(np.asarray(keys))
    v = jnp.asarray(np.asarray(vals))
    if k.shape != v.shape or k.ndim != 1:
        raise ValueError("keys/vals must be equal-length 1-D arrays")
    return k, v


def _pad_to(k, v, d):
    n = k.shape[0]
    n_pad = (-n) % d
    valid = np.ones(n + n_pad, np.int32)
    if n_pad:
        valid[n:] = 0
        k = jnp.concatenate([k, jnp.zeros(n_pad, k.dtype)])
        v = jnp.concatenate([v, jnp.zeros(n_pad, v.dtype)])
    return k, v, jnp.asarray(valid), n + n_pad
