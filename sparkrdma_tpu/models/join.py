"""Device-native equi-joins on the mesh: the SQL-exchange workloads.

The reference's benchmark list ends with Spark SQL TPC-DS q64/q72 —
"broadcast + exchange shuffle" joins (BASELINE.md configs).  These are
the corresponding device-native models, for the star-schema shape those
queries have: a large FACT table joined to a DIMENSION table whose join
keys are unique.

- :class:`HashJoiner` — the exchange-shuffle join: BOTH sides merge
  into one packed (key, role, payload) stream that is hash-partitioned
  and moved with ONE ``all_to_all`` (round 1 ran one exchange per side —
  two bucket sorts and six collectives; the fused stream halves that),
  then every device probes its co-partitioned rows locally.
- :class:`BroadcastJoiner` — the broadcast join: the dimension side is
  small, so it is replicated to every device (``in_specs=P(None)``, the
  all-gather XLA inserts for a replicated operand) and only the fact
  side is sharded; no exchange at all.

The local probe is ONE unstable multi-operand sort keyed ``(key,
role)`` — role 0 = valid dimension, 1 = valid fact, 2 = invalid — so
each key run opens with its (unique) dimension row, followed by a
log-step forward fill that propagates the latest dimension (key, value)
rightward; a fact row matches iff the filled key equals its own.  Both
sides' values ride ONE unsigned payload column (bitcast; uint32, or
uint64 when any column is 64-bit under ``jax_enable_x64`` — narrower
ints/floats widen losslessly) — a row is either a fact or a dimension,
never both.  Alternatives measured on real hardware: the
round-1 formulation (2-key sort + 2 cummax + cumsum + compact-table
gather) ran 54 ms at 4.2M rows because the value gather alone costs
~43 ms (TPU gathers run ~10 cycles/element); the forward fill does the
same fill in ~7 ms, for 17.6 ms total (3.1x).  ``jnp.searchsorted``
lowers to a gather per binary-search step (worse), and a general
``associative_scan`` fill compiles pathologically at multi-million
element sizes.

Output rows are the probe layout with a found mask (1 only on matched
fact rows); unmatched/dimension rows are dropped host-side (inner
join).  Static shapes throughout (SURVEY.md §7 "variable-length blocks"
hard part does not arise).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.models._base import (
    ExchangeModel,
    check_no_silent_truncation,
)
from sparkrdma_tpu.ops.partition import (
    hash_partition_ids,
    partition_to_buckets_dropping,
)
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS

# role column: dimension rows sort before fact rows of the same key,
# invalid (padding / bucket-fill) rows sort last and never match
_ROLE_DIM = 0
_ROLE_FACT = 1
_ROLE_INVALID = 2


def _transport_width(*cols) -> int:
    """Transport word size for the packed stream: 8 bytes as soon as
    any key/value column is 64-bit (only reachable under
    ``jax_enable_x64`` — check_no_silent_truncation rejects int64
    without it), else 4."""
    return 8 if any(np.dtype(c.dtype).itemsize == 8 for c in cols) else 4


def _key_u(k: jax.Array, width: int) -> jax.Array:
    """Injective unsigned view of an integer key column (grouping is
    all the probe needs, so any bijection works)."""
    return k.astype(jnp.uint64 if width == 8 else jnp.uint32)


def _pay_u(v: jax.Array, width: int) -> jax.Array:
    """Lossless unsigned transport view of a value column: same-width
    dtypes bitcast, narrower ints/floats widen first."""
    ut = jnp.uint64 if width == 8 else jnp.uint32
    if v.dtype.itemsize == width:
        return jax.lax.bitcast_convert_type(v, ut)
    if jnp.issubdtype(v.dtype, jnp.floating):
        ft = jnp.float64 if width == 8 else jnp.float32
        return jax.lax.bitcast_convert_type(v.astype(ft), ut)
    it = jnp.int64 if width == 8 else jnp.int32
    return jax.lax.bitcast_convert_type(v.astype(it), ut)


def _pay_from_u(u: jax.Array, dtype, width: int) -> jax.Array:
    """Inverse of :func:`_pay_u`."""
    if np.dtype(dtype).itemsize == width:
        return jax.lax.bitcast_convert_type(u, dtype)
    if jnp.issubdtype(np.dtype(dtype), np.floating):
        ft = jnp.float64 if width == 8 else jnp.float32
        return jax.lax.bitcast_convert_type(u, ft).astype(dtype)
    it = jnp.int64 if width == 8 else jnp.int32
    return jax.lax.bitcast_convert_type(u, it).astype(dtype)


def _pack_sides(lk, lv, l_valid, rk, rv, r_valid):
    """Merge fact and dimension columns into one (key, role, payload)
    unsigned stream (facts first)."""
    w = _transport_width(lk, rk, lv, rv)
    ku = jnp.concatenate([_key_u(lk, w), _key_u(rk, w)])
    role = jnp.concatenate([
        jnp.where(l_valid > 0, jnp.uint32(_ROLE_FACT),
                  jnp.uint32(_ROLE_INVALID)),
        jnp.where(r_valid > 0, jnp.uint32(_ROLE_DIM),
                  jnp.uint32(_ROLE_INVALID)),
    ])
    pay = jnp.concatenate([_pay_u(lv, w), _pay_u(rv, w)])
    return ku, role, pay


def _probe_fill(sk, srole, spay):
    """Log-step forward fill over an already (key, role)-sorted packed
    stream: propagate each (unique-keyed) dimension row's (key, value)
    rightward; a fact row matches iff the filled dimension key equals
    its own (runs with no dimension row inherit a previous run's fill,
    which the key test rejects; invalid rows never fill and never
    match).  Returns ``(dim_val, found)`` with found a bool mask true
    exactly on matched fact rows.  Shared with the fused
    join+aggregate (models/join_aggregate.py), whose sort key differs.
    Large TPU fills run as ONE Pallas pass (ops/scan_kernels.py)
    instead of the log-step loop.
    """
    from sparkrdma_tpu.ops.scan_kernels import (
        MIN_KERNEL_ELEMS,
        kernel_eligible,
        scan_flagged,
        use_scan_kernels,
    )

    m = int(sk.shape[0])
    if (m >= MIN_KERNEL_ELEMS and kernel_eligible(sk, spay)
            and use_scan_kernels()):
        flag, (fkey, fval) = scan_flagged(
            "fill", srole == _ROLE_DIM, (sk, spay)
        )
        found = (srole == _ROLE_FACT) & flag & (fkey == sk)
        return fval, found
    flag = srole == _ROLE_DIM
    fkey = sk
    fval = spay
    s = 1
    while s < m:
        pf = jnp.concatenate([flag[:s], flag[:-s]])
        pk = jnp.concatenate([fkey[:s], fkey[:-s]])
        pv = jnp.concatenate([fval[:s], fval[:-s]])
        need = ~flag
        fkey = jnp.where(need, pk, fkey)
        fval = jnp.where(need, pv, fval)
        flag = flag | pf
        s <<= 1
    found = (srole == _ROLE_FACT) & flag & (fkey == sk)
    return fval, found


def _probe_packed(ku, role, pay):
    """Sort-merge probe over a packed (key, role, payload) stream.

    One unstable sort keyed (key, role) groups each key's run with its
    dimension row first, then the :func:`_probe_fill` forward fill
    matches fact rows.  Returns ``(keys_u, fact_pay, dim_pay, found)``
    with found = 1 exactly on matched fact rows.
    """
    sk, srole, spay = jax.lax.sort(
        (ku, role, pay), num_keys=2, is_stable=False
    )
    fval, found_b = _probe_fill(sk, srole, spay)
    found = found_b.astype(jnp.int32)
    fval = jnp.where(found > 0, fval, jnp.zeros((), fval.dtype))
    is_fact = (srole == _ROLE_FACT).astype(jnp.int32)
    return sk, spay, fval, found, is_fact


@functools.lru_cache(maxsize=16)
def make_hash_join_step(mesh: Mesh, n_left: int, n_right: int,
                        capacity: int):
    """Jitted fused-exchange join step over global [D*n_left] fact and
    [D*n_right] dimension columns sharded on the mesh axis: both sides
    ride ONE hash exchange as a packed stream, then probe locally."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # local shards
        ku, role, pay = _pack_sides(lk, lv, l_valid, rk, rv, r_valid)
        if D == 1:
            eku, erole, epay = ku, role, pay
            fill = jnp.int32(0)
        else:
            # padding rides the trash bucket (consumes no real
            # capacity, excluded from overflow accounting)
            ids = hash_partition_ids(ku, D)
            (bk, br, bp), counts = partition_to_buckets_dropping(
                ids, role != _ROLE_INVALID, (ku, role, pay), D, capacity,
                fill_values=(
                    jnp.zeros((), ku.dtype), jnp.uint32(_ROLE_INVALID),
                    jnp.zeros((), pay.dtype),
                ),
            )
            eku = jax.lax.all_to_all(
                bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0
            ).reshape(-1)
            erole = jax.lax.all_to_all(
                br, EXCHANGE_AXIS, split_axis=0, concat_axis=0
            ).reshape(-1)
            epay = jax.lax.all_to_all(
                bp, EXCHANGE_AXIS, split_axis=0, concat_axis=0
            ).reshape(-1)
            fill = jnp.max(counts).astype(jnp.int32)
        sk, spay, fval, found, is_fact = _probe_packed(eku, erole, epay)
        return sk, spay, fval, found, is_fact, fill[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 6, out_specs=(spec,) * 6
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=16)
def make_broadcast_join_step(mesh: Mesh, n_left: int, n_right_total: int):
    """Jitted broadcast join: fact sharded, dimension replicated."""
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):  # rk/rv/r_valid: FULL table
        ku, role, pay = _pack_sides(lk, lv, l_valid, rk, rv, r_valid)
        return _probe_packed(ku, role, pay)

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(None), P(None), P(None)),
        out_specs=(spec,) * 5,
    )
    return jax.jit(mapped)


#: join variants (Spark/SQL parity): inner keeps matched fact rows with
#: the dim value; left_outer keeps EVERY fact row plus a matched mask;
#: semi keeps matched fact rows without the dim value (left-semi,
#: TPC-DS q16); anti keeps the UNmatched fact rows (left-anti, q94).
JOIN_HOWS = ("inner", "left_outer", "semi", "anti")


class HashJoiner(ExchangeModel):
    """Exchange-shuffle join of (fact_keys, fact_vals) with a
    unique-keyed (dim_keys, dim_vals); ``how`` picks the variant
    (:data:`JOIN_HOWS`)."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 capacity_factor: float = 1.6):
        super().__init__(mesh, capacity_factor)

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals,
             how: str = "inner"):
        """inner → (keys, fact_vals, dim_vals) for matching fact rows;
        left_outer → (keys, fact_vals, dim_vals, matched) for ALL fact
        rows (dim_vals is 0 where unmatched); semi/anti → (keys,
        fact_vals) for matched/unmatched fact rows.  Input order is not
        preserved."""
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D, self.quantize_shapes)
        rk, rv, r_valid, nr = _pad_to(rk, rv, D, self.quantize_shapes)

        # place inputs once: only the capacities change between retries
        placed = tuple(
            jax.device_put(x, self.sharding)
            for x in (lk, lv, l_valid, rk, rv, r_valid)
        )

        def attempt(factor: float):
            # one capacity for the fused fact+dim stream
            cap = self._capacity((nl + nr) // D, factor)
            step = make_hash_join_step(self.mesh, nl // D, nr // D, cap)
            sk, spay, fval, found, is_fact, fill = step(*placed)
            overflowed = int(np.max(np.asarray(fill))) > cap
            return (sk, spay, fval, found, is_fact), overflowed

        sk, spay, fval, found, is_fact = self._retry_with_factor(attempt)
        return _mask_output(sk, spay, fval, found, is_fact,
                            lk.dtype, lv.dtype, rv.dtype, how)


class BroadcastJoiner(ExchangeModel):
    """Broadcast join: dimension side replicated to every device;
    ``how`` picks the variant (:data:`JOIN_HOWS`)."""

    def join(self, fact_keys, fact_vals, dim_keys, dim_vals,
             how: str = "inner"):
        """Same output contract as :meth:`HashJoiner.join`."""
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D, self.quantize_shapes)
        r_valid = jnp.ones(rk.shape[0], jnp.int32)
        step = make_broadcast_join_step(self.mesh, nl // D, rk.shape[0])
        rep = NamedSharding(self.mesh, P(None))
        sk, spay, fval, found, is_fact = step(
            jax.device_put(lk, self.sharding),
            jax.device_put(lv, self.sharding),
            jax.device_put(l_valid, self.sharding),
            jax.device_put(jnp.asarray(rk), rep),
            jax.device_put(jnp.asarray(rv), rep),
            jax.device_put(r_valid, rep),
        )
        return _mask_output(sk, spay, fval, found, is_fact,
                            lk.dtype, lv.dtype, rv.dtype, how)


def _mask_output(sk, spay, fval, found, is_fact, key_dtype, lv_dtype,
                 rv_dtype, how="inner"):
    """Host-side join filter per variant, restoring the original dtypes
    from the unsigned transport views."""
    if how not in JOIN_HOWS:
        raise ValueError(f"how must be one of {JOIN_HOWS}, got {how!r}")
    width = np.dtype(sk.dtype).itemsize
    found_h = np.asarray(found) > 0
    if how == "inner":
        mask = found_h
    elif how in ("left_outer",):
        mask = np.asarray(is_fact) > 0
    elif how == "semi":
        mask = found_h
    else:  # anti: real fact rows with no dimension match
        mask = (np.asarray(is_fact) > 0) & ~found_h
    keys = np.asarray(sk).astype(np.dtype(key_dtype))[mask]
    outl = np.asarray(_pay_from_u(spay, lv_dtype, width))[mask]
    if how in ("semi", "anti"):
        return keys, outl
    outv = np.asarray(_pay_from_u(fval, rv_dtype, width))[mask]
    if how == "left_outer":
        return keys, outl, outv, found_h[mask]
    return keys, outl, outv


def _as_columns(keys, vals):
    check_no_silent_truncation(keys=keys, vals=vals)
    k = jnp.asarray(np.asarray(keys))
    v = jnp.asarray(np.asarray(vals))
    if k.shape != v.shape or k.ndim != 1:
        raise ValueError("keys/vals must be equal-length 1-D arrays")
    return k, v


def _pad_to(k, v, d, quantize=True):
    """Pad columns to a multiple of ``d`` on the compile-shape ladder
    (models/_base.quantize_padded_length) with a validity column."""
    from sparkrdma_tpu.models._base import quantize_padded_length

    n = k.shape[0]
    total = (
        quantize_padded_length(n, d) if quantize else n + ((-n) % d)
    )
    n_pad = total - n
    valid = np.ones(n + n_pad, np.int32)
    if n_pad:
        valid[n:] = 0
        k = jnp.concatenate([k, jnp.zeros(n_pad, k.dtype)])
        v = jnp.concatenate([v, jnp.zeros(n_pad, v.dtype)])
    return k, v, jnp.asarray(valid), n + n_pad
