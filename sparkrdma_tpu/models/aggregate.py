"""Device-native keyed aggregation: the combineByKey workload.

Generalizes WordCount's reduceByKey(+) to the full aggregation family —
sum, count, min, max, mean per key — as one SPMD program: hash exchange
(ops/exchange.py) followed by the one-pass segment aggregation
(ops/segment.py aggregate_by_key_local).  The device analog of Spark's
Aggregator running during the read path
(RdmaShuffleReader.scala:82-97); the record-plane equivalent lives in
shuffle/reader.py (arbitrary Python combiners), this one trades
generality for MXU/VPU-rate throughput on numeric columns.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.ops.segment import aggregate_by_key_local
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


class KeyStats(NamedTuple):
    """Per-key aggregates (mean derived host-side: sum / count)."""

    sum: int
    count: int
    min: int
    max: int

    @property
    def mean(self) -> float:
        return self.sum / self.count


@functools.lru_cache(maxsize=16)
def make_aggregate_step(mesh: Mesh, n_local: int, capacity: int,
                        with_validity: bool = True):
    """Jitted aggregateByKey step over global [D*n_local] columns
    sharded on the mesh axis.  ``with_validity=False`` is the D == 1
    unpadded fast path (segment.py: drops the validity sort operand)."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    if not with_validity:
        if D != 1:
            raise ValueError(
                "with_validity=False requires D == 1 (bucket fills on "
                "a real exchange need the validity column)"
            )

        def body_nv(k, v):  # local [n_local], all slots real
            uniq, sums, counts, mins, maxs, n_unique = (
                aggregate_by_key_local(k, v, None)
            )
            return (uniq, sums, counts, mins, maxs, n_unique[None],
                    jnp.zeros(1, jnp.int32))

        mapped = jax.shard_map(
            body_nv, mesh=mesh, in_specs=(spec, spec),
            out_specs=(spec,) * 7,
        )
        return jax.jit(mapped)

    def body(k, v, valid):  # local [n_local]
        # (hash_exchange is the identity for D == 1 — no padded sorts)
        flat_k, flat_v, flat_m, max_fill = hash_exchange(
            k, v, valid, D, capacity
        )
        sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
        flat_k = jnp.where(flat_m > 0, flat_k, sentinel)
        flat_v = jnp.where(flat_m > 0, flat_v, jnp.zeros((), v.dtype))
        uniq, sums, counts, mins, maxs, n_unique = aggregate_by_key_local(
            flat_k, flat_v, flat_m
        )
        return uniq, sums, counts, mins, maxs, n_unique[None], max_fill[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec,) * 7
    )
    return jax.jit(mapped)


class KeyedAggregator(ExchangeModel):
    """Host-facing aggregateByKey: returns {key: KeyStats}."""

    def __init__(self, mesh: Optional[Mesh] = None, capacity_factor: float = 2.0):
        super().__init__(mesh, capacity_factor)

    def aggregate(self, keys, vals) -> Dict[int, KeyStats]:
        """Sums accumulate in the value dtype and wrap on overflow (JVM
        Int/Long parity).  For wide sums pass int64 values with
        ``jax_enable_x64`` on; without it int64 inputs would silently
        truncate, so that combination is rejected."""
        # int64-without-x64 inputs are rejected inside _run_padded_keyed
        # (shared with every keyed model)
        rows, nu = self._run_padded_keyed(keys, vals, make_aggregate_step)
        if rows is None:
            return {}
        uniq_h, sums_h, counts_h, mins_h, maxs_h = rows
        out: Dict[int, KeyStats] = {}
        for d in range(self.n_devices):
            # results live at run-end positions: extract by counts > 0
            (idx,) = (counts_h[d] > 0).nonzero()
            for i in idx:
                out[int(uniq_h[d, i])] = KeyStats(
                    int(sums_h[d, i]), int(counts_h[d, i]),
                    int(mins_h[d, i]), int(maxs_h[d, i]),
                )
        return out
