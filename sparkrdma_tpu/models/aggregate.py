"""Device-native keyed aggregation: the combineByKey workload.

Generalizes WordCount's reduceByKey(+) to the full aggregation family —
sum, count, min, max, mean per key — as one SPMD program: hash exchange
(ops/exchange.py) followed by the one-pass segment aggregation
(ops/segment.py aggregate_by_key_local).  The device analog of Spark's
Aggregator running during the read path
(RdmaShuffleReader.scala:82-97); the record-plane equivalent lives in
shuffle/reader.py (arbitrary Python combiners), this one trades
generality for MXU/VPU-rate throughput on numeric columns.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.exchange import hash_exchange
from sparkrdma_tpu.ops.segment import aggregate_by_key_local
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


class KeyStats(NamedTuple):
    """Per-key aggregates (mean derived host-side: sum / count)."""

    sum: int
    count: int
    min: int
    max: int

    @property
    def mean(self) -> float:
        return self.sum / self.count


@functools.lru_cache(maxsize=16)
def make_aggregate_step(mesh: Mesh, n_local: int, capacity: int):
    """Jitted aggregateByKey step over global [D*n_local] columns
    sharded on the mesh axis."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(k, v, valid):  # local [n_local]
        flat_k, flat_v, flat_m, max_fill = hash_exchange(
            k, v, valid, D, capacity
        )
        sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
        flat_k = jnp.where(flat_m > 0, flat_k, sentinel)
        flat_v = jnp.where(flat_m > 0, flat_v, jnp.zeros((), v.dtype))
        uniq, sums, counts, mins, maxs, n_unique = aggregate_by_key_local(
            flat_k, flat_v, flat_m
        )
        return uniq, sums, counts, mins, maxs, n_unique[None], max_fill[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec,) * 7
    )
    return jax.jit(mapped)


class KeyedAggregator(ExchangeModel):
    """Host-facing aggregateByKey: returns {key: KeyStats}."""

    def __init__(self, mesh: Optional[Mesh] = None, capacity_factor: float = 2.0):
        super().__init__(mesh, capacity_factor)

    def aggregate(self, keys, vals) -> Dict[int, KeyStats]:
        keys = np.asarray(keys)
        vals = np.asarray(vals)
        if keys.shape != vals.shape or keys.ndim != 1:
            raise ValueError("keys/vals must be equal-length 1-D arrays")
        n = keys.shape[0]
        if n == 0:
            return {}
        D = self.n_devices
        n_pad = (-n) % D
        valid = np.ones(n + n_pad, np.int32)
        if n_pad:
            keys = np.concatenate([keys, np.zeros(n_pad, keys.dtype)])
            vals = np.concatenate([vals, np.zeros(n_pad, vals.dtype)])
            valid[n:] = 0
        jk, jv, jval = jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(valid)

        def run(cap):
            step = make_aggregate_step(self.mesh, (n + n_pad) // D, cap)
            uniq, sums, counts, mins, maxs, n_unique, max_fill = step(
                *(jax.device_put(x, self.sharding) for x in (jk, jv, jval))
            )
            return (uniq, sums, counts, mins, maxs, n_unique), max_fill

        uniq, sums, counts, mins, maxs, n_unique = (
            self._run_with_overflow_retry(n + n_pad, run)
        )
        uniq_h = np.asarray(uniq).reshape(D, -1)
        stats = [np.asarray(a).reshape(D, -1) for a in (sums, counts, mins, maxs)]
        nu = np.asarray(n_unique).reshape(-1)
        out: Dict[int, KeyStats] = {}
        for d in range(D):
            for i in range(nu[d]):
                out[int(uniq_h[d, i])] = KeyStats(
                    int(stats[0][d, i]), int(stats[1][d, i]),
                    int(stats[2][d, i]), int(stats[3][d, i]),
                )
        return out
