"""Device-native dataflow "models": the benchmark workloads of the
reference (HiBench TeraSort / Sort / WordCount, README.md:7-19) rebuilt
as single XLA programs over the exchange mesh — partition, all_to_all,
and reduce/sort fused into one jitted SPMD step instead of a CPU
serializer + NIC pull loop."""

from sparkrdma_tpu.models.aggregate import KeyedAggregator, KeyStats
from sparkrdma_tpu.models.external_sort import ExternalTeraSorter
from sparkrdma_tpu.models.join import JOIN_HOWS, BroadcastJoiner, HashJoiner
from sparkrdma_tpu.models.join_aggregate import BroadcastJoinAggregator
from sparkrdma_tpu.models.ring_attention import ring_attention, ulysses_attention
from sparkrdma_tpu.models.terasort import TeraSorter, make_sort_step
from sparkrdma_tpu.models.topk import GroupedTopK
from sparkrdma_tpu.models.wordcount import WordCounter, make_count_step

__all__ = [
    "TeraSorter", "make_sort_step", "WordCounter", "make_count_step",
    "HashJoiner", "BroadcastJoiner", "JOIN_HOWS",
    "BroadcastJoinAggregator", "ExternalTeraSorter",
    "ring_attention", "ulysses_attention",
    "KeyedAggregator", "KeyStats", "GroupedTopK",
]
