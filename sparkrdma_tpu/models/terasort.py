"""TeraSort on the mesh: the flagship workload.

The reference's headline benchmark is HiBench TeraSort 175 GB — a
``sortByKey`` whose shuffle moves every record once over the NIC
(README.md:7-19).  Here the whole job is ONE jitted SPMD program per
step:

    sample → splitters → range partition → all_to_all → local sort

Each device samples its keys, the sample is all-gathered to derive
global equal-frequency splitters, records are capacity-bucketed per
destination (sparkrdma_tpu.ops.partition), exchanged with a single
``all_to_all`` riding ICI, and sorted locally — the concatenation of the
devices' outputs (minus sentinel padding) is the global sort.

Skew handling: buckets are capacity-padded (static shapes); true counts
travel with the exchange, and overflow (count > capacity) is detected on
the host, which re-runs with a larger capacity factor — the SPMD analog
of the reference's maxAggBlock fetch cap (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.partition import make_range_splitters
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh


def _local_sort_step(keys, vals, n_devices, capacity, sample_size):
    """Per-device body (runs under shard_map).  keys/vals: [n_local].

    TPU-tuned shape: sort the LOCAL pairs first, so (a) the sample is an
    exact local quantile sketch and (b) each destination's records form
    one contiguous window of the sorted run — bucketing is then pure
    sequential gathers with zero scatters and no second keyed sort.
    """
    n_local = keys.shape[0]
    k, v = jax.lax.sort((keys, vals), num_keys=1, is_stable=True)
    # exact local quantiles (k is sorted): positions i*n/S
    sample = k[(jnp.arange(sample_size) * n_local) // sample_size]
    all_samples = jax.lax.all_gather(sample, EXCHANGE_AXIS)  # [D, S]
    splitters = make_range_splitters(all_samples.reshape(-1), n_devices)
    # destination windows: device p gets keys in [splitters[p-1], splitters[p])
    edges = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.searchsorted(k, splitters, side="right").astype(jnp.int32),
        jnp.full((1,), n_local, jnp.int32),
    ])
    counts = edges[1:] - edges[:-1]                       # true counts [D]
    slot = jnp.arange(capacity, dtype=jnp.int32)
    idx = jnp.clip(edges[:-1][:, None] + slot[None, :], 0, n_local - 1)
    valid = slot[None, :] < jnp.minimum(counts, capacity)[:, None]
    sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
    bk = jnp.where(valid, k[idx], sentinel)               # [D, cap]
    bv = jnp.where(valid, v[idx], jnp.zeros((), v.dtype))
    # exchange: device d keeps row d of every source
    rk = jax.lax.all_to_all(bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rv = jax.lax.all_to_all(bv, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rcounts = jax.lax.all_to_all(
        jnp.minimum(counts, capacity).reshape(n_devices, 1), EXCHANGE_AXIS,
        split_axis=0, concat_axis=0,
    ).reshape(n_devices)
    # merge the D received sorted runs; sentinel padding sorts to the tail
    sorted_k, sorted_v = jax.lax.sort(
        (rk.reshape(-1), rv.reshape(-1)), num_keys=1, is_stable=True
    )
    n_valid = jnp.sum(rcounts).astype(jnp.int32)
    # overflow indicator: true pre-clamp counts, maxed over destinations
    overflow = jnp.max(counts).astype(jnp.int32)
    return sorted_k, sorted_v, n_valid, overflow


@functools.lru_cache(maxsize=16)
def make_sort_step(
    mesh: Mesh, n_local: int, capacity: int, sample_size: int = 1024
):
    """Build the jitted distributed-sort step for a fixed local size.

    Returns fn(keys, vals) over GLOBAL arrays [D * n_local] sharded on
    the mesh axis, producing per-device sorted runs
    (keys' [D, D*capacity], vals', valid counts [D], max bucket fill [D]).
    """
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(k, v):  # local [n_local]
        sk, sv, n_valid, overflow = _local_sort_step(
            k, v, D, capacity, sample_size
        )
        return sk, sv, n_valid[None], overflow[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec),
        out_specs=(spec, spec, spec, spec),
    )
    return jax.jit(mapped)


class TeraSorter:
    """Host-facing driver for the distributed sort (the sortByKey job).

    ``sort(keys, vals)`` pads to the mesh, runs the SPMD step, re-runs
    with doubled capacity on overflow, and returns globally sorted
    host arrays.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_factor: float = 1.3,
        sample_size: int = 1024,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = len(list(self.mesh.devices.flat))
        self.capacity_factor = capacity_factor
        self.sample_size = sample_size
        self.sharding = NamedSharding(self.mesh, P(EXCHANGE_AXIS))

    def _capacity(self, n_local: int, factor: float) -> int:
        cap = int(math.ceil(n_local / self.n_devices * factor))
        return max(8, (cap + 7) // 8 * 8)  # sublane-friendly

    def sort_device(
        self, keys: jax.Array, vals: jax.Array, capacity: Optional[int] = None
    ):
        """One SPMD sort step on device-resident global arrays whose
        length is a multiple of D.  Returns device results unfetched
        (async) — the jittable hot path."""
        n = keys.shape[0]
        if n % self.n_devices:
            raise ValueError(f"length {n} not divisible by D={self.n_devices}")
        n_local = n // self.n_devices
        cap = capacity or self._capacity(n_local, self.capacity_factor)
        step = make_sort_step(
            self.mesh, n_local, cap, min(self.sample_size, max(1, n_local))
        )
        keys = jax.device_put(keys, self.sharding)
        vals = jax.device_put(vals, self.sharding)
        return step(keys, vals), cap

    def sort(self, keys, vals=None) -> Tuple[np.ndarray, np.ndarray]:
        """Full host-facing sortByKey: returns (sorted_keys, sorted_vals)."""
        keys = np.asarray(keys)
        if vals is None:
            vals = np.zeros_like(keys)
        vals = np.asarray(vals)
        if keys.shape != vals.shape or keys.ndim != 1:
            raise ValueError("keys/vals must be equal-length 1-D arrays")
        n = keys.shape[0]
        if n == 0:
            return keys.copy(), vals.copy()
        # pad to a multiple of D with sentinels that sort last and are
        # trimmed via the valid counts
        sentinel = np.array(np.iinfo(keys.dtype).max, keys.dtype)
        D = self.n_devices
        n_pad = (-n) % D
        if n_pad:
            keys = np.concatenate([keys, np.full(n_pad, sentinel, keys.dtype)])
            vals = np.concatenate([vals, np.zeros(n_pad, vals.dtype)])
        factor = self.capacity_factor
        for _attempt in range(6):
            (sk, sv, n_valid, max_fill), cap = self.sort_device(
                jnp.asarray(keys), jnp.asarray(vals),
                capacity=self._capacity(keys.shape[0] // D, factor),
            )
            if int(jnp.max(max_fill)) <= cap:
                break
            factor *= 2  # skewed keys overflowed a bucket: re-run bigger
        else:
            raise RuntimeError("bucket overflow persisted after 6 retries")
        # stitch: per-device sorted runs, trimmed to their valid counts
        sk_h = np.asarray(sk).reshape(D, -1)
        sv_h = np.asarray(sv).reshape(D, -1)
        nv = np.asarray(n_valid).reshape(-1)
        out_k = np.concatenate([sk_h[d, : nv[d]] for d in range(D)])
        out_v = np.concatenate([sv_h[d, : nv[d]] for d in range(D)])
        # drop host padding sentinels (they sorted into the final run)
        if n_pad:
            out_k, out_v = out_k[:n], out_v[:n]
        return out_k, out_v
