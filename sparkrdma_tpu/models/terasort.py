"""TeraSort on the mesh: the flagship workload.

The reference's headline benchmark is HiBench TeraSort 175 GB — a
``sortByKey`` whose shuffle moves every record once over the NIC
(README.md:7-19).  Here the whole job is ONE jitted SPMD program per
step:

    local sort → quantile sample → splitters → contiguous destination
    windows → all_to_all → merge received sorted runs

Each device sorts its local pairs first (so the sample is an exact local
quantile sketch and destination windows are contiguous — bucketing is
pure sequential gathers, zero scatters), the sample is all-gathered to
derive global equal-frequency splitters, windows are exchanged with a
single ``all_to_all`` riding ICI, and the received runs are merged.
The concatenation of the devices' outputs (trimmed by the true counts)
is the global sort.

Validity is tracked as an explicit 0/1 column ordered as a secondary
sort key, so padding always sorts strictly after real records — real
keys equal to the dtype max are NOT confused with padding.

Skew handling: buckets are capacity-padded (static shapes); true counts
travel with the exchange, and overflow (count > capacity) is detected on
the host, which re-runs with a larger capacity factor — the SPMD analog
of the reference's maxAggBlock fetch cap (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.ops.partition import make_range_splitters
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def _local_sort_step(keys, vals, valid, n_devices, capacity, sample_size):
    """Per-device body (runs under shard_map).  keys/vals: [n_local];
    ``valid`` is int32 0/1 or None (= everything valid, skips the column).

    Invalid (padding) slots sort after every real slot of the same key
    via the secondary sort key, and are excluded from counts.
    """
    n_local = keys.shape[0]
    if n_devices == 1:
        # degenerate mesh: a distributed sort on one device IS the local
        # sort — skip sampling, windowing, the all_to_all, and the merge
        # re-sort entirely (they would re-sort the same data)
        sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
        if valid is None:
            k, v = jax.lax.sort((keys, vals), num_keys=1, is_stable=False)
            n_real = jnp.int32(n_local)
        else:
            inv = jnp.int32(1) - valid
            keys = jnp.where(valid > 0, keys, sentinel)
            k, _, v = jax.lax.sort(
                (keys, inv, vals), num_keys=2, is_stable=False
            )
            n_real = jnp.sum(valid).astype(jnp.int32)
        pad = capacity - n_local
        if pad < 0:
            k, v = k[:capacity], v[:capacity]
        else:
            k = jnp.concatenate([k, jnp.full((pad,), sentinel, k.dtype)])
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        n_valid = jnp.minimum(n_real, jnp.int32(capacity))
        return k, v, n_valid, jnp.int32(n_local)
    if valid is None:
        # fast path: every input slot is real
        k, v = jax.lax.sort((keys, vals), num_keys=1, is_stable=False)
        n_real = jnp.int32(n_local)
    else:
        # force invalid slots onto the dtype-max key, then the
        # (key, invalid) two-key sort puts every invalid slot at the
        # global tail (max-key group, ordered after real max-keyed
        # records within it), so validity per destination window is
        # always a SUFFIX — a per-window valid count replaces a whole
        # per-element column.  The rewrite makes the suffix property
        # hold for ARBITRARY caller-supplied (keys, valid), not just
        # inputs whose invalid slots already carry the sentinel.
        inv = jnp.int32(1) - valid
        keys = jnp.where(
            valid > 0, keys, jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
        )
        k, _, v = jax.lax.sort((keys, inv, vals), num_keys=2, is_stable=False)
        n_real = jnp.sum(valid).astype(jnp.int32)
    # exact local quantiles (k is sorted): positions i*n/S
    sample = k[(jnp.arange(sample_size) * n_local) // sample_size]
    all_samples = jax.lax.all_gather(sample, EXCHANGE_AXIS)  # [D, S]
    splitters = make_range_splitters(all_samples.reshape(-1), n_devices)
    # destination windows: device p gets keys in [splitters[p-1], splitters[p])
    edges = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.searchsorted(k, splitters, side="right").astype(jnp.int32),
        jnp.full((1,), n_local, jnp.int32),
    ])
    counts = edges[1:] - edges[:-1]                       # shipped counts [D]
    starts = edges[:-1]
    # valid records in window [start, end): everything before the global
    # invalid tail at position n_real
    valid_counts = jnp.clip(
        jnp.minimum(edges[1:], n_real) - starts, 0, capacity
    )
    slot = jnp.arange(capacity, dtype=jnp.int32)
    window_valid = slot[None, :] < jnp.minimum(counts, capacity)[:, None]
    sentinel = jnp.array(jnp.iinfo(k.dtype).max, k.dtype)
    # windows are CONTIGUOUS runs of the locally-sorted arrays, so copy
    # them with dynamic_slice (sequential HBM reads) rather than k[idx]
    # fancy indexing — the latter lowers to a general gather, which on
    # TPU costs ~30× the bandwidth-bound copy for these shapes
    kp = jnp.concatenate([k, jnp.full((capacity,), sentinel, k.dtype)])
    vp = jnp.concatenate([v, jnp.zeros((capacity,), v.dtype)])

    def fill(p, bufs):
        fk, fv = bufs
        wk = jax.lax.dynamic_slice(kp, (starts[p],), (capacity,))
        wv = jax.lax.dynamic_slice(vp, (starts[p],), (capacity,))
        fk = jax.lax.dynamic_update_slice(fk, wk[None], (p, 0))
        fv = jax.lax.dynamic_update_slice(fv, wv[None], (p, 0))
        return fk, fv

    # pcast-to-varying: the loop carry must be device-varying like the
    # filled windows, or shard_map rejects the replicated zeros init
    bk0 = jax.lax.pcast(
        jnp.zeros((n_devices, capacity), k.dtype), EXCHANGE_AXIS, to="varying"
    )
    bv0 = jax.lax.pcast(
        jnp.zeros((n_devices, capacity), v.dtype), EXCHANGE_AXIS, to="varying"
    )
    bk, bv = jax.lax.fori_loop(0, n_devices, fill, (bk0, bv0))
    bk = jnp.where(window_valid, bk, sentinel)            # [D, cap]
    bv = jnp.where(window_valid, bv, jnp.zeros((), v.dtype))
    # exchange: device d keeps row d of every source
    rk = jax.lax.all_to_all(bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rv = jax.lax.all_to_all(bv, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rvalid = jax.lax.all_to_all(
        valid_counts.reshape(n_devices, 1), EXCHANGE_AXIS,
        split_axis=0, concat_axis=0,
    ).reshape(n_devices)
    n_valid = jnp.sum(rvalid).astype(jnp.int32)
    # reconstruct per-slot validity from the suffix property, then merge
    # the D received runs with validity as tiebreak so padding (incl.
    # pads whose key equals a real max-valued key) sorts strictly last
    riv = (slot[None, :] >= rvalid[:, None]).astype(jnp.int32).reshape(-1)
    sorted_k, sorted_iv, sorted_v = jax.lax.sort(
        (rk.reshape(-1), riv, rv.reshape(-1)),
        num_keys=2, is_stable=False,
    )
    # overflow indicator: true pre-clamp counts, maxed over destinations
    overflow = jnp.max(counts).astype(jnp.int32)
    return sorted_k, sorted_v, n_valid, overflow


def _local_sort_wide_step(keys, payload, n_devices, capacity,
                          sample_size):
    """Wide-record variant (the HiBench TeraSort shape: 10B key + 90B
    value, README.md:7-19): keys [n_local] ride the sort/sample/window
    machinery with a row INDEX as the carried operand, and the payload
    matrix [n_local, W] follows via two batched row gathers plus the
    same all_to_all — the sort cost is unchanged while every exchanged
    record carries ``8 + 4W`` bytes."""
    n_local = keys.shape[0]
    W = payload.shape[1]
    sentinel = jnp.array(jnp.iinfo(keys.dtype).max, keys.dtype)
    iota = jnp.arange(n_local, dtype=jnp.int32)
    if n_devices == 1:
        k, perm = jax.lax.sort((keys, iota), num_keys=1, is_stable=False)
        p = jnp.take(payload, perm, axis=0)
        pad = capacity - n_local
        if pad < 0:
            k, p = k[:capacity], p[:capacity]
        elif pad:
            k = jnp.concatenate([k, jnp.full((pad,), sentinel, k.dtype)])
            p = jnp.concatenate([p, jnp.zeros((pad, W), p.dtype)], axis=0)
        n_valid = jnp.minimum(jnp.int32(n_local), jnp.int32(capacity))
        return k, p, n_valid, jnp.int32(n_local)
    k, perm = jax.lax.sort((keys, iota), num_keys=1, is_stable=False)
    ps = jnp.take(payload, perm, axis=0)
    sample = k[(jnp.arange(sample_size) * n_local) // sample_size]
    all_samples = jax.lax.all_gather(sample, EXCHANGE_AXIS)
    splitters = make_range_splitters(all_samples.reshape(-1), n_devices)
    edges = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.searchsorted(k, splitters, side="right").astype(jnp.int32),
        jnp.full((1,), n_local, jnp.int32),
    ])
    counts = edges[1:] - edges[:-1]
    starts = edges[:-1]
    clamped = jnp.minimum(counts, capacity)
    slot = jnp.arange(capacity, dtype=jnp.int32)
    window_valid = slot[None, :] < clamped[:, None]
    kp = jnp.concatenate([k, jnp.full((capacity,), sentinel, k.dtype)])
    pp = jnp.concatenate(
        [ps, jnp.zeros((capacity, W), ps.dtype)], axis=0
    )

    def fill(p_, bufs):
        fk, fp = bufs
        wk = jax.lax.dynamic_slice(kp, (starts[p_],), (capacity,))
        wp = jax.lax.dynamic_slice(pp, (starts[p_], 0), (capacity, W))
        fk = jax.lax.dynamic_update_slice(fk, wk[None], (p_, 0))
        fp = jax.lax.dynamic_update_slice(fp, wp[None], (p_, 0, 0))
        return fk, fp

    bk0 = jax.lax.pcast(
        jnp.zeros((n_devices, capacity), k.dtype), EXCHANGE_AXIS,
        to="varying",
    )
    bp0 = jax.lax.pcast(
        jnp.zeros((n_devices, capacity, W), ps.dtype), EXCHANGE_AXIS,
        to="varying",
    )
    bk, bp = jax.lax.fori_loop(0, n_devices, fill, (bk0, bp0))
    bk = jnp.where(window_valid, bk, sentinel)
    rk = jax.lax.all_to_all(bk, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rp = jax.lax.all_to_all(bp, EXCHANGE_AXIS, split_axis=0, concat_axis=0)
    rvalid = jax.lax.all_to_all(
        clamped.reshape(n_devices, 1), EXCHANGE_AXIS,
        split_axis=0, concat_axis=0,
    ).reshape(n_devices)
    n_valid = jnp.sum(rvalid).astype(jnp.int32)
    riv = (slot[None, :] >= rvalid[:, None]).astype(jnp.int32).reshape(-1)
    iota2 = jnp.arange(n_devices * capacity, dtype=jnp.int32)
    sorted_k, _siv, perm2 = jax.lax.sort(
        (rk.reshape(-1), riv, iota2), num_keys=2, is_stable=False
    )
    sorted_p = jnp.take(
        rp.reshape(n_devices * capacity, W), perm2, axis=0
    )
    overflow = jnp.max(counts).astype(jnp.int32)
    return sorted_k, sorted_p, n_valid, overflow


@functools.lru_cache(maxsize=16)
def make_wide_sort_step(mesh: Mesh, n_local: int, payload_words: int,
                        capacity: int, sample_size: int = 1024):
    """Jitted wide-record sort step: fn(keys [D*n_local], payload
    [D*n_local, W]) → (keys' [D, D*cap], payload' [D, D*cap, W],
    valid counts [D], max bucket fill [D])."""
    D = len(list(mesh.devices.flat))
    from jax.sharding import PartitionSpec as P

    spec = P(EXCHANGE_AXIS)
    spec2 = P(EXCHANGE_AXIS, None)

    def body(k, p):
        sk, sp, n_valid, overflow = _local_sort_wide_step(
            k, p, D, capacity, sample_size
        )
        return sk, sp, n_valid[None], overflow[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec2),
        out_specs=(spec, spec2, spec, spec),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=16)
def make_sort_step(
    mesh: Mesh, n_local: int, capacity: int, sample_size: int = 1024,
    with_validity: bool = True,
):
    """Build the jitted distributed-sort step for a fixed local size.

    With ``with_validity`` the step is fn(keys, vals, valid) where
    ``valid`` int32 0/1 marks real records; without, fn(keys, vals)
    treats every slot as real (the no-padding fast path).  Arrays are
    GLOBAL [D * n_local] sharded on the mesh axis; outputs are
    per-device sorted runs
    (keys' [D, D*capacity], vals', valid counts [D], max bucket fill [D]).
    """
    D = len(list(mesh.devices.flat))
    from jax.sharding import PartitionSpec as P

    spec = P(EXCHANGE_AXIS)

    if with_validity:
        def body(k, v, valid):  # local [n_local]
            sk, sv, n_valid, overflow = _local_sort_step(
                k, v, valid, D, capacity, sample_size
            )
            return sk, sv, n_valid[None], overflow[None]

        in_specs = (spec, spec, spec)
    else:
        def body(k, v):  # local [n_local]
            sk, sv, n_valid, overflow = _local_sort_step(
                k, v, None, D, capacity, sample_size
            )
            return sk, sv, n_valid[None], overflow[None]

        in_specs = (spec, spec)

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs,
        out_specs=(spec, spec, spec, spec),
    )
    return jax.jit(mapped)


class TeraSorter(ExchangeModel):
    """Host-facing driver for the distributed sort (the sortByKey job).

    ``sort(keys, vals)`` pads to the mesh, runs the SPMD step, re-runs
    with doubled capacity on overflow, and returns globally sorted
    host arrays.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_factor: float = 1.3,
        sample_size: int = 1024,
    ):
        super().__init__(mesh, capacity_factor)
        self.sample_size = sample_size

    def sort_device(
        self, keys: jax.Array, vals: jax.Array,
        valid: Optional[jax.Array] = None, capacity: Optional[int] = None,
    ):
        """One SPMD sort step on device-resident global arrays whose
        length is a multiple of D.  Returns device results unfetched
        (async) — the jittable hot path."""
        n = keys.shape[0]
        if n % self.n_devices:
            raise ValueError(f"length {n} not divisible by D={self.n_devices}")
        n_local = n // self.n_devices
        cap = capacity or self._capacity(n_local)
        step = make_sort_step(
            self.mesh, n_local, cap, min(self.sample_size, max(1, n_local)),
            with_validity=valid is not None,
        )
        keys = jax.device_put(keys, self.sharding)
        vals = jax.device_put(vals, self.sharding)
        if valid is None:
            return step(keys, vals), cap
        valid = jax.device_put(valid, self.sharding)
        return step(keys, vals, valid), cap

    def sort_device_wide(
        self, keys: jax.Array, payload: jax.Array,
        capacity: Optional[int] = None,
    ):
        """Wide-record sort step (HiBench shape): ``payload`` is
        [n, W] int32 rows that follow their keys through the exchange.
        Length must divide D; returns device results unfetched."""
        n = keys.shape[0]
        if n % self.n_devices:
            raise ValueError(
                f"length {n} not divisible by D={self.n_devices}"
            )
        if payload.ndim != 2 or payload.shape[0] != n:
            raise ValueError(
                f"payload must be [n, W], got {payload.shape}"
            )
        n_local = n // self.n_devices
        cap = capacity or self._capacity(n_local)
        step = make_wide_sort_step(
            self.mesh, n_local, int(payload.shape[1]), cap,
            min(self.sample_size, max(1, n_local)),
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        keys = jax.device_put(keys, self.sharding)
        payload = jax.device_put(
            payload, NamedSharding(self.mesh, P(EXCHANGE_AXIS, None))
        )
        return step(keys, payload), cap

    def sort(self, keys, vals=None) -> Tuple[np.ndarray, np.ndarray]:
        """Full host-facing sortByKey: returns (sorted_keys, sorted_vals)."""
        keys = np.asarray(keys)
        if vals is None:
            vals = np.zeros_like(keys)
        vals = np.asarray(vals)
        if keys.shape != vals.shape or keys.ndim != 1:
            raise ValueError("keys/vals must be equal-length 1-D arrays")
        n = keys.shape[0]
        if n == 0:
            return keys.copy(), vals.copy()
        # pad to a multiple of D on the compile-shape ladder
        # (_base.quantize_padded_length); padding is tracked by the
        # validity column (NOT by key value), so max-valued real keys
        # are safe
        D = self.n_devices
        n_pad = self._padded_length(n) - n
        sentinel = np.array(np.iinfo(keys.dtype).max, keys.dtype)
        if n_pad:
            keys = np.concatenate([keys, np.full(n_pad, sentinel, keys.dtype)])
            vals = np.concatenate([vals, np.zeros(n_pad, vals.dtype)])
            valid = np.ones(n + n_pad, np.int32)
            valid[n:] = 0
            jval = jnp.asarray(valid)
        else:
            jval = None  # fast path: no padding column needed
        jk, jv = jnp.asarray(keys), jnp.asarray(vals)

        def run(cap):
            (sk, sv, n_valid, max_fill), _ = self.sort_device(
                jk, jv, jval, capacity=cap
            )
            return (sk, sv, n_valid), max_fill

        sk, sv, n_valid = self._run_with_overflow_retry(n + n_pad, run)
        # stitch: per-device sorted runs, trimmed to their valid counts
        # (padding always sorts to each run's tail via the validity key)
        sk_h = np.asarray(sk).reshape(D, -1)
        sv_h = np.asarray(sv).reshape(D, -1)
        nv = np.asarray(n_valid).reshape(-1)
        out_k = np.concatenate([sk_h[d, : nv[d]] for d in range(D)])
        out_v = np.concatenate([sv_h[d, : nv[d]] for d in range(D)])
        return out_k, out_v
