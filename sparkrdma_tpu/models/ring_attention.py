"""Sequence-parallel attention: ring and Ulysses (all-to-all) schedules.

The long-context capability SURVEY.md §5 marks as first-class for the
rebuild, in both canonical forms:

- :func:`ring_attention` — sequences sharded over the mesh axis, K/V
  blocks circulating one ``ppermute`` hop per step
  (sparkrdma_tpu.parallel.ring), each chip folding one block into a
  flash-style online-softmax accumulator (running max + denominator).
  Attention over sequence length S costs O(S/D) resident memory per
  chip; every FLOP lands on the MXU as [s_loc, d] × [d, s_blk] matmuls.
  Communication: D-1 neighbor hops of the K/V shard (bandwidth-optimal
  on a ring ICI topology, overlappable with compute).

- :func:`ulysses_attention` — the all-to-all schedule: one
  ``all_to_all`` converts sequence sharding into *head* sharding (each
  chip gets H/D full-length heads), full flash attention runs locally
  per head, and a second ``all_to_all`` restores sequence sharding.
  Communication: 2 all_to_alls of the activations, independent of S in
  round count — the better schedule when H ≥ D and the interconnect
  favors few large collectives (the same trade the reference's grouped
  fetches vs per-block reads make, RdmaShuffleFetcherIterator.scala:214-240).

Both are numerically identical to full softmax attention (the online
rescaling is exact, not an approximation); causal masking uses global
positions derived from each block's source index.

Shapes: q/k/v are [S, d] or [..., S, d] with any leading batch/head
dims; the sequence axis is sharded over the mesh, leading dims are
replicated work per chip (ring) or redistributed (Ulysses).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.attention import NEG_INF, block_attention
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh
from sparkrdma_tpu.parallel.ring import (
    ring_shift,
    supports_pallas_partition_id,
)


@functools.lru_cache(maxsize=16)
def _ring_attention_fn(mesh: Mesh, n_seqs: int, s_local: int, d_head: int,
                       causal: bool, dtype_str: str, impl: Optional[str]):
    D = len(list(mesh.devices.flat))
    spec = P(None, EXCHANGE_AXIS, None)
    # Backends whose SPMD partitioner rejects PartitionId (the CPU
    # backend, when the ring scan keeps axis_index alive into the
    # Pallas offsets) get a DATA-CARRIED device index instead: a tiny
    # iota sharded on the mesh axis rides in as a fourth input and
    # ``idx_[0]`` replaces ``axis_index`` — numerically identical, no
    # PartitionId HLO anywhere in the program.
    native_index = supports_pallas_partition_id()

    def body(q_, k_, v_, *idx_):  # local views: [n_seqs, s_local, d]
        my = jax.lax.axis_index(EXCHANGE_AXIS) if native_index \
            else idx_[0][0]
        scale = 1.0 / np.sqrt(d_head)

        def step(carry, j):
            m, l, o, cur_k, cur_v = carry
            src = (my - j) % D
            # hot op: blockwise flash partials, MXU via the Pallas
            # kernel on TPU backends (ops/attention.py); vmapped over
            # the batch·head axis (pallas_call vmaps to a grid dim)
            m_blk, l_blk, o_blk = jax.vmap(
                lambda qq, kk, vv: block_attention(
                    qq, kk, vv,
                    q_offset=my * s_local, k_offset=src * s_local,
                    causal=causal, scale=scale, impl=impl,
                )
            )(q_, cur_k, cur_v)
            # exact online-softmax fold: rows fully masked in this block
            # carry m_blk = NEG_INF, so beta = 0 kills their partials
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_blk - m_new)
            l_new = l * alpha + l_blk * beta
            o_new = o * alpha[..., None] + o_blk * beta[..., None]
            return (
                m_new, l_new, o_new,
                ring_shift(cur_k), ring_shift(cur_v),
            ), None

        # derive the initial stats from q_ so they carry the same varying
        # mesh-axis type as the loop outputs (shard_map typing rule);
        # accumulate in float32 regardless of input dtype
        q32 = q_.astype(jnp.float32)
        m0 = jnp.full_like(q32[..., 0], NEG_INF)
        l0 = jnp.zeros_like(q32[..., 0])
        o0 = jnp.zeros_like(q32)
        (m, l, o, _, _), _ = jax.lax.scan(
            step, (m0, l0, o0, k_, v_), jnp.arange(D)
        )
        # guard fully-masked rows (l == 0 can only happen with causal=False
        # pathological inputs; causal row 0 always sees itself)
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.astype(q_.dtype)

    # check_vma=False: interpret-mode pallas_call bodies mix varying and
    # replicated values in ways the strict vma checker rejects (JAX
    # suggests this workaround in the error itself); collectives inside
    # are unaffected
    in_specs = (spec, spec, spec) if native_index \
        else (spec, spec, spec, P(EXCHANGE_AXIS))
    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False,
    )
    jitted = jax.jit(mapped)
    if native_index:
        return jitted
    idx = jax.device_put(
        jnp.arange(D, dtype=jnp.int32),
        NamedSharding(mesh, P(EXCHANGE_AXIS)),
    )
    return lambda q3, k3, v3: jitted(q3, k3, v3, idx)


@functools.lru_cache(maxsize=16)
def _ulysses_attention_fn(mesh: Mesh, n_heads: int, s_local: int, d_head: int,
                          causal: bool, dtype_str: str, impl: Optional[str]):
    D = len(list(mesh.devices.flat))
    spec = P(None, EXCHANGE_AXIS, None)
    scale = 1.0 / np.sqrt(d_head)

    def body(q_, k_, v_):  # local views: [H, s_local, d]
        # seq-sharded → head-sharded: split the head axis D ways, send
        # group g to device g, concatenate received chunks along the
        # sequence axis → [H/D, S, d] full-length heads
        def to_heads(x):
            # tiled: divide the head axis by D, multiply the sequence
            # axis by D (tiled=False would *replace* the split axis)
            return jax.lax.all_to_all(
                x, EXCHANGE_AXIS, split_axis=0, concat_axis=1, tiled=True
            )

        qh, kh, vh = to_heads(q_), to_heads(k_), to_heads(v_)
        # full flash attention per local head (the Pallas kernel grids
        # over K blocks with an online-softmax accumulator, so one call
        # IS flash attention over the whole sequence)
        m, l, o = jax.vmap(
            lambda qq, kk, vv: block_attention(
                qq, kk, vv, q_offset=0, k_offset=0,
                causal=causal, scale=scale, impl=impl,
            )
        )(qh, kh, vh)
        out = (o / jnp.maximum(l, 1e-30)[..., None]).astype(q_.dtype)
        # head-sharded → seq-sharded: inverse all_to_all
        return jax.lax.all_to_all(
            out, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=True
        )

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(mapped)


def _canonicalize(q, k, v, D):
    """Flatten leading dims to one batch·head axis: [..., S, d] → [N, S, d]."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError("q, k, v must share a shape")
    if q.ndim < 2:
        raise ValueError(f"need [..., S, d_head], got {q.shape}")
    lead = q.shape[:-2]
    S, d_head = q.shape[-2], q.shape[-1]
    if S % D:
        raise ValueError(f"sequence length {S} not divisible by D={D}")
    q3 = q.reshape((-1, S, d_head))
    k3 = k.reshape((-1, S, d_head))
    v3 = v.reshape((-1, S, d_head))
    return q3, k3, v3, lead, S, d_head


def _dispatch(make_fn, q, k, v, mesh, causal, impl):
    """Shared tail of both schedules: canonicalize, build the cached
    jitted step, shard inputs on the sequence axis, restore shape."""
    mesh = mesh if mesh is not None else make_mesh()
    D = len(list(mesh.devices.flat))
    q3, k3, v3, lead, S, d_head = _canonicalize(q, k, v, D)
    fn = make_fn(
        mesh, q3.shape[0], S // D, d_head, causal, str(q.dtype), impl
    )
    sharding = NamedSharding(mesh, P(None, EXCHANGE_AXIS, None))
    out = fn(*(jax.device_put(x, sharding) for x in (q3, k3, v3)))
    return out.reshape(lead + (S, d_head))


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
    impl: Optional[str] = None,
) -> jax.Array:
    """Exact attention over sequences sharded on the mesh axis, K/V
    circulating the ring.

    q/k/v: [S, d_head] or [..., S, d_head] (leading batch/head dims).
    Returns softmax(q kᵀ / √d) v with the same shape as q.

    ``impl`` selects the per-block kernel: "pallas", "xla", or None =
    auto (pallas on TPU backends).
    """
    return _dispatch(_ring_attention_fn, q, k, v, mesh, causal, impl)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
    impl: Optional[str] = None,
) -> jax.Array:
    """Exact attention via the Ulysses (all-to-all head-parallel)
    schedule: requires a head axis whose size is divisible by D.

    q/k/v: [..., H, S, d_head] (leading batch dims allowed; the axis
    immediately before S is treated as heads).  Returns the same shape.
    """
    mesh_ = mesh if mesh is not None else make_mesh()
    D = len(list(mesh_.devices.flat))
    n_heads = int(np.prod(q.shape[:-2])) if q.ndim > 2 else 1
    if n_heads % D:
        raise ValueError(
            f"batch·head product {n_heads} not divisible by D={D} "
            "(the Ulysses schedule shards heads; use ring_attention "
            "when heads < devices)"
        )
    return _dispatch(_ulysses_attention_fn, q, k, v, mesh_, causal, impl)
