"""Ring attention: sequence-parallel attention over the exchange ring.

The long-context capability SURVEY.md §5 marks as first-class for the
rebuild: sequences sharded over the mesh axis, K/V blocks circulating
one ``ppermute`` hop per step (sparkrdma_tpu.parallel.ring), each chip
folding one block into a flash-style online-softmax accumulator
(running max + denominator), so attention over a sequence of length S
costs O(S/D) resident memory per chip and every FLOP lands on the MXU
as a [s_loc, d] × [d, s_blk] matmul.

Computation is numerically identical to full softmax attention (the
online rescaling is exact, not an approximation); causal masking uses
global positions derived from each block's source index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.attention import NEG_INF, block_attention
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh
from sparkrdma_tpu.parallel.ring import ring_shift


@functools.lru_cache(maxsize=16)
def _ring_attention_fn(mesh: Mesh, s_local: int, d_head: int, causal: bool,
                       dtype_str: str, impl: Optional[str]):
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS, None)

    def body(q_, k_, v_):  # local views: [s_local, d]
        my = jax.lax.axis_index(EXCHANGE_AXIS)
        scale = 1.0 / np.sqrt(d_head)

        def step(carry, j):
            m, l, o, cur_k, cur_v = carry
            src = (my - j) % D
            # hot op: blockwise flash partials, MXU via the Pallas
            # kernel on TPU backends (ops/attention.py)
            m_blk, l_blk, o_blk = block_attention(
                q_, cur_k, cur_v,
                q_offset=my * s_local, k_offset=src * s_local,
                causal=causal, scale=scale, impl=impl,
            )
            # exact online-softmax fold: rows fully masked in this block
            # carry m_blk = NEG_INF, so beta = 0 kills their partials
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_blk - m_new)
            l_new = l * alpha + l_blk * beta
            o_new = o * alpha[:, None] + o_blk * beta[:, None]
            return (
                m_new, l_new, o_new,
                ring_shift(cur_k), ring_shift(cur_v),
            ), None

        # derive the initial stats from q_ so they carry the same varying
        # mesh-axis type as the loop outputs (shard_map typing rule);
        # accumulate in float32 regardless of input dtype
        q32 = q_.astype(jnp.float32)
        m0 = jnp.full_like(q32[:, 0], NEG_INF)
        l0 = jnp.zeros_like(q32[:, 0])
        o0 = jnp.zeros_like(q32)
        (m, l, o, _, _), _ = jax.lax.scan(
            step, (m0, l0, o0, k_, v_), jnp.arange(D)
        )
        # guard fully-masked rows (l == 0 can only happen with causal=False
        # pathological inputs; causal row 0 always sees itself)
        out = o / jnp.maximum(l, 1e-30)[:, None]
        return out.astype(q_.dtype)

    # check_vma=False: interpret-mode pallas_call bodies mix varying and
    # replicated values in ways the strict vma checker rejects (JAX
    # suggests this workaround in the error itself); collectives inside
    # are unaffected
    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(mapped)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
    impl: Optional[str] = None,
) -> jax.Array:
    """Exact attention over sequences sharded on the mesh axis.

    q/k/v: [S, d_head] global arrays (S divisible by D).  Returns
    softmax(q kᵀ / √d) v, computed blockwise over the ring.

    ``impl`` selects the per-block kernel: "pallas", "xla", or None =
    auto (pallas on TPU backends).
    """
    mesh = mesh if mesh is not None else make_mesh()
    D = len(list(mesh.devices.flat))
    S, d_head = q.shape
    if S % D:
        raise ValueError(f"sequence length {S} not divisible by D={D}")
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError("q, k, v must share [S, d_head]")
    fn = _ring_attention_fn(mesh, S // D, d_head, causal, str(q.dtype), impl)
    sharding = NamedSharding(mesh, P(EXCHANGE_AXIS, None))
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return fn(q, k, v)
