"""Ring attention: sequence-parallel attention over the exchange ring.

The long-context capability SURVEY.md §5 marks as first-class for the
rebuild: sequences sharded over the mesh axis, K/V blocks circulating
one ``ppermute`` hop per step (sparkrdma_tpu.parallel.ring), each chip
folding one block into a flash-style online-softmax accumulator
(running max + denominator), so attention over a sequence of length S
costs O(S/D) resident memory per chip and every FLOP lands on the MXU
as a [s_loc, d] × [d, s_blk] matmul.

Computation is numerically identical to full softmax attention (the
online rescaling is exact, not an approximation); causal masking uses
global positions derived from each block's source index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh
from sparkrdma_tpu.parallel.ring import ring_shift

NEG_INF = -1e30


@functools.lru_cache(maxsize=16)
def _ring_attention_fn(mesh: Mesh, s_local: int, d_head: int, causal: bool,
                       dtype_str: str):
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS, None)

    def body(q_, k_, v_):  # local views: [s_local, d]
        my = jax.lax.axis_index(EXCHANGE_AXIS)
        scale = 1.0 / np.sqrt(d_head)
        q_pos = my * s_local + jnp.arange(s_local)  # global query positions

        def step(carry, j):
            m, l, o, cur_k, cur_v = carry
            src = (my - j) % D
            # scores on the MXU: [s_local, s_local]
            s = (q_ @ cur_k.T) * scale
            if causal:
                k_pos = src * s_local + jnp.arange(s_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask, s, NEG_INF)
            # online softmax: rescale running stats by the new max
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_new = l * alpha + p.sum(axis=-1)
            o_new = o * alpha[:, None] + p @ cur_v
            return (
                m_new, l_new, o_new,
                ring_shift(cur_k), ring_shift(cur_v),
            ), None

        # derive the initial stats from q_ so they carry the same varying
        # mesh-axis type as the loop outputs (shard_map typing rule)
        m0 = jnp.full_like(q_[:, 0], NEG_INF)
        l0 = jnp.zeros_like(q_[:, 0])
        o0 = jnp.zeros_like(q_)
        (m, l, o, _, _), _ = jax.lax.scan(
            step, (m0, l0, o0, k_, v_), jnp.arange(D)
        )
        # guard fully-masked rows (l == 0 can only happen with causal=False
        # pathological inputs; causal row 0 always sees itself)
        return o / jnp.maximum(l, 1e-30)[:, None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return jax.jit(mapped)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over sequences sharded on the mesh axis.

    q/k/v: [S, d_head] global arrays (S divisible by D).  Returns
    softmax(q kᵀ / √d) v, computed blockwise over the ring.
    """
    mesh = mesh if mesh is not None else make_mesh()
    D = len(list(mesh.devices.flat))
    S, d_head = q.shape
    if S % D:
        raise ValueError(f"sequence length {S} not divisible by D={D}")
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError("q, k, v must share [S, d_head]")
    fn = _ring_attention_fn(mesh, S // D, d_head, causal, str(q.dtype))
    sharding = NamedSharding(mesh, P(EXCHANGE_AXIS, None))
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return fn(q, k, v)
