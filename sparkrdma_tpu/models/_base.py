"""Shared host-driver machinery for the SPMD model pipelines.

Capacity sizing and the overflow-retry loop are policy, shared by every
capacity-bucketed exchange model (sort, count, …): buckets are padded to
a static capacity; true counts travel with the exchange; if any bucket's
true count exceeded capacity the host re-runs the step with doubled
capacity (the SPMD inversion of the reference's maxAggBlock fetch cap,
SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh

MAX_OVERFLOW_RETRIES = 6


def quantize_padded_length(n: int, d: int) -> int:
    """Smallest padded length ≥ n that is a multiple of ``d`` and sits
    on a 16-steps-per-octave ladder (≤12.5% padding, worst case just
    past an octave boundary where the step is 1/8 of n).

    The SPMD steps compile per (n_local, capacity) shape, so feeding
    exact input sizes compiles a fresh XLA program for every distinct
    job size (20-40s per novel shape on a real chip).  Quantizing the
    padded length collapses arbitrary sizes onto ~16 shapes per octave;
    padding rides the existing validity column.  Inputs already on the
    ladder (e.g. power-of-two benches) pad nothing and keep the
    validity-free fast path.
    """
    if n <= 0:
        return n
    if n <= 16:
        m = n
    else:
        k = (n - 1).bit_length()
        step = 1 << max(0, k - 4)
        m = (n + step - 1) // step * step
    return (m + d - 1) // d * d


def check_no_silent_truncation(**columns) -> None:
    """Reject int64 columns when jax_enable_x64 is off: jnp.asarray
    would silently truncate them to int32, colliding keys or corrupting
    values with no error.  Shared by every keyed model (aggregations
    AND joins)."""
    for name, col in columns.items():
        if np.asarray(col).dtype == np.int64 and not jax.config.jax_enable_x64:
            raise ValueError(
                f"int64 {name} require jax_enable_x64 (without it JAX "
                "silently truncates to int32 — colliding keys / "
                "corrupting values)"
            )


class ExchangeModel:
    """Base for host-facing drivers of capacity-bucketed SPMD steps."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 capacity_factor: float = 1.3,
                 quantize_shapes: bool = True):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = len(list(self.mesh.devices.flat))
        self.capacity_factor = capacity_factor
        # quantize padded lengths onto the compile-shape ladder
        # (quantize_padded_length); opt out for exact-shape control
        self.quantize_shapes = quantize_shapes
        self.sharding = NamedSharding(self.mesh, P(EXCHANGE_AXIS))

    def _padded_length(self, n: int) -> int:
        """Padded total length for an n-row input: multiple of D, on
        the compile-shape ladder when ``quantize_shapes``."""
        if self.quantize_shapes:
            return quantize_padded_length(n, self.n_devices)
        return n + ((-n) % self.n_devices)

    def _capacity(self, n_local: int, factor: Optional[float] = None) -> int:
        """Per-bucket capacity: n_local/D scaled by the skew factor,
        rounded up to a sublane-friendly multiple of 8."""
        factor = self.capacity_factor if factor is None else factor
        cap = int(math.ceil(n_local / self.n_devices * factor))
        return max(8, (cap + 7) // 8 * 8)

    def _retry_with_factor(self, run: Callable[[float], Tuple]):
        """Call ``run(factor)`` → (outputs, overflowed: bool); re-run
        with doubled skew factor while any bucket overflowed.  The
        general form for models with more than one capacity (e.g. the
        two-sided join)."""
        factor = self.capacity_factor
        for _attempt in range(MAX_OVERFLOW_RETRIES):
            outputs, overflowed = run(factor)
            if not overflowed:
                return outputs
            factor *= 2  # key skew overflowed a bucket: retry bigger
        raise RuntimeError(
            f"bucket overflow persisted after {MAX_OVERFLOW_RETRIES} retries"
        )

    def _run_with_overflow_retry(
        self, n_total: int, run: Callable[[int], Tuple]
    ):
        """Call ``run(capacity)`` → (outputs, max_fill); re-run with
        doubled factor while any bucket overflowed."""

        def attempt(factor: float):
            cap = self._capacity(n_total // self.n_devices, factor)
            outputs, max_fill = run(cap)
            return outputs, int(np.max(np.asarray(max_fill))) > cap

        return self._retry_with_factor(attempt)

    def _run_padded_keyed(self, keys, vals, make_step):
        """Shared host driver for keyed-exchange models (wordcount,
        aggregate): pad columns to a multiple of D with a validity
        column, place them on the mesh ONCE, run
        ``make_step(mesh, n_local, capacity)`` under the overflow-retry
        policy, and hand back per-device host rows.

        The step must return ``(*row_arrays, n_unique[1], max_fill[1])``
        per device.  Returns ``(rows, nu)``: each of ``rows`` reshaped
        to [D, -1] on the host, ``nu`` the int32[D] valid-row counts.
        """
        import jax.numpy as jnp

        keys = np.asarray(keys)
        vals = np.asarray(vals)
        if keys.shape != vals.shape or keys.ndim != 1:
            raise ValueError("keys/vals must be equal-length 1-D arrays")
        check_no_silent_truncation(keys=keys, vals=vals)
        n = keys.shape[0]
        if n == 0:
            return None, None
        D = self.n_devices
        n_pad = self._padded_length(n) - n
        valid = np.ones(n + n_pad, np.int32)
        if n_pad:
            keys = np.concatenate([keys, np.zeros(n_pad, keys.dtype)])
            vals = np.concatenate([vals, np.zeros(n_pad, vals.dtype)])
            valid[n:] = 0
        # D == 1 with no padding: every slot is real, so the step can
        # drop the validity operand from its sort (the sort is the
        # step's whole cost on one chip)
        fast = D == 1 and n_pad == 0
        cols = (keys, vals) if fast else (keys, vals, valid)
        # place once: only the capacity changes between overflow retries
        placed = tuple(
            jax.device_put(jnp.asarray(x), self.sharding) for x in cols
        )

        def run(cap):
            step = make_step(
                self.mesh, (n + n_pad) // D, cap,
                with_validity=not fast,
            )
            *rows, n_unique, max_fill = step(*placed)
            return (rows, n_unique), max_fill

        rows, n_unique = self._run_with_overflow_retry(n + n_pad, run)
        host_rows = [np.asarray(r).reshape(D, -1) for r in rows]
        nu = np.asarray(n_unique).reshape(-1)
        return host_rows, nu
