"""Fused broadcast-join + aggregation: one sort where q64/q72 plans more.

TPC-DS q64/q72 physical plans end in ``fact ⋈ dim → aggregate``.  Run
naively that is two full-length sorts back to back — the broadcast
probe's (key, role) sort and the aggregation's group-key sort (the
read-path combine of RdmaShuffleReader.scala:82-97) — and the sorts are
where the time goes on TPU (join.py module docs).

Whenever the aggregation's group key is a pure function of the JOIN key
(group by the join key itself, its bucket, a date part, ... — the
common star-schema shape), the two groupings are compatible: sorting
the packed stream by ``(group_key, join_key, role)`` groups equal join
keys contiguously *inside* contiguous group-key runs.  ONE sort then
serves both stages:

  sort (gk, key, role, payload)          # 4 operands, 3 sort keys
  → log-step forward fill of dim rows    # the join probe (join.py)
  → per-gk-run sum/count via global cumsum + run-end diffs
  → per-gk-run min/max via log-step segmented scans (ops/segment.py)

versus the unfused ``make_broadcast_join_step`` + ``make_aggregate_step``
pair's two 3-operand sorts.  Outputs use the same run-end layout as
``aggregate_by_key_local`` (extract where ``counts > 0``).

Multi-device: each device aggregates its local packed shard; a group
key can surface on several devices, so per-device rows are PARTIAL
aggregates — the host wrapper merges them (sum/count add, min/max
combine), the same final-merge contract as Spark's two-phase
aggregation.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.models._base import ExchangeModel
from sparkrdma_tpu.models.aggregate import KeyStats
from sparkrdma_tpu.models.join import (
    _ROLE_INVALID,
    _as_columns,
    _pack_sides,
    _pad_to,
    _probe_fill,
)
from sparkrdma_tpu.ops.segment import (
    _ff_run_carry,
    _prev_end,
    segmented_scan,
)
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS

# group-key / aggregation-value hooks both receive UNSIGNED transport
# columns (join.py _pay_u views); agg_val_fn picks the output dtype and
# min/max identities follow it
GroupKeyFn = Callable[[jax.Array], jax.Array]
AggValFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


def _minmax_identities(dtype):
    dt = np.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(jnp.inf, dt), jnp.array(-jnp.inf, dt)
    return jnp.array(jnp.iinfo(dt).max, dt), jnp.array(jnp.iinfo(dt).min, dt)


@functools.lru_cache(maxsize=16)
def make_broadcast_join_aggregate_step(
    mesh: Mesh,
    n_left: int,
    n_right_total: int,
    group_key_fn: GroupKeyFn,
    agg_val_fn: AggValFn,
):
    """Jitted fused step: fact side sharded [D*n_left], dimension side
    replicated; returns per-device run-end partial aggregates
    ``(gk, sums, counts, mins, maxs, n_groups)``.

    ``group_key_fn(key_u)`` must depend ONLY on the join key (that is
    the fusion precondition); ``agg_val_fn(key_u, fact_pay_u,
    dim_val_u)`` builds the aggregated value per matched fact row.

    Both hooks key the compile cache BY IDENTITY: pass module-level
    functions (or hold a reference), not fresh per-call lambdas — a
    new lambda each call re-traces and re-jits the whole step.
    """
    spec = P(EXCHANGE_AXIS)

    def body(lk, lv, l_valid, rk, rv, r_valid):
        ku, role, pay = _pack_sides(lk, lv, l_valid, rk, rv, r_valid)
        gk = group_key_fn(ku).astype(ku.dtype)
        # invalid rows ride a sentinel group so they sort to the global
        # tail and can never delimit or join a real group's run
        gmax = jnp.array(jnp.iinfo(gk.dtype).max, gk.dtype)
        gk = jnp.where(role != _ROLE_INVALID, gk, gmax)
        sgk, sk, srole, spay = jax.lax.sort(
            (gk, ku, role, pay), num_keys=3, is_stable=False
        )
        dim_val, found = _probe_fill(sk, srole, spay)
        v = agg_val_fn(sk, spay, dim_val)
        id_min, id_max = _minmax_identities(v.dtype)
        mi = found.astype(jnp.int32)
        vz = jnp.where(found, v, jnp.zeros((), v.dtype))
        # group-run boundaries on the group key alone
        is_last = jnp.concatenate([sgk[1:] != sgk[:-1], jnp.ones(1, bool)])
        heads = jnp.concatenate([jnp.ones(1, bool), sgk[1:] != sgk[:-1]])
        from sparkrdma_tpu.ops.scan_kernels import cumsum_1d

        csum_v = cumsum_1d(vz)
        csum_m = cumsum_1d(mi)
        flag, (fv, fm) = _ff_run_carry(is_last, (csum_v, csum_m))
        prev_v, prev_m = _prev_end(flag, (fv, fm))
        counts = jnp.where(is_last, csum_m - prev_m, 0).astype(jnp.int32)
        # the sentinel-group tail never counts: found is 0 there
        real = counts > 0
        counts = jnp.where(real, counts, 0)
        sums = jnp.where(real, csum_v - prev_v, 0).astype(v.dtype)
        mins = segmented_scan(
            jnp.where(found, v, id_min), heads, jnp.minimum, id_min
        )
        maxs = segmented_scan(
            jnp.where(found, v, id_max), heads, jnp.maximum, id_max
        )
        mins = jnp.where(real, mins, 0).astype(v.dtype)
        maxs = jnp.where(real, maxs, 0).astype(v.dtype)
        out_gk = jnp.where(real, sgk, gmax)
        n_groups = jnp.sum(real.astype(jnp.int32))
        return out_gk, sums, counts, mins, maxs, n_groups[None]

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(None), P(None), P(None)),
        out_specs=(spec,) * 6,
    )
    return jax.jit(mapped)


class BroadcastJoinAggregator(ExchangeModel):
    """Host-facing fused ``fact ⋈ dim → aggregateByKey`` for group keys
    derived from the join key.  Returns ``{group_key: KeyStats}`` over
    matched fact rows (inner-join semantics: unmatched facts aggregate
    nowhere)."""

    def join_aggregate(
        self,
        fact_keys,
        fact_vals,
        dim_keys,
        dim_vals,
        group_key_fn: Optional[GroupKeyFn] = None,
        agg_val_fn: Optional[AggValFn] = None,
    ) -> Dict[int, KeyStats]:
        if group_key_fn is None:
            group_key_fn = _identity_group_key
        if agg_val_fn is None:
            agg_val_fn = _dim_value_agg
        lk, lv = _as_columns(fact_keys, fact_vals)
        rk, rv = _as_columns(dim_keys, dim_vals)
        D = self.n_devices
        lk, lv, l_valid, nl = _pad_to(lk, lv, D, self.quantize_shapes)
        r_valid = jnp.ones(rk.shape[0], jnp.int32)
        step = make_broadcast_join_aggregate_step(
            self.mesh, nl // D, rk.shape[0], group_key_fn, agg_val_fn
        )
        rep = NamedSharding(self.mesh, P(None))
        gk, sums, counts, mins, maxs, _n = step(
            jax.device_put(lk, self.sharding),
            jax.device_put(lv, self.sharding),
            jax.device_put(l_valid, self.sharding),
            jax.device_put(jnp.asarray(rk), rep),
            jax.device_put(jnp.asarray(rv), rep),
            jax.device_put(r_valid, rep),
        )
        # merge per-device PARTIAL rows (two-phase aggregation's final
        # combine): sums/counts add, mins/maxs combine.  Group keys are
        # computed in the unsigned transport domain; report them in the
        # join-key dtype's domain (same-width signed reinterpretation,
        # the _mask_output contract) so negative join keys round-trip
        gk_h = np.asarray(gk)
        signed = np.dtype(f"i{gk_h.dtype.itemsize}")
        gk_h = gk_h.view(signed).astype(np.dtype(lk.dtype), copy=False)
        sums_h, counts_h = np.asarray(sums), np.asarray(counts)
        mins_h, maxs_h = np.asarray(mins), np.asarray(maxs)
        # preserve the aggregate dtype: agg_val_fn may return floats
        # (the +/-inf min/max identities support them) — int() here
        # would silently truncate
        def conv_for(a):
            return float if np.issubdtype(a.dtype, np.floating) else int

        c_sum, c_min, c_max = (
            conv_for(sums_h), conv_for(mins_h), conv_for(maxs_h)
        )
        out: Dict[int, KeyStats] = {}
        (idx,) = (counts_h > 0).nonzero()
        for i in idx:
            key = int(gk_h[i])
            prev = out.get(key)
            if prev is None:
                out[key] = KeyStats(
                    c_sum(sums_h[i]), int(counts_h[i]),
                    c_min(mins_h[i]), c_max(maxs_h[i]),
                )
            else:
                out[key] = KeyStats(
                    prev.sum + c_sum(sums_h[i]),
                    prev.count + int(counts_h[i]),
                    min(prev.min, c_min(mins_h[i])),
                    max(prev.max, c_max(maxs_h[i])),
                )
        return out


def _identity_group_key(key_u):
    return key_u


def _dim_value_agg(key_u, fact_pay_u, dim_val_u):
    # default: aggregate the joined dimension value, reinterpreted as
    # the signed width (int32/int64 transport parity)
    it = jnp.int64 if dim_val_u.dtype.itemsize == 8 else jnp.int32
    return jax.lax.bitcast_convert_type(dim_val_u, it)
